"""Continuous batching: many requests share one fixed device batch.

A serving engine cannot wait for a whole batch to finish: requests
arrive at different times with different prompt and output lengths. This
engine keeps a **fixed-shape** slot batch on device — (n_slots,
max_len) KV cache — and multiplexes requests onto it:

  - a free slot is filled by prefilling one request's prompt into a
    single-sequence mini-cache and scattering it into the slot (two
    jitted programs; prompt lengths bucket to powers of two to bound
    recompiles);
  - every tick runs ONE jitted decode step over all slots; inactive
    slots compute garbage that is masked on host and their cache
    lengths are frozen, so shapes never change;
  - a request leaves its slot on EOS or at its max_new budget, and the
    slot is immediately refillable — no head-of-line blocking.

This is the TPU analogue of GPU continuous batching: instead of paging,
the cache is a dense per-slot ring the scheduler rolls back by writing
`lengths` (kvcache.py's write-at-own-length contract makes stale slots
self-healing). The per-tick host sync is one (n_slots,) int32 fetch.

Greedy output for any request is exactly what the single-request Engine
produces — the scheduling is invisible to the math (tested).

The reference repo for this project is empty (SURVEY.md §0); there is no
upstream serving engine to cite.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.cache import PoolExhausted
from shellac_tpu.inference.kvcache import (
    PagedKVCache,
    QuantPagedKVCache,
    kv_field_names,
    scatter_slot,
    slot_view,
)
from shellac_tpu.inference.qos import WeightedFairQueue
from shellac_tpu.models import transformer
from shellac_tpu.obs import EngineMetrics, get_registry
from shellac_tpu.ops.sampling import NEG_INF, sample_batched
from shellac_tpu.parallel.sharding import make_shardings


@dataclass
class _Request:
    rid: Any
    tokens: np.ndarray  # (S,) int32 prompt
    max_new: int
    stop: Optional[List[List[int]]] = None  # token-id stop sequences
    # Per-request sampling settings, resolved to concrete values at
    # submit time (top_k is always >= 1; vocab size = disabled).
    temperature: float = 0.0
    top_k: int = 1
    top_p: float = 1.0
    min_p: float = 0.0
    # EOS is banned from sampling until this many tokens are emitted
    # (0 = off; stop sequences still end generation regardless).
    min_tokens: int = 0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    prompt_logprobs: bool = False
    plp: Optional[List[float]] = None
    # Per emitted token, the engine's top-K alternatives as
    # ([ids], [logprobs]) pairs (engines built with top_logprobs > 0).
    tlp: Optional[List] = None
    seed: Optional[int] = None
    # Disaggregated serving: run the prompt, sample the first token,
    # then FREEZE the slot instead of decoding — the KV-migration
    # exporter (inference/disagg.py) ships the slot to a decode
    # replica and releases it. Frozen slots never join decode windows.
    prefill_only: bool = False
    # Additive per-token logit biases applied before sampling (OpenAI
    # semantics); logprobs still report the raw distribution.
    logit_bias: Optional[Dict[int, float]] = None
    # Structured decoding: a compiled constraints.TokenDFA whose
    # transition table masks the logits each step (None = free).
    constraint: Optional[Any] = None
    # Observability span (obs.RequestTrace) riding the request through
    # the pipeline; the engine marks prefill-start and first-token on
    # it. None when the caller doesn't trace (offline batch runs).
    trace: Optional[Any] = None
    # Generated tokens so far. INVARIANT (the server's streaming path
    # reads this between engine steps): `out` only ever grows, except
    # that a stop-sequence match removes exactly the matched suffix
    # (<= the longest stop length) once, at completion. Streaming holds
    # back that many tokens so an emitted token can never be retracted.
    out: List[int] = field(default_factory=list)
    # Logprob of each emitted token under the raw (unfiltered,
    # untempered) model distribution — same convention as the
    # single-request Engine. Populated only when the engine was built
    # with logprobs=True; kept in lockstep with `out`.
    lps: List[float] = field(default_factory=list)
    # Multi-tenant QoS: owning tenant id (None = untagged), priority
    # class (inference/qos.py PRIORITY_CLASSES; lower = better) and
    # DRR weight steering the weighted-fair pending queue, and the
    # monotonic enqueue time the preemption driver reads wait ages
    # from.
    tenant: Optional[str] = None
    qos_class: int = 1
    qos_weight: float = 4.0
    t_queued: float = 0.0
    # Preempt-and-park: True while this mid-decode request is frozen
    # in its slot awaiting export (frozen_decodes). Frozen slots never
    # join decode windows and never settle through _finish_check —
    # they leave through export_slot -> release_frozen, exactly like
    # prefill_only freezes.
    frozen: bool = False

    def hit_stop(self) -> Optional[int]:
        """Length of the matched stop suffix of `out`, or None."""
        for seq in self.stop or ():
            n = len(seq)
            if n and len(self.out) >= n and self.out[-n:] == seq:
                return n
        return None


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class _PrefillFlight:
    """Host-side record of ONE dispatched (not yet settled) prefill.

    `arrays` are the prefill program's device outputs — (first token,
    its raw logprob, the top-K alternatives or None, and the
    prompt-logprob payload or None) — held as futures: nothing is
    synced at dispatch. `req` captures which request owned the slot AT
    DISPATCH so settlement can discard results for slots whose request
    was cancelled/replaced while the prefill was in flight (identity
    check, the same arbitration _DecodeWindow settlement uses). The
    prompt-logprob payload is either the whole-prompt (pad,) score
    array or the chunked path's list of (in-chunk scores, size,
    boundary score) pieces — both ride the ONE batched settle pull."""

    __slots__ = ("slot", "req", "arrays", "t_dispatch")

    def __init__(self, slot, req, arrays):
        self.slot = slot
        self.req = req
        self.arrays = arrays  # (first, lp, tl, plp) futures
        self.t_dispatch = time.perf_counter()


class _DecodeWindow:
    """Host-side record of ONE dispatched (not yet synced) decode
    window.

    `arrays` are the window's device outputs (dispatched async — jax
    returns futures immediately); `pairs` captures which request owned
    each active slot AT DISPATCH, so settlement can discard results for
    slots whose request was cancelled/replaced while the window was in
    flight (identity check, the same arbitration the supervisor uses
    for stale generations). `ticks` is the window's decode_ticks at
    dispatch (the auto-tuner may retune between windows)."""

    __slots__ = ("pairs", "ticks", "arrays", "t_dispatch")

    def __init__(self, pairs, ticks, arrays):
        self.pairs = pairs      # [(slot, _Request)] active at dispatch
        self.ticks = ticks
        self.arrays = arrays    # (toks, lps, tlvs, tlis, acts) futures
        self.t_dispatch = time.perf_counter()


class BatchingEngine:
    """Fixed-slot continuous batching over one model.

    Storage policy is delegated to a cache backend
    (inference/cache): the engine holds the decode ALGORITHM — slot
    scheduling, the jitted window programs, sampling state — and asks
    `self.cache_backend` for construction, sharding axes, slot
    residency, and capacity accounting. `cache_backend` accepts a
    registry name ("dense", "dense-int8", "rolling", "rolling-int8";
    the paged subclass takes "paged"/"paged-int8") or a constructed
    CacheBackend; the legacy kv_quant / rolling_window kwargs remain
    as aliases that resolve through the same registry.
    """

    # Backend families this engine class can drive (the paged subclass
    # overrides — its jitted programs scatter through block tables).
    _backend_family = ("dense", "dense-int8", "rolling", "rolling-int8")
    # Can this engine score prompts (prompt_logprobs)? Subclasses whose
    # prefill skips scoring forwards (speculative drafts) set False.
    _scores_prompts = True
    # Extra per-slot residency past prompt + max_new + 1 the engine's
    # window may write (the speculative mixin sets gamma + 1: a verify
    # round writes g+1 positions before rolling back).
    _footprint_slack = 0
    # Can decode_ticks be retuned post-construction? The speculative
    # engine pins it to 1 (a verify round already emits up to gamma+1
    # tokens per sync) and sets this False so the auto-tuner skips it.
    _decode_ticks_tunable = True

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        n_slots: int = 8,
        max_len: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
        attn_impl: str = "auto",
        decode_ticks="auto",
        overlap_decode: bool = False,
        overlap_prefill: bool = False,
        max_prefills_per_step: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        logprobs: bool = False,
        top_logprobs: int = 0,
        mesh=None,
        kv_quant: Optional[str] = None,
        rolling_window: bool = False,
        pp_pipeline: bool = False,
        cache_backend=None,
        registry=None,
    ):
        top_logprobs = int(top_logprobs or 0)
        if top_logprobs < 0 or top_logprobs > 32:
            raise ValueError(
                f"top_logprobs={top_logprobs}: must be in [0, 32]"
            )
        if top_logprobs and not logprobs:
            raise ValueError(
                "top_logprobs needs logprobs=True (the alternatives "
                "ride the same scoring pass)"
            )
        # decode_ticks: K decode steps per host sync, or "auto" — the
        # serving entry points run inference.autotune against the live
        # mesh at startup and write the winner back; until tuned,
        # "auto" behaves exactly like 1 (bit-identical), so library
        # construction stays cheap and deterministic.
        self.decode_ticks_requested = decode_ticks
        if decode_ticks == "auto":
            decode_ticks = 1
        elif isinstance(decode_ticks, str):
            raise ValueError(
                f"decode_ticks={decode_ticks!r}: need an int >= 1 or "
                "'auto'"
            )
        if decode_ticks < 1:
            raise ValueError(f"decode_ticks must be >= 1, got {decode_ticks}")
        if max_prefills_per_step is not None and max_prefills_per_step < 1:
            raise ValueError("max_prefills_per_step must be >= 1")
        # prefill_chunk: chunk size, None (whole prompts), or "auto" —
        # the serving entry points sweep candidates on the live engine
        # (inference.autotune.autotune_prefill_chunk) and write the
        # winner back; until tuned, "auto" behaves exactly like None.
        self.prefill_chunk_requested = prefill_chunk
        if prefill_chunk == "auto":
            prefill_chunk = None
        elif isinstance(prefill_chunk, str):
            raise ValueError(
                f"prefill_chunk={prefill_chunk!r}: need an int >= 1, "
                "None, or 'auto'"
            )
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.cfg = cfg
        self.params = params
        self.n_slots = n_slots
        self.max_len = max_len or cfg.max_seq_len
        self.eos_id = eos_id
        self.attn_impl = attn_impl
        # With a mesh the engine runs sharded, same contract as the
        # single-request Engine: params already placed (shard_params),
        # KV cache sharded over kv_heads, slot batch replicated (the
        # scheduler owns it). Shardings are pinned at the jit
        # boundaries so GSPMD keeps one layout across every program.
        self.mesh = mesh
        # Storage policy: resolve the cache backend (registry name,
        # constructed instance, or the legacy kv_quant/rolling_window
        # aliases — one resolution path, shared with the CLI).
        from shellac_tpu.inference.cache import (
            CacheBackend,
            make_backend,
            resolve_backend_name,
        )

        # Chunked-prefill continuations READ the ring before their own
        # rows age out; the ring carries that chunk as slack.
        self._chunk_slack = prefill_chunk or 1
        wants_paged = any(n.startswith("paged")
                          for n in self._backend_family)
        if isinstance(cache_backend, CacheBackend):
            # A constructed instance carries its own policy + geometry;
            # engine kwargs that contradict it must refuse as loudly as
            # the name path does — silently dropped knobs are exactly
            # the capacity incidents the registry exists to prevent.
            if kv_quant is not None and kv_quant != cache_backend.kv_quant:
                raise ValueError(
                    f"kv_quant={kv_quant!r} conflicts with the "
                    f"{cache_backend.name!r} backend instance"
                )
            if rolling_window and not cache_backend.is_rolling:
                raise ValueError(
                    f"rolling_window={rolling_window!r} conflicts with "
                    f"the {cache_backend.name!r} backend instance"
                )
            if (cache_backend.n_slots != n_slots
                    or cache_backend.max_len != self.max_len):
                raise ValueError(
                    f"{cache_backend.name!r} backend instance geometry "
                    f"(n_slots={cache_backend.n_slots}, "
                    f"max_len={cache_backend.max_len}) does not match "
                    f"the engine (n_slots={n_slots}, "
                    f"max_len={self.max_len})"
                )
            backend = cache_backend
        else:
            name = resolve_backend_name(
                cache_backend, kv_quant=kv_quant,
                rolling_window=rolling_window,
            )
            if name not in self._backend_family:
                raise ValueError(
                    f"{type(self).__name__} drives cache backends "
                    f"{self._backend_family}; {name!r} needs a "
                    "different engine class — resolve it through "
                    "inference.cache.engine_class"
                )
            backend = make_backend(
                name, cfg, n_slots, self.max_len,
                chunk_slack=self._chunk_slack,
            )
        if backend.is_paged != wants_paged:
            raise ValueError(
                f"{type(self).__name__} cannot drive the "
                f"{backend.name!r} backend (paged={backend.is_paged})"
            )
        backend.bind(self)
        self.cache_backend = backend
        # Legacy attributes, derived from the backend — the jitted
        # programs and external callers keep reading them.
        self.kv_quant = backend.kv_quant
        self.rolling_window = backend.is_rolling
        # Token-level pipelined decode on pp meshes: slots split into
        # pp staggered groups so every pipeline stage computes a
        # different group each microtick instead of idling pp-1 of the
        # time (inference/pp_pipeline.py). Bit-exact per slot; greedy
        # parity is tested against the unpipelined engine.
        self.pp_pipeline = bool(pp_pipeline)
        self._pp = 0
        if self.pp_pipeline:
            from shellac_tpu.inference.pp_pipeline import (
                validate_pp_pipeline,
            )

            self._pp = validate_pp_pipeline(
                cfg, mesh, n_slots, self.kv_quant, self.rolling_window,
                self.cache_backend.is_paged,
            )
        self.decode_ticks = decode_ticks
        # Overlapped dispatch: with overlap_decode=True, step() keeps a
        # two-deep window pipeline — the NEXT decode window is
        # dispatched (async) before the previous one's host sync is
        # paid, so the device computes window k+1 while the host
        # settles window k's requests and runs admissions. Requests
        # admitted during a step join at the NEXT window boundary, and
        # per-request outputs stay token-identical to the strict
        # ordering (greedy and per-request-seeded sampling; the shared
        # unseeded stream draws in a different order, like any
        # scheduling change). False = strict ordering, bit-identical to
        # the pre-overlap engine.
        self.overlap_decode = bool(overlap_decode)
        # Dispatched-but-unsynced decode windows, oldest first. Depth
        # is bounded at 2 by step()'s structure (pre-dispatch exactly
        # one window before settling exactly one).
        self._windows: deque[_DecodeWindow] = deque()
        # Overlapped prefill dispatch: with overlap_prefill=True, an
        # admission dispatches its prefill program and returns — the
        # slot is marked prefill-pending (excluded from decode windows
        # until settled), the host immediately admits the next request
        # or dispatches the next decode window, and every in-flight
        # prefill settles in ONE batched device_get at the next step
        # boundary (first tokens, logprobs, top-K, and the opt-in
        # prompt-logprob payload all ride the same pull; TTFT is
        # recorded at settle). False = each prefill settles inside its
        # own admission, bit-identical to the pre-overlap engine.
        self.overlap_prefill = bool(overlap_prefill)
        # Dispatched-but-unsettled prefills, oldest first.
        self._pflights: List[_PrefillFlight] = []
        # Test/bench seam, the prefill-side twin of _window_hooks:
        # None, or an object with on_prefill_dispatch(flight) /
        # before_prefill_sync(flights).
        self._prefill_hooks = None
        # Test/bench seam (inference.autotune.SimulatedHostLatency):
        # None, or an object with on_dispatch(window) / before_sync
        # (window) — a sleep-injecting RPC shim that lets CPU CI
        # reproduce the relay-bound regime BENCH_DECODE measured.
        self._window_hooks = None
        # Wall-clock the current step() spent blocked in decode-window
        # syncs (read back out as the host-overhead histogram).
        self._sync_block_s = 0.0
        # Per-step phase attribution accumulators (obs.STEP_PHASES):
        # reset by step(), written by the fill/prefill/settle helpers,
        # observed into shellac_step_phase_seconds at step end.
        self._phase_s: Dict[str, float] = {}
        # Cap prefills per engine step: a burst of queued prompts would
        # otherwise run n_slots sequential prefill programs before the
        # next decode tick, stalling every active request's output for
        # the whole burst. None = no cap (drain-oriented batch use);
        # servers should set 1-2 to bound decode latency jitter.
        self.max_prefills_per_step = max_prefills_per_step
        # Chunked prefill: prompts longer than this many tokens prefill
        # incrementally, one chunk program per step (each chunk counts
        # against max_prefills_per_step), so ONE long prompt can no
        # longer stall every active request for its whole prefill the
        # way the admission cap alone cannot prevent. None = whole
        # prompts in one program (the drain-oriented default).
        self.prefill_chunk = prefill_chunk
        self._prefilling: Dict[int, int] = {}  # slot -> tokens written
        self._chunk_jit: Dict[Any, Any] = {}  # keyed (pad, fresh)
        # logprobs=True: every emitted token's logprob (raw-logit
        # log_softmax, the Engine convention) is tracked; finished
        # requests deposit theirs here, keyed by rid, for the server
        # (or any caller) to pop.
        self.logprobs = logprobs
        # K alternatives recorded per generated token (0 = off). The
        # engine computes its max for every request; per-request k is
        # the renderer's slice.
        self.top_logprobs = top_logprobs
        self.finished_top_logprobs: Dict[Any, List] = {}
        self.finished_logprobs: Dict[Any, List[float]] = {}
        # prompt_logprobs=True requests deposit the prompt's per-token
        # logprobs here on completion (keyed by rid), like
        # finished_logprobs.
        self.finished_prompt_logprobs: Dict[Any, List[float]] = {}
        # Per-slot additive logit biases and remaining min_tokens (EOS
        # ban countdown, decremented on device inside the decode scan).
        # The (n_slots, vocab) bias matrix is allocated lazily on the
        # first biased request — most deployments never pay for it; the
        # shared zero row keeps prefill jit signatures stable.
        self._sbias: Optional[jax.Array] = None
        self._zero_bias_row = jnp.zeros((1, cfg.vocab_size), jnp.float32)
        self._slot_bias: List[Optional[Dict[int, float]]] = [None] * n_slots
        self._smin = jnp.zeros((n_slots,), jnp.int32)
        # Device-side stop/budget decisions: per-slot remaining max_new
        # budget and a sticky done flag, threaded through the decode
        # window so a slot that samples EOS (or exhausts its budget)
        # mid-window FREEZES on device — no overshoot compute, no
        # cache-length drift — and the window reports per-tick validity
        # flags so the host slices instead of scanning. Stop SEQUENCES
        # stay a host decision (arbitrary token lists); a stop-matched
        # slot decodes to the end of its window like before, and the
        # host discards the tail.
        self._srem = jnp.zeros((n_slots,), jnp.int32)
        self._sdone = jnp.zeros((n_slots,), bool)
        # OpenAI-style repetition penalties over GENERATED tokens:
        # per-slot token-count matrix (lazily allocated, like the bias
        # matrix) plus presence/frequency coefficient vectors. Counts
        # update on device inside the decode scan.
        self._scounts: Optional[jax.Array] = None
        self._spres = jnp.zeros((n_slots,), jnp.float32)
        self._sfreq = jnp.zeros((n_slots,), jnp.float32)
        self._slot_pen: List[bool] = [False] * n_slots
        # Per-request deterministic sampling: seed (-1 = unseeded, use
        # the shared stream) + the slot's generated-token count at the
        # start of each decode window (host-known: len(req.out)).
        self._sseed = jnp.full((n_slots,), -1, jnp.int32)
        # Structured decoding: active constrained slots' TokenDFA
        # tables stacked into one device table (rows bucketed so the
        # decode trace is reused across request churn), a per-slot row
        # offset (-1 = unconstrained), and per-slot DFA state that
        # advances on device inside the decode scan.
        self._slot_dfa: List[Optional[Any]] = [None] * n_slots
        self._ctrans: Optional[jax.Array] = None
        self._con_dirty = False
        self._coff = jnp.full((n_slots,), -1, jnp.int32)
        self._cstate = jnp.zeros((n_slots,), jnp.int32)
        # Shared dummy table for unconstrained decode steps (the hot
        # path): allocated once, like _zero_bias_row.
        self._dummy_ctrans = jnp.full(
            (1, cfg.vocab_size + 1), -1, jnp.int32
        )
        # Engine-level sampling defaults; submit() can override any of
        # them per request. Each slot's effective settings live in
        # device vectors fed to the jitted programs, so one decode tick
        # serves greedy and sampled requests side by side.
        self._defaults = {
            "temperature": float(temperature),
            # top_k resolves once, here: None (disabled) = full vocab.
            "top_k": int(top_k) if top_k is not None else cfg.vocab_size,
            "top_p": float(top_p) if top_p is not None else 1.0,
            "min_p": float(min_p) if min_p is not None else 0.0,
        }
        self._validate_sampling(self._defaults, "engine defaults")
        self._stemp = jnp.full((n_slots,), self._defaults["temperature"],
                               jnp.float32)
        self._stopk = jnp.full((n_slots,), self._defaults["top_k"],
                               jnp.int32)
        self._stopp = jnp.full((n_slots,), self._defaults["top_p"],
                               jnp.float32)
        self._sminp = jnp.full((n_slots,), self._defaults["min_p"],
                               jnp.float32)
        # The construction seed is retained (not just consumed into the
        # key) so the multi-host epoch resync can re-key deterministically
        # per (seed, epoch) instead of collapsing every job onto the
        # same post-recovery stream.
        self.seed = int(seed)
        self._key = jax.random.PRNGKey(seed)

        # The backend builds the device cache (dense rows, int8 rows +
        # scales, a rolling ring, or the paged block pool — the engine
        # never branches on the kind).
        self._cache = self.cache_backend.init_cache()
        self._cur = jnp.zeros((n_slots,), jnp.int32)  # next input token
        # The pending queue is a weighted-fair queue over priority
        # classes (deficit round robin on token costs). With a single
        # class in play — every engine that never tags qos_class —
        # it is FIFO-identical to the deque it replaced.
        self._queue: WeightedFairQueue = WeightedFairQueue()
        self._slots: List[Optional[_Request]] = [None] * n_slots
        # Prefill-only requests whose prompt KV is resident and frozen,
        # awaiting export (rid -> slot). The serving scheduler drains
        # this after each step: export_slot -> release_frozen.
        self.frozen_prefills: Dict[Any, int] = {}
        # Preempted mid-decode requests frozen in place awaiting
        # export (rid -> slot). A SEPARATE table from frozen_prefills:
        # the scheduler's export policies differ (prefill_only slots
        # settle their client with a migration/park receipt; preempted
        # slots keep their client attached across park -> resume).
        self.frozen_decodes: Dict[Any, int] = {}
        self._prefill_jit: Dict[int, Any] = {}  # bucketed by padded S
        # Lazily built single-request Engine sharing these params:
        # the dense beam_search() entry point (the paged subclass
        # searches its own block pool instead).
        self._beam_delegate = None
        # The decode jit is built lazily (first _decode_tokens): with a
        # mesh its out_shardings pin the cache layout, and the paged
        # subclass swaps in its own cache (different pytree) after this
        # constructor runs. Two decode variants (one trace each):
        # greedy_only skips the batched sampler's full-vocab sorts when
        # every active request is greedy — the common serving default.
        self._decode = None
        # The backend built the final cache pytree above, so shardings
        # pin once, here, for every backend kind (the paged subclass no
        # longer swaps a transient dense cache).
        self._mesh_setup()
        # Serving observability (read by the HTTP /stats endpoint).
        # Written only by the engine-owning thread; plain ints so
        # cross-thread reads are merely possibly-stale, never torn.
        self.stats: Dict[str, int] = {
            "requests_completed": 0,
            "tokens_generated": 0,
            "engine_steps": 0,
            "prefills": 0,
            "prefill_chunks": 0,
            "requests_cancelled": 0,
            # Mirrored as shellac_engine_* gauges at /metrics scrape
            # time: the live decode_ticks (the auto-tuner rewrites it)
            # and the window pipeline depth (2 = overlapped dispatch,
            # 1 = strict ordering) so the tier's load scoring can see
            # how each replica runs its hot loop.
            "decode_ticks": decode_ticks,
            "overlap_depth": 2 if self.overlap_decode else 1,
            # Admission-side pipeline knobs, mirrored like the decode
            # ones: is prefill dispatch overlapped, and what chunk size
            # is live (0 = whole prompts; the auto-tuner rewrites it).
            "overlap_prefill": 1 if self.overlap_prefill else 0,
            "prefill_chunk": prefill_chunk or 0,
            # The active storage policy (registry name). Non-numeric,
            # so the /metrics stat mirror skips it; the server exposes
            # it as the shellac_engine_cache_backend_info gauge label.
            "cache_backend": self.cache_backend.name,
            # Disaggregated serving: migration legs served by this
            # engine, plus the backend's resident bytes per KV token —
            # the tier's transfer-cost estimate reads the mirrored
            # shellac_engine_kv_bytes_per_token gauge.
            "kv_exports": 0,
            "kv_imports": 0,
            "kv_bytes_per_token": self.cache_backend.bytes_per_token(),
            # Multi-tenant QoS: mid-decode freezes ordered by the
            # serving scheduler's preempt-and-park driver.
            "preemptions": 0,
        }
        self.stats.update(self.cache_backend.initial_stats())
        # How decode_ticks was chosen: "fixed" (explicit int) or
        # "auto" (pending tune; autotune rewrites it to "auto-tuned").
        self.decode_ticks_source = (
            "auto" if self.decode_ticks_requested == "auto" else "fixed"
        )
        # How prefill_chunk was chosen, mirroring decode_ticks_source:
        # "fixed" (explicit int or None) or "auto" (pending tune;
        # autotune_prefill_chunk rewrites it to "auto-tuned").
        self.prefill_chunk_source = (
            "auto" if self.prefill_chunk_requested == "auto" else "fixed"
        )
        # Richer observability (histograms + gauges) over the shared
        # registry — the Prometheus-facing counterpart of `stats`.
        # Everything it records is host-side and per engine STEP, never
        # per token and never inside a jitted program.
        self.obs = EngineMetrics(
            registry if registry is not None else get_registry()
        )

    # ---- sharding ----------------------------------------------------

    def _mesh_setup(self) -> None:
        """Pin the cache's shardings on the mesh, whatever its backend
        kind. Called once self._cache holds its final pytree (end of
        the constructor). Re-called, it just recomputes the sharding
        tree and invalidates the lazily-built decode jit.
        """
        if self.mesh is None:
            self._cache_sh = None
            return
        # The backend that built the cache provides its axes — the
        # sharding tree can never desync from the pytree.
        self._cache_sh = make_shardings(
            self.mesh, self.cache_backend.logical_axes()
        )
        self._cache = jax.device_put(self._cache, self._cache_sh)
        self._decode = None

    def _jit_cache_program(self, fn, n_tail: int, **jit_kw):
        """jit a program returning (cache, <n_tail others>), pinning the
        cache's shardings on the mesh (no-op unsharded) and donating
        the cache argument: every program threads cache-in -> cache-out
        (arg index 1, after params) and the caller rebinds self._cache
        from the result immediately, so XLA may write the update in
        place instead of copying the whole pool each prefill/decode."""
        if self._cache_sh is not None:
            jit_kw["out_shardings"] = (self._cache_sh,) + (None,) * n_tail
        return jax.jit(fn, donate_argnums=(1,), **jit_kw)

    # ---- jitted programs --------------------------------------------

    def _fresh_mini(self, length: int):
        """Batch-1 cache of the engine's cache kind (prefill scratch),
        built by the backend so it always matches the slot cache."""
        return self.cache_backend.init_mini(length)

    @staticmethod
    def _plp_within(logits, tokens):
        """Each token's logprob given its IN-ROW predecessor: position
        t scores from logits row t-1; position 0 (no predictor in this
        row) reports 0.0. The single definition the whole-prompt AND
        chunked paths share, so their scoring cannot drift."""
        lps = jax.nn.log_softmax(logits[0, :-1].astype(jnp.float32))
        tok_lp = jnp.take_along_axis(
            lps, tokens[0, 1:][:, None], axis=-1
        )[:, 0]
        return jnp.zeros((tokens.shape[1],), jnp.float32).at[1:].set(tok_lp)

    def _prefill_impl(self, params, cache, tokens, prompt_len, slot, key,
                      samp, want_plp: bool = False):
        """Prefill one request and scatter it into `slot` of `cache`.

        want_plp additionally returns the PROMPT's per-token logprobs
        (token t given tokens[:t]; position 0 has no predictor and
        reports 0.0 — the server renders it as null)."""
        mini = self._fresh_mini(self.max_len)
        logits, mini = transformer.forward_with_cache(
            self.cfg, params, tokens, mini, new_tokens_len=prompt_len,
            fresh_cache=True, attn_impl=self.attn_impl, mesh=self.mesh,
        )
        last = jnp.take_along_axis(
            logits, (prompt_len - 1)[:, None, None].astype(jnp.int32), axis=1
        )[0, 0]
        first, first_lp = self._sample_first(key, last, samp)
        plp = (self._plp_within(logits, tokens) if want_plp
               else jnp.zeros((tokens.shape[1],), jnp.float32))
        tlv, tli = self._first_tl(last)
        return (scatter_slot(cache, mini, slot), first, first_lp, plp,
                tlv, tli)

    def _decode_impl(self, params, cache, cur, active, key, samp,
                     greedy_only: bool = False, use_bias: bool = False,
                     use_pen: bool = False, use_seed: bool = False,
                     use_con: bool = False):
        """decode_ticks decode steps over every slot, ONE host sync.

        Per-tick host reads dominate serving latency when the device is
        remote (each tick would pay a full RPC round trip); scanning K
        ticks on device amortizes that K-fold. The per-slot stop
        decisions the host used to make by scanning the window live
        HERE now: a slot whose sampled token is EOS, or whose max_new
        budget runs out, sets its sticky `done` flag and freezes
        (lengths, sampling state, token stream) for the rest of the
        window — the host receives per-tick validity flags and slices,
        instead of re-deriving EOS/budget cuts from the raw token
        matrix. Inactive slots stay frozen throughout. Returns (cache,
        tokens (K, n_slots), logprobs (K, n_slots) — zeros unless
        self.logprobs, min_rem, counts, cstate, top-K values/ids, rem,
        done, acts (K, n_slots) validity flags).

        use_con: constrained slots mask logits through their DFA row
        and advance their state per sampled token — two gathers per
        tick, no host sync, so structured decoding rides the same
        multi-tick scan.
        """

        bias = samp[4] if use_bias else None
        min_rem0 = samp[5]
        pres, freq, counts0 = samp[6], samp[7], samp[8]
        seed_vec, gen0 = samp[9], samp[10]
        ctrans, coff, cstate0 = samp[11], samp[12], samp[13]
        rem0, done0 = samp[14], samp[15]

        def tick(carry, key_i):
            key, i = key_i
            cache, cur, min_rem, counts, cstate, rem, done = carry
            # A slot finished earlier in THIS window freezes exactly
            # like an inactive one.
            act = active & ~done
            old_lengths = cache.lengths
            logits, cache = transformer.forward_with_cache(
                self.cfg, params, cur[:, None], cache,
                attn_impl=self.attn_impl, mesh=self.mesh,
            )
            lengths = jnp.where(act, cache.lengths, old_lengths)
            cache = cache.replace(lengths=lengths)
            nxt, min_rem, new_cstate, lp, tlv, tli = (
                self._row_decode_step(
                    key, logits[:, 0], cur, act, min_rem, bias,
                    (pres, freq, counts) if use_pen else None,
                    (coff, cstate, ctrans) if use_con else None,
                    samp[:4], seed_vec if use_seed else None, gen0 + i,
                    greedy_only, use_pen, use_con, use_seed,
                )
            )
            if use_con:
                cstate = new_cstate
            if use_pen:
                counts = counts.at[
                    jnp.arange(counts.shape[0]), nxt
                ].add(act.astype(jnp.float32))
            # Device-side stop decision: this emitted token ends the
            # request when it is EOS (min_tokens already banned EOS
            # from sampling while its countdown runs) or when it is the
            # last of the max_new budget. rem <= 1 rather than == 1 so
            # a slot that somehow enters with rem 0 freezes instead of
            # wrapping.
            fin = act & (rem <= 1)
            if self.eos_id is not None:
                fin = fin | (act & (nxt == self.eos_id))
            rem = jnp.where(act, jnp.maximum(rem - 1, 0), rem)
            done = done | fin
            return ((cache, nxt, min_rem, counts, cstate, rem, done),
                    (nxt, lp, tlv, tli, act))

        keys = jax.random.split(key, self.decode_ticks)
        ticks_i = jnp.arange(self.decode_ticks, dtype=jnp.int32)
        ((cache, _, min_rem, counts, cstate, rem, done),
         (toks, lps, tlvs, tlis, acts)) = jax.lax.scan(
            tick, (cache, cur, min_rem0, counts0, cstate0, rem0, done0),
            (keys, ticks_i),
        )
        return (cache, toks, lps, min_rem, counts, cstate, tlvs, tlis,
                rem, done, acts)

    def _row_decode_step(self, key, logits, cur_r, active_r, min_rem_r,
                         bias_r, pen_r, con_r, samp_r, seed_r, gen_idx_r,
                         greedy_only, use_pen, use_con, use_seed):
        """The per-row exit math of ONE decode tick, shared by the
        unpipelined scan (_decode_impl, rows = all slots) and the
        pipelined scan (_decode_impl_pp, rows = the exiting group) so
        the two paths cannot drift: logit adjust (bias + min_tokens),
        OpenAI penalties, DFA constraint masking + state advance,
        sampling, and logprob extraction are defined once, here.

        logits: (R, V) raw fp32 rows. pen_r = (pres, freq, counts)
        rows or None; con_r = (coff, cstate, ctrans) or None. Returns
        (nxt, min_rem_new, cstate_new or None, lp, tlv, tli); callers
        own the counts scatter (their layouts differ) and any validity
        masking beyond active_r (the pipelined path folds its warmup
        mask into it)."""
        adj = self._adjust_logits(logits, bias_r, min_rem_r)
        if use_pen:
            # OpenAI semantics over generated tokens: presence
            # subtracts once per seen token, frequency per count.
            pres_r, freq_r, counts_r = pen_r
            adj = adj - (pres_r[:, None] * (counts_r > 0.0)
                         + freq_r[:, None] * counts_r)
        row = None
        if use_con:
            coff_r, cstate_r, ctrans = con_r
            con = coff_r >= 0
            row = ctrans[jnp.clip(coff_r, 0, None) + cstate_r]
            allowed = row[:, :-1] >= 0  # (R, V)
            if self.eos_id is not None:
                # EOS legality comes from the dedicated last column
                # (allowed exactly in accepting states).
                allowed = allowed.at[:, self.eos_id].set(
                    row[:, -1] >= 0
                )
            # Constraint wins over any user bias: disallowed stays
            # -inf regardless of logit_bias.
            adj = jnp.where(con[:, None] & ~allowed, NEG_INF, adj)
        if greedy_only:
            nxt = jnp.argmax(adj, axis=-1).astype(jnp.int32)
        elif use_seed:
            nxt = sample_batched(
                key, adj, *samp_r, seed=seed_r, gen_idx=gen_idx_r,
            )
        else:
            nxt = sample_batched(key, adj, *samp_r)
        nxt = jnp.where(active_r, nxt, cur_r)
        min_rem_new = jnp.where(
            active_r, jnp.maximum(min_rem_r - 1, 0), min_rem_r
        )
        cstate_new = None
        if use_con:
            col = nxt
            if self.eos_id is not None:
                col = jnp.where(
                    nxt == self.eos_id, row.shape[1] - 1, nxt
                )
            new_st = jnp.take_along_axis(
                row, col[:, None], axis=1
            )[:, 0]
            cstate_new = jnp.where(
                con & active_r, jnp.maximum(new_st, 0), cstate_r
            )
        k_tl = self.top_logprobs
        n_rows = nxt.shape[0]
        if self.logprobs:
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32))
            lp = jnp.take_along_axis(lsm, nxt[:, None], axis=-1)[:, 0]
            if k_tl:
                tlv, tli = jax.lax.top_k(lsm, k_tl)
                tli = tli.astype(jnp.int32)
            else:
                tlv = jnp.zeros((n_rows, 0), jnp.float32)
                tli = jnp.zeros((n_rows, 0), jnp.int32)
        else:
            lp = jnp.zeros((n_rows,), jnp.float32)
            tlv = jnp.zeros((n_rows, 0), jnp.float32)
            tli = jnp.zeros((n_rows, 0), jnp.int32)
        return nxt, min_rem_new, cstate_new, lp, tlv, tli

    def _decode_impl_pp(self, params, cache, cur, active, key, samp,
                        greedy_only: bool = False, use_bias: bool = False,
                        use_pen: bool = False, use_seed: bool = False,
                        use_con: bool = False):
        """Token-level pipelined decode window — same contract as
        _decode_impl (decode_ticks tokens per slot, one host sync),
        restructured so pp stages never idle.

        Slots split into pp contiguous groups of G = n_slots/pp. A
        stage register (pp, G, 1, D) rolls through pp*K + (pp-1)
        microticks: each microtick vmaps every stage's layer block
        over the group it holds (pp groups advance concurrently on
        their own devices), the group leaving the last stage is
        sampled, and it re-enters stage 0 next microtick with its
        fresh token. The pp-1 tail microticks drain the register so
        no pipeline state crosses the call boundary — slot churn
        (prefills, releases) between windows needs no special casing.
        Drain-tail entries never exit; their cache writes land at each
        slot's NEXT position and are overwritten by that token's real
        pass in the following window (same self-healing argument as
        the engine's finished-slot overshoot).

        Per-row math is identical to _decode_impl (same block, norm,
        unembed, adjust, sample formulas on the same values), so
        greedy output is bit-exact vs the unpipelined engine.

        Device-side stop decisions are NOT wired here: freezing a
        group mid-register would leave drain-tail bookkeeping per
        stage for a path whose win is stage utilization, not host
        syncs. rem/done pass through untouched, the validity flags
        report every active exit, and the host keeps its historical
        EOS/budget scan for pipelined engines — outputs are identical
        either way (the flags only dropped tokens the host discarded).
        """
        from shellac_tpu.inference import pp_pipeline as ppl

        pp = self._pp
        n_slots = self.n_slots
        G = n_slots // pp
        K = self.decode_ticks
        total = pp * K + pp - 1
        cdt = self.cfg.compute_dtype
        d_model = self.cfg.d_model
        vocab = self.cfg.vocab_size

        bias = samp[4] if use_bias else None
        min_rem0 = samp[5]
        pres, freq, counts0 = samp[6], samp[7], samp[8]
        seed_vec, gen0 = samp[9], samp[10]
        ctrans, coff, cstate0 = samp[11], samp[12], samp[13]
        rem0, done0 = samp[14], samp[15]

        cache_fields = kv_field_names(self.kv_quant)
        cache_st = tuple(
            ppl.stage_split(getattr(cache, f), pp) for f in cache_fields
        )
        sp = ppl.stage_split(params["layers"], pp)

        def rows(vec, gstart):
            return jax.lax.dynamic_slice_in_dim(vec, gstart, G, axis=0)

        def put_rows(vec, val, gstart):
            return jax.lax.dynamic_update_slice_in_dim(
                vec, val, gstart, axis=0
            )

        def microtick(carry, inp):
            key_t, t = inp
            (cache_st, lengths, cur, min_rem, counts, cstate,
             stage_x, stage_pos, stage_gstart) = carry

            # Entry: the group t mod pp embeds its latest token into
            # stage 0. During the drain tail these entries are dead
            # (they never exit; see docstring).
            gstart_in = (t % pp) * G
            cur_in = rows(cur, gstart_in)
            len_in = rows(lengths, gstart_in)
            x_in = ppl.embed_group(self.cfg, params, cur_in, self.mesh)
            stage_x = jnp.roll(stage_x, 1, axis=0).at[0].set(x_in)
            stage_pos = jnp.roll(stage_pos, 1, axis=0).at[0].set(len_in)
            stage_gstart = (
                jnp.roll(stage_gstart, 1, axis=0).at[0].set(gstart_in)
            )
            stage_x = ppl.constrain_register(stage_x, self.mesh)

            outs, cache_st = ppl.stage_apply(
                self.cfg, self.mesh, self.attn_impl, sp,
                cache_st, stage_x, stage_pos, stage_gstart,
                rolled=self.rolling_window,
            )
            outs = ppl.constrain_register(outs, self.mesh)
            stage_x = outs

            # Exit: the group leaving stage pp-1 gets sampled. Before
            # warmup completes (t < pp-1) the exit rows are garbage —
            # every state update is masked off and the emitted tokens
            # are dropped on the host side.
            exit_valid = t >= (pp - 1)
            gstart_out = stage_gstart[pp - 1]
            pos_out = stage_pos[pp - 1]
            logits_g = ppl.head_logits(self.cfg, params, outs[pp - 1])

            # Warmup exits (t < pp-1) are garbage: fold the validity
            # mask into the active rows so _row_decode_step's own
            # masking freezes every state update, and the emitted
            # tokens (cur echoes) are dropped on the host side.
            active_eff = rows(active, gstart_out) & exit_valid
            cur_out = rows(cur, gstart_out)
            len_out = rows(lengths, gstart_out)
            bias_g = (
                jax.lax.dynamic_slice(bias, (gstart_out, 0), (G, vocab))
                if use_bias else None
            )
            pen_r = None
            if use_pen:
                pen_r = (
                    rows(pres, gstart_out), rows(freq, gstart_out),
                    jax.lax.dynamic_slice(
                        counts, (gstart_out, 0), (G, vocab)
                    ),
                )
            con_r = None
            if use_con:
                con_r = (rows(coff, gstart_out),
                         rows(cstate, gstart_out), ctrans)
            # This exit is the group's ((t - (pp-1)) // pp)-th token of
            # the window — the per-slot gen counter seeded sampling
            # uses, so seeded streams match the unpipelined engine.
            k_idx = jnp.maximum(t - (pp - 1), 0) // pp
            nxt, min_rem_g, cstate_g, lp, tlv, tli = (
                self._row_decode_step(
                    key_t, logits_g, cur_out, active_eff,
                    rows(min_rem, gstart_out), bias_g, pen_r, con_r,
                    (rows(samp[0], gstart_out),
                     rows(samp[1], gstart_out),
                     rows(samp[2], gstart_out),
                     rows(samp[3], gstart_out)),
                    rows(seed_vec, gstart_out) if use_seed else None,
                    rows(gen0, gstart_out) + k_idx,
                    greedy_only, use_pen, use_con, use_seed,
                )
            )
            lengths = put_rows(
                lengths, jnp.where(active_eff, pos_out + 1, len_out),
                gstart_out,
            )
            cur = put_rows(cur, nxt, gstart_out)
            min_rem = put_rows(min_rem, min_rem_g, gstart_out)
            if use_con:
                cstate = put_rows(cstate, cstate_g, gstart_out)
            if use_pen:
                counts = counts.at[
                    gstart_out + jnp.arange(G), nxt
                ].add(active_eff.astype(jnp.float32))
            new_carry = (cache_st, lengths, cur, min_rem, counts,
                         cstate, stage_x, stage_pos, stage_gstart)
            return new_carry, (nxt, lp, tlv, tli, active_eff)

        stage_x0 = ppl.constrain_register(
            jnp.zeros((pp, G, 1, d_model), cdt), self.mesh
        )
        # Warmup stages hold garbage (gstart 0); pin their write
        # position to group 0's CURRENT lengths so the garbage K/V
        # lands exactly where group 0's real token writes correct
        # values before any read — never at position 0, which would
        # corrupt live prefix rows.
        stage_pos0 = jnp.broadcast_to(
            cache.lengths[:G][None, :], (pp, G)
        )
        stage_gstart0 = jnp.zeros((pp,), jnp.int32)
        keys = jax.random.split(key, total)
        ts = jnp.arange(total, dtype=jnp.int32)
        carry0 = (cache_st, cache.lengths, cur, min_rem0, counts0,
                  cstate0, stage_x0, stage_pos0, stage_gstart0)
        ((cache_st, lengths, _, min_rem, counts, cstate, _, _, _),
         (nxts, lps, tlvs, tlis, acts)) = jax.lax.scan(
            microtick, carry0, (keys, ts)
        )
        cache = cache.replace(
            lengths=lengths,
            **{f: ppl.stage_merge(c)
               for f, c in zip(cache_fields, cache_st)},
        )
        # Exits come out round-robin: microtick pp-1+m emits group
        # m mod pp's (m//pp)-th token. Groups are contiguous ascending
        # slot ranges, so reshaping the valid tail gives (K, n_slots)
        # in slot order — the same shape _decode_impl returns.
        toks = nxts[pp - 1:].reshape(K, n_slots)
        lps_out = lps[pp - 1:].reshape(K, n_slots)
        k_tl = self.top_logprobs
        tlvs_out = tlvs[pp - 1:].reshape(K, n_slots, k_tl)
        tlis_out = tlis[pp - 1:].reshape(K, n_slots, k_tl)
        acts_out = acts[pp - 1:].reshape(K, n_slots)
        return (cache, toks, lps_out, min_rem, counts, cstate,
                tlvs_out, tlis_out, rem0, done0, acts_out)

    # ---- scheduling --------------------------------------------------

    @staticmethod
    def _validate_sampling(d: Dict[str, Any], label) -> None:
        if d["temperature"] < 0:
            raise ValueError(f"{label}: temperature must be >= 0")
        if d["top_k"] < 1:
            raise ValueError(f"{label}: top_k must be >= 1 (or None)")
        if not 0 < d["top_p"] <= 1:
            raise ValueError(f"{label}: top_p must be in (0, 1]")
        if not 0 <= d["min_p"] < 1:
            raise ValueError(f"{label}: min_p must be in [0, 1)")

    def _adjust_logits(self, logits, bias, min_rem):
        """Apply per-row logit biases and the min_tokens EOS ban to a
        (B, V) fp32 logit block; sampling consumes the result while
        logprobs keep reporting the raw distribution."""
        x = logits.astype(jnp.float32)
        if bias is not None:
            x = x + bias
        if self.eos_id is not None:
            col = jnp.where(min_rem > 0, NEG_INF, x[:, self.eos_id])
            x = x.at[:, self.eos_id].set(col)
        return x

    def _first_tl(self, last):
        """Top-K alternatives of a prefill's first sampled position
        ((1, K) values, (1, K) ids) — zero-width when disabled, so
        every prefill program keeps one output arity per engine."""
        k = self.top_logprobs
        if not k:
            return (jnp.zeros((1, 0), jnp.float32),
                    jnp.zeros((1, 0), jnp.int32))
        lsm = jax.nn.log_softmax(last.astype(jnp.float32))[None]
        vals, ids = jax.lax.top_k(lsm, k)
        return vals, ids.astype(jnp.int32)

    @staticmethod
    def _unpack_samp(samp):
        """Unpack a _slot_samp tuple: (temperature, top_k, top_p,
        min_p, bias row, min_tokens, seed, constraint mask), each a
        (1,)/(1, V) array. The scalars ride ONE packed int32 device
        buffer (floats bitcast); this is the single place the layout
        is decoded, shared by every prefill program."""
        packed, bias, cmask = samp
        fl = jax.lax.bitcast_convert_type(packed[:3], jnp.float32)
        return (fl[0][None], packed[3][None], fl[1][None], fl[2][None],
                bias, packed[4][None], packed[5][None], cmask)

    def _sample_first(self, key, last, samp):
        """Sample a prefill's first output token from the adjusted
        (biased, EOS-banned, constraint-masked) logits; the logprob
        stays on the raw ones. A seeded request's first token is draw
        gen_idx=0 of its own deterministic stream."""
        temp, topk, topp, minp, bias, min_rem, seed, cmask = (
            self._unpack_samp(samp)
        )
        adjusted = self._adjust_logits(last[None], bias, min_rem)
        # Constraint mask LAST: a grammar-disallowed token must stay
        # disallowed no matter what the user's logit_bias says.
        adjusted = adjusted + cmask
        first = sample_batched(
            key, adjusted, temp, topk, topp, minp,
            seed=seed, gen_idx=jnp.zeros((1,), jnp.int32),
        )[0]
        lp = jax.nn.log_softmax(last.astype(jnp.float32))[first]
        return first, lp

    def submit(self, rid, tokens, max_new: int, stop=None, *,
               temperature=None, top_k=None, top_p=None,
               min_p=None, min_tokens=None, logit_bias=None,
               presence_penalty=None, frequency_penalty=None,
               prompt_logprobs=False, seed=None,
               constraint=None, trace=None,
               prefill_only: bool = False,
               tenant=None, qos_class=None, qos_weight=None) -> None:
        """Queue a request. `stop`: optional list of token-id sequences;
        generation ends when the output ends with any of them, and the
        matched sequence is removed from the returned tokens.
        temperature/top_k/top_p/min_p override the engine defaults for
        this request only — requests with different sampling settings
        share one device batch."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size == 0:
            raise ValueError(f"request {rid!r}: empty prompt")
        if max_new < 1:
            # The engine always emits the prefill-sampled token, so
            # max_new=0 would still return one token; reject it.
            raise ValueError(f"request {rid!r}: max_new must be >= 1")
        if tokens.size + max_new + 1 > self.max_len:
            raise ValueError(
                f"request {rid!r}: prompt {tokens.size} + max_new {max_new} "
                f"exceeds max_len {self.max_len}"
            )
        if stop is not None:
            stop = [list(map(int, s)) for s in stop]
            if any(len(s) == 0 for s in stop):
                raise ValueError(f"request {rid!r}: empty stop sequence")
        d = self._defaults
        samp = {
            "temperature": float(
                temperature if temperature is not None else d["temperature"]
            ),
            "top_k": int(top_k) if top_k is not None else d["top_k"],
            "top_p": float(top_p) if top_p is not None else d["top_p"],
            "min_p": float(min_p) if min_p is not None else d["min_p"],
        }
        self._validate_sampling(samp, f"request {rid!r}")
        min_tokens = int(min_tokens) if min_tokens is not None else 0
        if min_tokens < 0:
            raise ValueError(f"request {rid!r}: min_tokens must be >= 0")
        if min_tokens > 0 and self.eos_id is None:
            raise ValueError(
                f"request {rid!r}: min_tokens needs the engine's eos_id "
                "(there is no EOS to suppress otherwise)"
            )
        if logit_bias is not None:
            try:
                logit_bias = {int(k): float(v)
                              for k, v in dict(logit_bias).items()}
            except (TypeError, ValueError) as e:
                raise ValueError(f"request {rid!r}: bad logit_bias: {e}")
            oob = [k for k in logit_bias if not 0 <= k < self.cfg.vocab_size]
            if oob:
                raise ValueError(
                    f"request {rid!r}: logit_bias token ids {oob} outside "
                    f"vocab [0, {self.cfg.vocab_size})"
                )
        if prompt_logprobs and getattr(self, "prefix_cache", False):
            raise ValueError(
                f"request {rid!r}: prompt_logprobs does not compose "
                "with the prefix cache (a cache hit skips exactly the "
                "forward passes that would score the prefix); use a "
                "non-prefix-cached engine for scoring"
            )
        pres = float(presence_penalty) if presence_penalty is not None \
            else 0.0
        freq = float(frequency_penalty) if frequency_penalty is not None \
            else 0.0
        for nm, v in (("presence_penalty", pres),
                      ("frequency_penalty", freq)):
            if not np.isfinite(v):
                raise ValueError(f"request {rid!r}: {nm} must be finite")
        if seed is not None:
            seed = int(seed)
            if seed < 0:
                raise ValueError(
                    f"request {rid!r}: seed must be >= 0 (negative is "
                    "the unseeded sentinel)"
                )
            # OpenAI clients send 63-bit seeds; the device vector is
            # int32. Fold deterministically instead of overflowing in
            # the scheduler thread.
            seed &= 0x7FFFFFFF
        if constraint is not None:
            from shellac_tpu.inference.constraints import TokenDFA

            if not isinstance(constraint, TokenDFA):
                raise ValueError(
                    f"request {rid!r}: constraint must be a compiled "
                    "constraints.TokenDFA (the server compiles specs; "
                    "library users call compile_token_dfa)"
                )
            if constraint.trans.shape[1] != self.cfg.vocab_size + 1:
                raise ValueError(
                    f"request {rid!r}: constraint table covers "
                    f"{constraint.trans.shape[1] - 1} tokens, model "
                    f"vocab is {self.cfg.vocab_size}"
                )
            if self.eos_id is None or constraint.eos_id != self.eos_id:
                raise ValueError(
                    f"request {rid!r}: constraint eos_id "
                    f"{constraint.eos_id} must equal the engine's "
                    f"eos_id {self.eos_id} (termination and EOS "
                    "masking must agree)"
                )
            if min_tokens > 0:
                raise ValueError(
                    f"request {rid!r}: min_tokens does not compose "
                    "with constraint (the EOS ban can contradict a "
                    "state where only EOS is legal)"
                )
        if prefill_only and constraint is not None:
            # A compiled TokenDFA is device-table state the wire
            # format cannot ship; constrained requests serve
            # monolithically (the tier's feature fallback).
            raise ValueError(
                f"request {rid!r}: prefill_only does not compose with "
                "constraint (the DFA table does not migrate)"
            )
        if qos_class is not None:
            qos_class = int(qos_class)
            if qos_class < 0:
                raise ValueError(
                    f"request {rid!r}: qos_class must be >= 0"
                )
        if qos_weight is not None:
            qos_weight = float(qos_weight)
            if qos_weight <= 0:
                raise ValueError(
                    f"request {rid!r}: qos_weight must be > 0"
                )
        self._queue.append(_Request(
            rid, tokens, max_new, stop=stop, min_tokens=min_tokens,
            logit_bias=logit_bias, presence_penalty=pres,
            frequency_penalty=freq,
            prompt_logprobs=bool(prompt_logprobs), seed=seed,
            constraint=constraint, trace=trace,
            prefill_only=bool(prefill_only),
            tenant=tenant if tenant is None else str(tenant),
            qos_class=qos_class if qos_class is not None else 1,
            qos_weight=qos_weight if qos_weight is not None else 4.0,
            t_queued=time.monotonic(), **samp,
        ))
        if trace is not None:
            # Flight-recorder timeline: the request entered the
            # engine's admission queue (queue-wait ends at the span's
            # prefill_start). No-op without a recorder on the trace.
            trace.record("queue", src="engine", rid=rid,
                         queue_depth=len(self._queue))

    def _slot_footprint(self, req: _Request) -> int:
        """Worst-case token residency of `req`: prompt + budget + 1,
        plus the engine's window slack (speculative rounds overshoot
        by gamma+1 before rolling back). The backend reserves this at
        admission and caps mid-decode growth at it."""
        return req.tokens.size + req.max_new + 1 + self._footprint_slack

    def _window_write_span(self) -> int:
        """Positions one decode window may write per slot — what the
        backend must keep resident ahead of the live length. The
        speculative mixin overrides (a verify round writes gamma+1)."""
        return self.decode_ticks

    def _prepare_slot(self, slot: int, req: _Request) -> None:
        """Reserve storage for `req` before its prefill (backend hook;
        paged allocates/attaches blocks). May raise PoolExhausted —
        _fill_slots requeues the request and retries after a release."""
        self.cache_backend.prepare_slot(slot, req,
                                        self._slot_footprint(req))

    def _release_slot(self, slot: int) -> None:
        """A request left `slot`: release its storage (backend hook;
        paged frees blocks) and clear the slot's SAMPLING state, which
        is the engine's own. Clearing the logit bias drops the engine
        back to the cheap no-bias decode variant — zeroing the row
        too, or a later unbiased request on this slot would silently
        inherit the stale biases."""
        self.cache_backend.release_slot(slot)
        if self._slot_bias[slot] is not None:
            self._sbias = self._sbias.at[slot].set(0.0)
            self._slot_bias[slot] = None
        if self._slot_pen[slot]:
            # Clear the coefficient AND the counts, or the next request
            # on this slot would inherit a stale repetition history.
            self._spres = self._spres.at[slot].set(0.0)
            self._sfreq = self._sfreq.at[slot].set(0.0)
            self._scounts = self._scounts.at[slot].set(0.0)
            self._slot_pen[slot] = False
        if self._slot_dfa[slot] is not None:
            self._slot_dfa[slot] = None
            self._cstate = self._cstate.at[slot].set(0)
            self._con_dirty = True

    def _bias_row(self, req: _Request) -> np.ndarray:
        row = np.zeros((self.cfg.vocab_size,), np.float32)
        for k, v in (req.logit_bias or {}).items():
            row[k] = v
        return row

    def _slot_samp(self, slot: int, req: _Request):
        """This request's sampling settings for the prefill jits:
        (packed scalars, logit bias row, first-token constraint mask).

        The six scalars (temperature, top_p, min_p bitcast to int32;
        top_k, remaining min_tokens, seed) are packed into ONE (6,)
        int32 host buffer so admission pays a single host->device
        upload instead of six round trips through the dispatch path —
        _unpack_samp is the matching device-side decoder. The bias row
        is a device slice of the matrix _set_slot_sampling already
        wrote (shared zero row when unbiased). The constraint mask is
        the DFA's state-0 row as an additive -inf mask — the prefill's
        sampled token must obey the grammar too; later tokens mask
        inside the decode scan."""
        packed = np.empty((6,), np.int32)
        packed[:3] = np.asarray(
            [req.temperature, req.top_p, req.min_p], np.float32
        ).view(np.int32)
        packed[3] = req.top_k
        packed[4] = req.min_tokens
        packed[5] = req.seed if req.seed is not None else -1
        bias = (self._sbias[slot][None] if req.logit_bias
                else self._zero_bias_row)
        if req.constraint is not None:
            row = req.constraint.trans[0]
            mask = np.where(row[:-1] >= 0, 0.0, NEG_INF).astype(np.float32)
            mask[req.constraint.eos_id] = 0.0 if row[-1] >= 0 else NEG_INF
            cmask = jnp.asarray(mask)[None]
        else:
            cmask = self._zero_bias_row
        return (jnp.asarray(packed), bias, cmask)

    def _set_slot_sampling(self, slot: int, req: _Request) -> None:
        """Write the request's settings into the per-slot vectors the
        decode program samples with."""
        self._stemp = self._stemp.at[slot].set(req.temperature)
        self._stopk = self._stopk.at[slot].set(req.top_k)
        self._stopp = self._stopp.at[slot].set(req.top_p)
        self._sminp = self._sminp.at[slot].set(req.min_p)
        new_bias = req.logit_bias or None
        if new_bias != self._slot_bias[slot]:
            # O(n_slots x vocab) device copy — only when this slot's
            # bias actually changes (never on the bias-free path).
            if self._sbias is None:
                self._sbias = jnp.zeros(
                    (self.n_slots, self.cfg.vocab_size), jnp.float32
                )
            self._sbias = self._sbias.at[slot].set(
                jnp.asarray(self._bias_row(req))
            )
            self._slot_bias[slot] = new_bias
        self._smin = self._smin.at[slot].set(req.min_tokens)
        self._sseed = self._sseed.at[slot].set(
            req.seed if req.seed is not None else -1
        )
        penalized = (req.presence_penalty != 0.0
                     or req.frequency_penalty != 0.0)
        if penalized or self._slot_pen[slot]:
            if self._scounts is None:
                self._scounts = jnp.zeros(
                    (self.n_slots, self.cfg.vocab_size), jnp.float32
                )
            self._spres = self._spres.at[slot].set(req.presence_penalty)
            self._sfreq = self._sfreq.at[slot].set(req.frequency_penalty)
            self._scounts = self._scounts.at[slot].set(0.0)
        self._slot_pen[slot] = penalized
        if req.constraint is not None or self._slot_dfa[slot] is not None:
            self._slot_dfa[slot] = req.constraint
            self._cstate = self._cstate.at[slot].set(0)
            # Lazy: admissions and releases in one engine step coalesce
            # into a single restack right before the next decode.
            self._con_dirty = True

    def _rebuild_constraints(self) -> None:
        """Restack active constrained slots' DFA tables into one device
        table with per-slot row offsets. Rows are bucketed to powers of
        two so the decode program's trace survives request churn."""
        self._con_dirty = False
        tables, offs, off = [], [], 0
        for dfa in self._slot_dfa:
            if dfa is None:
                offs.append(-1)
                continue
            offs.append(off)
            tables.append(dfa.trans)
            off += dfa.trans.shape[0]
        self._coff = jnp.asarray(offs, jnp.int32)
        if not tables:
            self._ctrans = None
            return
        rows = _bucket(off)
        stacked = np.concatenate(tables, axis=0)
        if rows > off:
            # Pad rows are unreachable (offsets only point at real
            # rows); -1 everywhere keeps them inert if that ever
            # changes.
            pad = np.full((rows - off, stacked.shape[1]), -1, np.int32)
            stacked = np.concatenate([stacked, pad], axis=0)
        self._ctrans = jnp.asarray(stacked)

    def _run_prefill(self, slot: int, req: _Request):
        """Run the (bucketed, jitted) prefill for `req`; returns
        (first sampled token, its raw logprob, top-K alternatives or
        None, prompt-logprob scores or None) — all DEVICE values, so
        dispatch pays no host sync; _settle_prefills (inline without
        overlap, batched at the next step boundary with it) pulls
        everything in one device_get."""
        s = req.tokens.size
        # Cap the bucket at max_len: a pad larger than the cache
        # (dense) or the block table (paged) would write out of
        # range — loudly for dense, silently-clamped for paged.
        pad = min(_bucket(s), self.max_len)
        key = (pad, req.prompt_logprobs)
        if key not in self._prefill_jit:
            self._prefill_jit[key] = self._jit_cache_program(
                self._prefill_impl, 5, static_argnames=("want_plp",)
            )
        padded = np.zeros((1, pad), np.int32)
        padded[0, :s] = req.tokens
        self._key, sub = jax.random.split(self._key)
        cache, first, lp, plp, tlv, tli = self._prefill_jit[key](
            self.params, self._cache, jnp.asarray(padded),
            jnp.asarray([s], jnp.int32), slot, sub, self._slot_samp(slot, req),
            want_plp=req.prompt_logprobs,
        )
        self._cache = cache
        # Prompt scoring no longer pays its own per-admission pull: the
        # device array rides the flight and lands in the ONE batched
        # settle device_get alongside the first token (SH002 history:
        # this line used to be a dedicated device_get).
        return (first, lp, ((tlv, tli) if self.top_logprobs else None),
                plp if req.prompt_logprobs else None)

    def _prefill_start_offset(self, slot: int) -> int:
        """Tokens already resident when prefill starts (the paged
        backend reports its matched prefix length)."""
        return self.cache_backend.prefill_offset(slot)

    def _fill_slots(self, budget: Optional[int] = None):
        done = 0
        for i in range(self.n_slots):
            if self._slots[i] is not None or not self._queue:
                continue
            if budget is not None and done >= budget:
                break
            done += 1
            req = self._queue.popleft()
            try:
                self._prepare_slot(i, req)
            except PoolExhausted:
                # Backend capacity exhausted: put the request back and
                # let it wait; retry after a slot frees its storage.
                self._queue.appendleft(req)
                break
            if req.trace is not None:
                # Queue wait ends here (after _prepare_slot: a paged
                # pool miss requeues the request, so its wait goes on).
                req.trace.prefill_start()
            self._set_slot_sampling(i, req)
            off = self._prefill_start_offset(i)
            if (self.prefill_chunk is not None
                    and req.tokens.size - off > self.prefill_chunk):
                # Long prompt: admit now, prefill incrementally in
                # step() (the slot stays out of decode until done).
                self._slots[i] = req
                self._prefilling[i] = off
                continue
            t_pf = time.perf_counter()
            arrays = self._run_prefill(i, req)
            self._dispatch_prefill(i, req, arrays)
            # Phase attribution: the prefill program dispatch, split
            # out of the surrounding admission bookkeeping (the settle
            # sync times itself into prefill_settle — immediately below
            # without overlap, at the next step boundary with it).
            self._phase_s["prefill_dispatch"] = (
                self._phase_s.get("prefill_dispatch", 0.0)
                + time.perf_counter() - t_pf
            )
            if not self.overlap_prefill:
                self._settle_prefills()

    def _dispatch_prefill(self, slot: int, req: _Request,
                          arrays) -> None:
        """A prefill (or final chunk) was just dispatched for `req`:
        record it as an in-flight _PrefillFlight. No host sync — the
        device outputs stay futures until _settle_prefills. The slot is
        occupied from here (pending accounting, admission exclusion)
        but prefill-pending: _active_rows keeps it out of decode
        windows until the settle writes its host bookkeeping."""
        self._slots[slot] = req
        self.stats["prefills"] += 1
        fl = _PrefillFlight(slot, req, arrays)
        self._pflights.append(fl)
        if self._prefill_hooks is not None:
            self._prefill_hooks.on_prefill_dispatch(fl)
        if self.overlap_prefill and req.trace is not None:
            # Flight-recorder timeline: dispatch half of the prefill
            # pipeline (settle lands as the span's first_token). Only
            # recorded under overlap — without it dispatch and settle
            # are one event, the span's existing prefill section.
            req.trace.record("prefill-dispatch", src="engine",
                             rid=req.rid, slot=slot,
                             depth=len(self._pflights))

    def _pending_prefill_slots(self):
        """Slots whose prefill is dispatched but not yet settled (and
        whose request still owns the slot) — excluded from decode
        windows until the settle writes their host bookkeeping."""
        return {fl.slot for fl in self._pflights
                if self._slots[fl.slot] is fl.req}

    def _settle_prefills(self) -> bool:
        """Settle EVERY in-flight prefill in ONE batched device_get:
        first tokens, logprobs, top-K alternatives, and the opt-in
        prompt-logprob payloads all ride the same pull. TTFT
        (trace.first_token) is recorded here — the settle point.
        Results for slots whose request was cancelled or replaced while
        the prefill was in flight are discarded (identity check, like
        stale decode windows). False if nothing was in flight."""
        if not self._pflights:
            return False
        flights, self._pflights = self._pflights, []
        t0 = time.perf_counter()
        if self._prefill_hooks is not None:
            self._prefill_hooks.before_prefill_sync(flights)
        host = jax.device_get([fl.arrays for fl in flights])  # shellac: ignore[SH002] — THE prefill settle: one batched pull for every in-flight prefill's first token / logprob / top-K / prompt scores (the per-admission pulls this replaces each paid their own round trip); the first tokens MUST reach the host here — settle is the TTFT point and the finish check needs them
        for fl, (first, lp, tl, plp) in zip(flights, host):
            if self._slots[fl.slot] is not fl.req:
                continue
            self._finish_prefill_host(fl.slot, fl.req, first, lp, tl,
                                      plp)
        self._phase_s["prefill_settle"] = (
            self._phase_s.get("prefill_settle", 0.0)
            + time.perf_counter() - t0
        )
        return True

    @staticmethod
    def _stitch_plp(plp_host, s: int) -> List[float]:
        """Normalize a settled prompt-logprob payload to the flat
        per-token list the server renders: either the whole-prompt
        score array (sliced to the real prompt length) or the chunked
        path's (in-chunk scores, size, boundary score) pieces stitched
        across chunk boundaries. Position 0 has no predictor and
        reports 0.0 (rendered as null)."""
        if not isinstance(plp_host, list):
            return [float(x) for x in np.asarray(plp_host)[:s]]
        flat = [0.0]
        for plp_w, sz, blp in plp_host:
            flat.extend(float(x) for x in np.asarray(plp_w)[1:sz])
            if blp is not None:
                flat.append(float(blp))
        return flat

    def _finish_prefill_host(self, slot: int, req: _Request, first,
                             lp=None, tl=None, plp=None) -> None:
        """Host half of prefill completion: all arguments are settled
        HOST values (pulled by _settle_prefills' one batched sync).
        The slot's prompt KV is now certainly resident, so paged
        prefix caching registers the prompt blocks as matchable here —
        at settle, never at dispatch (an in-flight program's blocks
        must not be matchable, and a cancelled flight's never are)."""
        self.cache_backend.on_prefill_complete(slot)
        first_tok = int(first)
        self._cur = self._cur.at[slot].set(first_tok)
        # Arm the device-side stop decisions: the prefill-sampled token
        # below is the first of max_new, so the decode window may emit
        # max_new - 1 more before the budget freeze; done clears in
        # case the slot's previous tenant froze it.
        self._srem = self._srem.at[slot].set(req.max_new - 1)
        self._sdone = self._sdone.at[slot].set(False)
        self._slots[slot] = req
        if req.constraint is not None:
            # Advance the DFA past the prefill-sampled token (host-side:
            # the token is already a host int here). Decode-time tokens
            # advance on device inside the scan.
            trans = req.constraint.trans
            col = (trans.shape[1] - 1 if first_tok == req.constraint.eos_id
                   else first_tok)
            nxt = int(trans[0, col])
            self._cstate = self._cstate.at[slot].set(max(nxt, 0))
        if self._slot_pen[slot]:
            # The prefill-sampled token is generated output: it joins
            # the slot's repetition counts.
            self._scounts = self._scounts.at[slot, first_tok].add(1.0)
        # The prefill-sampled token consumed one unit of the EOS ban.
        if req.min_tokens > 0:
            self._smin = self._smin.at[slot].set(req.min_tokens - 1)
        req.out.append(first_tok)
        if req.trace is not None:
            # The batched settle pull already synced: the first token
            # is a host value, so this is the request's TTFT point —
            # under overlap_prefill, the settle boundary, not the
            # dispatch (docs/decode_performance.md "Prefill overlap").
            req.trace.first_token()
        if self.logprobs and lp is not None:
            req.lps.append(float(lp))
        if self.top_logprobs and tl is not None:
            tlv, tli = tl  # host arrays — pulled with `first` above
            req.tlp = [(np.asarray(tli)[0].tolist(),
                        np.asarray(tlv)[0].tolist())]
        if req.prompt_logprobs and plp is not None:
            req.plp = self._stitch_plp(plp, req.tokens.size)
        if req.prefill_only:
            # Disaggregated freeze: the device-side done flag (PR 7's
            # freeze mechanism) plus host-side exclusion keep the slot
            # out of every decode window; the KV-migration exporter
            # ships it and release_frozen() reclaims the slot.
            self._sdone = self._sdone.at[slot].set(True)
            self.frozen_prefills[req.rid] = slot
            if req.trace is not None:
                req.trace.record("prefill-frozen", src="engine",
                                 rid=req.rid, slot=slot,
                                 prompt_len=int(req.tokens.size))

    # ---- chunked prefill --------------------------------------------

    def _advance_prefills(self, budget: Optional[int]) -> int:
        """Run up to `budget` prefill-chunk programs (all of them when
        budget is None); returns the number launched. Lowest slot
        first, drained depth-first — chunk N+1 reuses chunk N's cache
        row while it is hot."""
        used = 0
        t_pf = time.perf_counter()
        settle0 = self._phase_s.get("prefill_settle", 0.0)
        while self._prefilling and (budget is None or used < budget):
            slot = min(self._prefilling)
            used += 1
            self.stats["prefill_chunks"] += 1
            req = self._slots[slot]
            off = self._prefilling[slot]
            chunk = req.tokens[off:off + self.prefill_chunk]
            s = chunk.size
            pad = min(_bucket(s), self.max_len - off)
            self._key, sub = jax.random.split(self._key)
            final = off + s >= req.tokens.size
            boundary = (jnp.asarray(0, jnp.int32) if final
                        else jnp.asarray(int(req.tokens[off + s]),
                                         jnp.int32))
            cache, first, lp, plp_w, blp, tlv, tli = self._chunk_prefill(
                pad, off == 0, jnp.asarray(
                    np.pad(chunk, (0, pad - s))[None]
                ),
                jnp.asarray([s], jnp.int32), jnp.asarray([off], jnp.int32),
                slot, sub, self._slot_samp(slot, req),
                boundary_next=boundary, want_plp=req.prompt_logprobs,
            )
            self._cache = cache
            if req.prompt_logprobs:
                # Collect DEVICE arrays; the one blocking transfer
                # happens at the final chunk, so scoring does not
                # serialize the chunk pipeline with per-chunk syncs.
                if req.plp is None:
                    req.plp = []
                req.plp.append((plp_w, s, None if final else blp))
            if final:
                del self._prefilling[slot]
                # The final chunk's stitching sync no longer happens
                # here: the collected plp pieces (device arrays) ride
                # the flight and settle in the ONE batched pull with
                # the first token — _stitch_plp flattens them host-side
                # at settle.
                pieces = req.plp
                req.plp = None
                self._dispatch_prefill(
                    slot, req,
                    (first, lp,
                     ((tlv, tli) if self.top_logprobs else None),
                     pieces),
                )
                if not self.overlap_prefill:
                    self._settle_prefills()
            else:
                self._prefilling[slot] = off + s
        if used:
            # The chunk loop's dispatch work (program dispatches + host
            # glue); any final-chunk settle inside the loop timed
            # itself into prefill_settle and is subtracted out.
            self._phase_s["prefill_dispatch"] = (
                self._phase_s.get("prefill_dispatch", 0.0)
                + (time.perf_counter() - t_pf)
                - (self._phase_s.get("prefill_settle", 0.0) - settle0)
            )
        return used

    def _chunk_prefill(self, pad, fresh, tokens, chunk_len, offset, slot,
                       key, samp, boundary_next=None, want_plp=False):
        """Dispatch one (bucketed, jitted) chunk-continuation program."""
        jkey = (pad, fresh, want_plp)
        if jkey not in self._chunk_jit:
            self._chunk_jit[jkey] = self._jit_cache_program(
                functools.partial(self._chunk_prefill_impl, fresh=fresh,
                                  want_plp=want_plp), 6
            )
        if boundary_next is None:
            boundary_next = jnp.zeros((), jnp.int32)
        return self._chunk_jit[jkey](
            self.params, self._cache, tokens, chunk_len, offset, slot, key,
            samp, boundary_next,
        )

    def _chunk_prefill_impl(self, params, cache, tokens, chunk_len, offset,
                            slot, key, samp, boundary_next, *, fresh: bool,
                            want_plp: bool = False):
        """Write one prompt chunk at `offset` into `slot`'s cache row.

        A batch-1 view of the row continues from `offset` tokens
        (fresh_cache only for the first chunk — later chunks attend to
        the buffered prefix via the masked decode path). The sampled
        token is only meaningful for the final chunk; earlier chunks
        compute and discard it (cheaper than a second program variant).

        want_plp additionally returns (a) each chunk token's logprob
        given its IN-CHUNK predecessor (rows 1..s-1; row 0's predictor
        lives in the previous chunk) and (b) the boundary logprob of
        `boundary_next` — the NEXT chunk's first token — from this
        chunk's final position, so the host can stitch the full prompt
        scoring across chunks.
        """
        view = slot_view(cache, slot, offset)
        logits, view = transformer.forward_with_cache(
            self.cfg, params, tokens, view, new_tokens_len=chunk_len,
            fresh_cache=fresh,
            attn_impl=self.attn_impl if fresh else "ref", mesh=self.mesh,
        )
        last = jnp.take_along_axis(
            logits, (chunk_len - 1)[:, None, None].astype(jnp.int32), axis=1
        )[0, 0]
        first, first_lp = self._sample_first(key, last, samp)
        plp_within = jnp.zeros((tokens.shape[1],), jnp.float32)
        boundary_lp = jnp.zeros((), jnp.float32)
        if want_plp:
            plp_within = self._plp_within(logits, tokens)
            boundary_lp = jax.nn.log_softmax(
                last.astype(jnp.float32)
            )[boundary_next]
        tlv, tli = self._first_tl(last)
        return (scatter_slot(cache, view, slot), first, first_lp,
                plp_within, boundary_lp, tlv, tli)

    def _finish_check(self, finished):
        for i, req in enumerate(self._slots):
            if req is None or not req.out or req.prefill_only \
                    or req.frozen:
                # Slots mid-chunked-prefill have no output yet; frozen
                # prefill-only slots settle through the export path
                # (even when the prefill token alone completes them —
                # the blob carries the completion); preempted frozen
                # decodes leave through export_slot -> release_frozen.
                continue
            last = req.out[-1]
            nstop = req.hit_stop()
            if nstop is not None:
                req.out = req.out[:-nstop]
                req.lps = req.lps[:len(req.out)]
                if req.tlp is not None:
                    req.tlp = req.tlp[:len(req.out)]
            if nstop is not None or (
                self.eos_id is not None and last == self.eos_id
            ) or len(req.out) >= req.max_new:
                finished.append((req.rid, req.out))
                if self.logprobs:
                    self.finished_logprobs[req.rid] = req.lps[:len(req.out)]
                if self.top_logprobs and req.tlp is not None:
                    self.finished_top_logprobs[req.rid] = (
                        req.tlp[:len(req.out)]
                    )
                if req.plp is not None:
                    self.finished_prompt_logprobs[req.rid] = req.plp
                self.stats["requests_completed"] += 1
                self.stats["tokens_generated"] += len(req.out)
                self._slots[i] = None
                self._release_slot(i)

    def step(self) -> List[Tuple[Any, List[int]]]:
        """Fill free slots, run one decode window (decode_ticks ticks);
        returns finished requests. One host sync per call regardless of
        decode_ticks.

        overlap_decode=True turns this into a two-deep pipeline: the
        next window is dispatched against the CURRENT slot view before
        the previous window's sync is paid, so the device computes
        window k+1 while the host settles window k (detokenize, finish
        checks, slot release) and runs admissions. Consequences, all
        tested: requests admitted in a step join at the NEXT window
        boundary; a slot whose request finished in the un-synced window
        decodes one more (frozen-by-done or discarded) window; settle
        discards results for slots whose request was cancelled or
        replaced in flight (identity check). Strict ordering
        (overlap_decode=False) is bit-identical to the pre-overlap
        engine.

        overlap_prefill=True pipelines the ADMISSION side the same
        way: prefills dispatched in earlier steps settle first — one
        batched pull for all of them, at the step boundary — and the
        settled slots join this step's window; admissions later in
        the step dispatch their prefill and leave it in flight.
        overlap_prefill=False settles each prefill inline at its
        admission, bit-identical to the pre-pipeline engine."""
        finished: List[Tuple[Any, List[int]]] = []
        self.stats["engine_steps"] += 1
        t_step0 = time.perf_counter()
        self._sync_block_s = 0.0
        self._phase_s = {}
        synced = False
        settled_prefills = False
        if self._pflights:
            # Step boundary: every prefill dispatched in earlier steps
            # settles NOW, in one batched pull, BEFORE the next decode
            # window is dispatched — settled slots join this step's
            # window instead of waiting another boundary. A request
            # satisfied by its prefill alone (max_new=1, instant EOS,
            # stop completed by the first token) must be noticed here,
            # before admissions, or its slot stays occupied a step.
            settled_prefills = self._settle_prefills()
            if settled_prefills:
                self._finish_check(finished)
        if self.overlap_decode and self._windows:
            # Keep the device busy across the sync: dispatch the next
            # window on the current (stale w.r.t. the un-synced window)
            # slot view, THEN pay the previous window's sync. Slots
            # whose request finished in the un-synced window carry a
            # device-side done flag, so their extra window freezes.
            rows = self._active_rows()
            if any(rows):
                self.obs.occupancy.observe(sum(rows) / self.n_slots)
                self._dispatch_window(rows)
            t_settle0 = time.perf_counter()
            synced = self._settle_window(finished) or synced
            # Split the settle section into its blocked-on-device part
            # (decode_sync) and the host-side application (settle).
            self._phase_s["decode_sync"] = self._sync_block_s
            self._phase_s["settle"] = max(
                0.0, time.perf_counter() - t_settle0 - self._sync_block_s
            )
        t_fill0 = time.perf_counter()
        settle_fill0 = self._phase_s.get("prefill_settle", 0.0)
        prefills0 = self.stats["prefills"] + self.stats["prefill_chunks"]
        # Fill/check until stable: a request satisfied by its prefill
        # alone (max_new=1, instant EOS, or a stop sequence completed by
        # the prefill token) frees its slot for the next queued request,
        # which may itself finish at prefill — every admitted request
        # must pass a finish check BEFORE the decode window, or its
        # one-shot finish condition is missed forever. The prefill
        # budget is shared across the loop's iterations (per step).
        remaining = self.max_prefills_per_step
        # In-flight chunked prefills advance FIRST: they are older than
        # anything still queued, and giving admissions priority would
        # let a sustained stream of short prompts starve an admitted
        # long prompt's chunks out of the per-step budget forever.
        if self._prefilling:
            used = self._advance_prefills(remaining)
            if remaining is not None:
                remaining -= used
            # A request satisfied by its final chunk alone (max_new=1,
            # instant EOS) must be noticed before admission/decode.
            self._finish_check(finished)
        while True:
            before = self.stats["prefills"]
            self._fill_slots(remaining)
            if remaining is not None:
                remaining -= self.stats["prefills"] - before
            n_done = len(finished)
            self._finish_check(finished)
            if len(finished) == n_done or (
                remaining is not None and remaining <= 0
            ):
                break
        if self._prefilling and (remaining is None or remaining > 0):
            # Chunked prompts admitted THIS step start their first
            # chunk immediately instead of idling a full decode window.
            self._advance_prefills(remaining)
            self._finish_check(finished)
        if self.stats["prefills"] + self.stats["prefill_chunks"] > prefills0:
            # Prefill-section wall time (the prefill/chunk programs this
            # step ran, including their host syncs) — observed only on
            # steps that actually prefilled.
            self.obs.prefill_seconds.observe(time.perf_counter() - t_fill0)
        # Admission phase: the fill section minus the prefill program
        # dispatches and any inline (non-overlapped) settles it ran
        # (queue pops, slot prep, finish checks in the loop).
        self._phase_s["admission"] = max(
            0.0,
            time.perf_counter() - t_fill0
            - self._phase_s.get("prefill_dispatch", 0.0)
            - (self._phase_s.get("prefill_settle", 0.0) - settle_fill0),
        )
        active_rows = self._active_rows()
        if any(active_rows) and not self._windows:
            self.obs.occupancy.observe(sum(active_rows) / self.n_slots)
            if self.overlap_decode:
                # Pipeline warm-up (or re-fill after an idle/abort
                # gap): dispatch and leave in flight; the next step
                # settles it.
                self._dispatch_window(active_rows)
            else:
                # Strict ordering: dispatch and sync within the step.
                pairs = [(i, self._slots[i])
                         for i in range(self.n_slots) if active_rows[i]]
                sync0 = self._sync_block_s
                per_slot, per_lps, per_tl = (
                    self._decode_tokens(active_rows)
                )
                self._phase_s["decode_sync"] = (
                    self._phase_s.get("decode_sync", 0.0)
                    + self._sync_block_s - sync0
                )
                t_settle0 = time.perf_counter()
                self._apply_pairs(pairs, per_slot, per_lps, per_tl)
                self._finish_check(finished)
                self._phase_s["settle"] = (
                    self._phase_s.get("settle", 0.0)
                    + time.perf_counter() - t_settle0
                )
                synced = True
        self._observe_cache_gauges()
        if synced:
            # Host overhead this step: wall time minus the time spent
            # blocked awaiting decode-window results — the part of the
            # tick the device cannot see and overlap exists to hide.
            self.obs.host_overhead.observe(max(
                0.0,
                time.perf_counter() - t_step0 - self._sync_block_s,
            ))
        self._observe_step_phases(t_step0, synced, finished, prefills0,
                                  settled_prefills)
        return finished

    def _observe_step_phases(self, t_step0: float, synced: bool,
                             finished, prefills0: int,
                             settled_prefills: bool = False) -> None:
        """Deposit this step's phase attribution (obs.STEP_PHASES) —
        only for steps that did work (synced a window, ran or settled
        a prefill, or finished a request): a server's idle polling
        steps would otherwise drown the distributions in zeros.
        host_bookkeeping is the remainder, so the six _sum series add
        up to the step loop's non-idle wall time."""
        did_work = synced or settled_prefills or bool(finished) or (
            self.stats["prefills"] + self.stats["prefill_chunks"]
            > prefills0
        )
        if not did_work or not self.obs.registry.enabled:
            return
        attributed = 0.0
        for phase in ("admission", "prefill_dispatch", "prefill_settle",
                      "decode_sync", "settle"):
            v = self._phase_s.get(phase, 0.0)
            attributed += v
            self.obs.step_phase.labels(phase=phase).observe(v)
        self.obs.step_phase.labels(phase="host_bookkeeping").observe(
            max(0.0, time.perf_counter() - t_step0 - attributed)
        )

    # ---- decode-window dispatch / settle ----------------------------

    def _active_rows(self) -> List[bool]:
        """Slots a decode window should advance right now (occupied,
        not mid-chunked-prefill, not awaiting an overlapped prefill
        settle, not frozen awaiting migration)."""
        pending = (self._pending_prefill_slots() if self._pflights
                   else ())
        return [
            r is not None and i not in self._prefilling
            and i not in pending and not r.prefill_only
            and not r.frozen
            for i, r in enumerate(self._slots)
        ]

    def _inflight_advance(self) -> Dict[int, int]:
        """Tokens the un-synced window(s) will have appended to each
        still-current request by the time they settle: a continuing
        request always accepts the full window (anything less means it
        finished, and then the projection is discarded with the slot),
        so the host can project len(out) forward WITHOUT syncing —
        the fact that makes overlapped gen0/length bookkeeping exact."""
        adv: Dict[int, int] = {}
        for w in self._windows:
            for slot, req in w.pairs:
                if self._slots[slot] is req:
                    adv[slot] = adv.get(slot, 0) + w.ticks
        return adv

    def _dispatch_window(self, active_rows) -> _DecodeWindow:
        """Dispatch ONE jitted decode window asynchronously and record
        it in the flight queue. No host sync happens here — jax returns
        the outputs as futures, and every per-slot device vector is
        rebound from them so admissions/releases that run before the
        sync compose in dispatch order."""
        if self._decode is None:
            impl = (self._decode_impl_pp if self.pp_pipeline
                    else self._decode_impl)
            self._decode = self._jit_cache_program(
                impl, 10,
                static_argnames=("greedy_only", "use_bias", "use_pen",
                                 "use_seed", "use_con"),
            )
        adv = self._inflight_advance()
        self._pre_decode(active_rows, adv)
        active = jnp.asarray(active_rows)
        self._key, sub = jax.random.split(self._key)
        greedy_only = all(
            r is None or r.temperature == 0.0 for r in self._slots
        )
        use_pen = any(self._slot_pen)
        if self._con_dirty:
            self._rebuild_constraints()
        use_con = self._ctrans is not None
        counts = (self._scounts if use_pen else self._zero_bias_row)
        # Generated-token counts at the window's start: host-known
        # len(out), projected past any window still in flight.
        gen0 = jnp.asarray(
            [len(r.out) + adv.get(i, 0) if r is not None else 0
             for i, r in enumerate(self._slots)],
            jnp.int32,
        )
        # Unconstrained steps pass the shared dummy table so the arg
        # tree keeps its structure without holding a real table alive.
        ctrans = self._ctrans if use_con else self._dummy_ctrans
        (self._cache, toks, lps, self._smin, counts, cstate,
         tlvs, tlis, self._srem, self._sdone, acts) = self._decode(
            self.params, self._cache, self._cur, active, sub,
            (self._stemp, self._stopk, self._stopp, self._sminp,
             self._sbias if self._sbias is not None
             else self._zero_bias_row, self._smin,
             self._spres, self._sfreq, counts,
             self._sseed, gen0, ctrans, self._coff, self._cstate,
             self._srem, self._sdone),
            greedy_only=greedy_only,
            use_bias=self._sbias is not None and any(
                b is not None for b in self._slot_bias
            ),
            use_pen=use_pen,
            use_seed=any(
                r is not None and r.seed is not None for r in self._slots
            ),
            use_con=use_con,
        )
        if use_pen:
            self._scounts = counts
        if use_con:
            self._cstate = cstate
        self._cur = toks[-1]
        w = _DecodeWindow(
            pairs=[(i, self._slots[i])
                   for i in range(self.n_slots) if active_rows[i]],
            ticks=self.decode_ticks,
            arrays=(toks, lps, tlvs, tlis, acts),
        )
        self._windows.append(w)
        for slot, req in w.pairs:
            if req.trace is not None:
                # Dispatch half of the overlap pipeline: recorded per
                # request so a timeline shows every window the request
                # rode, with the in-flight depth at dispatch.
                req.trace.record("window-dispatch", src="engine",
                                 rid=req.rid, slot=slot, ticks=w.ticks,
                                 depth=len(self._windows))
        if self._window_hooks is not None:
            self._window_hooks.on_dispatch(w)
        return w

    def _sync_window(self, w: _DecodeWindow):
        """THE host sync: pull a dispatched window's packed results
        (tokens, validity flags, logprob sidecars — one transfer) and
        slice each slot's valid prefix. Returns (tokens, logprobs,
        top-K alternatives) keyed by slot."""
        t0 = time.perf_counter()
        if self._window_hooks is not None:
            self._window_hooks.before_sync(w)
        host_toks, host_lps, host_tlv, host_tli, host_acts = (
            jax.device_get(w.arrays)  # shellac: ignore[SH002] — the decode window's ONE packed sync; everything the host needs arrives in this single transfer
        )
        t1 = time.perf_counter()
        self._sync_block_s += t1 - t0
        # Window wall time, dispatch to results-on-host: under
        # overlapped dispatch this spans the host work interleaved with
        # the window — the overlapped reality, not the serial span.
        self.obs.decode_window_seconds.observe(t1 - w.t_dispatch)
        # Device-side stop decisions arrive as per-tick validity flags;
        # valid ticks are a prefix (done is sticky), so each slot's
        # token list is a slice, not a scan.
        n_valid = host_acts.sum(axis=0)
        per_slot = [host_toks[:n_valid[i], i].tolist()
                    for i in range(self.n_slots)]
        if not self.logprobs:
            return per_slot, None, None
        per_lps = [host_lps[:n_valid[i], i].tolist()
                   for i in range(self.n_slots)]
        if not self.top_logprobs:
            return per_slot, per_lps, None
        # (ticks, n_slots, K) -> per slot, per valid tick: (ids, lps).
        per_tl = [
            [(host_tli[j, i].tolist(), host_tlv[j, i].tolist())
             for j in range(n_valid[i])]
            for i in range(self.n_slots)
        ]
        return per_slot, per_lps, per_tl

    def _apply_pairs(self, pairs, per_slot, per_lps, per_tl) -> None:
        """Append a window's valid tokens to the requests that owned
        the slots at dispatch. The identity check discards results for
        slots cancelled or re-admitted while the window was in flight
        (overlap), and the per-token break re-checks the host-only
        finish conditions (stop sequences; EOS/budget are pre-cut
        device-side but re-checked as the single source of truth)."""
        for slot, req in pairs:
            if self._slots[slot] is not req or slot in self._prefilling:
                # Cancelled or replaced while the window was in flight:
                # results discarded, and deliberately NO settle event —
                # a cancelled request's timeline ends at its
                # cancellation, never with a stale-slot settle.
                continue
            if req.trace is not None:
                req.trace.record("window-settle", src="engine",
                                 rid=req.rid, slot=slot,
                                 n_tokens=len(per_slot[slot]))
            for j, tok in enumerate(per_slot[slot]):
                req.out.append(int(tok))
                if per_lps is not None:
                    req.lps.append(float(per_lps[slot][j]))
                if per_tl is not None:
                    if req.tlp is None:
                        req.tlp = []
                    req.tlp.append(per_tl[slot][j])
                last = req.out[-1]
                if (self.eos_id is not None and last == self.eos_id) or (
                    len(req.out) >= req.max_new
                ) or req.hit_stop() is not None:
                    # Later window tokens are post-EOS/budget/stop
                    # overshoot; the device froze (EOS/budget) or kept
                    # decoding (stop sequence), and the request never
                    # sees them either way.
                    break

    def _settle_window(self, finished) -> bool:
        """Sync and settle the OLDEST in-flight window; False if none
        was in flight."""
        if not self._windows:
            return False
        w = self._windows.popleft()
        per_slot, per_lps, per_tl = self._sync_window(w)
        self._apply_pairs(w.pairs, per_slot, per_lps, per_tl)
        self._finish_check(finished)
        return True

    def _observe_cache_gauges(self) -> None:
        """Per-step utilization gauges. Host-known values only (slot
        list, host-tracked lengths) — no device reads."""
        obs = self.obs
        if not obs.registry.enabled:
            return
        obs.slots_busy.set(sum(r is not None for r in self._slots))
        obs.queue_depth.set(len(self._queue))
        obs.kv_util.set(self._kv_utilization())

    def _kv_utilization(self) -> float:
        """Live residency / capacity, by the backend's own accounting
        (dense: token counting; paged: pool blocks in use)."""
        return self.cache_backend.utilization()

    def _decode_tokens(self, active_rows):
        """Advance every active slot; returns (tokens_per_slot,
        logprobs_per_slot or None, top-K per slot or None), already cut
        to each slot's valid count, in one host sync. The strict-
        ordering path (dispatch + immediate sync); overridden wholesale
        by the speculative engine."""
        w = self._dispatch_window(active_rows)
        self._windows.pop()  # settled inline, not via the flight queue
        return self._sync_window(w)

    def _pre_decode(self, active_rows, advance=None) -> None:
        """Backend hook before each decode window (paged: grow block
        tables to cover the window's write span). `advance` maps slot
        -> tokens an un-synced in-flight window will still append
        (overlapped dispatch), so length projections stay exact
        without a host sync."""
        self.cache_backend.pre_window(active_rows, advance,
                                      self._window_write_span())

    def release_frozen(self, rid) -> Optional[_Request]:
        """Release a frozen slot (prefill-only OR preempted decode)
        after its export (caller must be the engine-owning thread —
        the same thread that froze it). Returns the request, or None
        for an unknown rid. Device rows need no repair: stale rows are
        self-healing, exactly as on cancel."""
        slot = self.frozen_prefills.pop(rid, None)
        if slot is None:
            slot = self.frozen_decodes.pop(rid, None)
        if slot is None:
            return None
        req = self._slots[slot]
        self._slots[slot] = None
        self._release_slot(slot)
        return req

    def preemptable(self) -> List[Tuple[Any, int, int, int]]:
        """(rid, slot, qos_class, resident_tokens) for every slot a
        preemption could evict right now: occupied, actively decoding
        (not frozen, not prefill-only, not mid-prefill), and carrying
        only state the migration wire format can ship (no compiled
        constraint). resident_tokens is the slot's physical KV
        residency — multiply by the backend's bytes_per_token() for
        the park-bytes cost the victim rule ranks on."""
        pending = (self._pending_prefill_slots() if self._pflights
                   else ())
        out = []
        for i, req in enumerate(self._slots):
            if (req is None or req.prefill_only or req.frozen
                    or i in self._prefilling or i in pending
                    or req.constraint is not None or not req.out):
                continue
            resident = int(req.tokens.size) + max(len(req.out) - 1, 0)
            out.append((req.rid, i, int(req.qos_class), resident))
        return out

    def preempt(self, rid) -> List[Tuple[Any, List[int]]]:
        """Freeze an actively-decoding request in place so the caller
        can export -> park -> release its slot (caller must be the
        engine-owning thread). Mirrors the prefill_only freeze: the
        device row gets its sticky done flag, the host excludes the
        slot from decode windows and _finish_check, and the rid lands
        in frozen_decodes.

        In-flight pipelines (overlapped prefills and decode windows)
        are settled FIRST so the host's `out` and the device KV agree
        at the freeze point — anything that finished while draining is
        returned exactly as step() results, for normal delivery. If
        the target itself finished during the drain, nothing freezes
        and the finished list carries its settlement."""
        finished: List[Tuple[Any, List[int]]] = []
        slot = next((i for i, r in enumerate(self._slots)
                     if r is not None and r.rid == rid), None)
        if slot is None:
            raise ValueError(f"preempt: rid {rid!r} holds no slot")
        req = self._slots[slot]
        if req.prefill_only or req.frozen:
            raise ValueError(f"preempt: rid {rid!r} is already frozen")
        if slot in self._prefilling or (
            self._pflights and slot in self._pending_prefill_slots()
        ):
            raise ValueError(f"preempt: rid {rid!r} is mid-prefill")
        if self._pflights:
            self._settle_prefills()
            self._finish_check(finished)
        while self._windows:
            self._settle_window(finished)
        if self._slots[slot] is not req:
            return finished
        self._sdone = self._sdone.at[slot].set(True)
        req.frozen = True
        self.frozen_decodes[rid] = slot
        self.stats["preemptions"] += 1
        if req.trace is not None:
            req.trace.record("preempt", src="engine", rid=rid,
                             slot=slot, n_out=len(req.out),
                             qos_class=int(req.qos_class))
        return finished

    def cancel(self, rid) -> bool:
        """Drop a queued or in-flight request (caller must be the
        engine-owning thread). Frees its slot immediately; device
        state needs no repair (stale cache rows are self-healing)."""
        for i, req in enumerate(self._slots):
            if req is not None and req.rid == rid:
                self._slots[i] = None
                self._prefilling.pop(i, None)
                self.frozen_prefills.pop(rid, None)
                self.frozen_decodes.pop(rid, None)
                self._release_slot(i)
                self.finished_logprobs.pop(rid, None)
                self.finished_prompt_logprobs.pop(rid, None)
                self.finished_top_logprobs.pop(rid, None)
                self.stats["requests_cancelled"] += 1
                if req.trace is not None:
                    req.trace.abort("cancelled")
                return True
        for req in list(self._queue):
            if req.rid == rid:
                self._queue.remove(req)
                self.stats["requests_cancelled"] += 1
                if req.trace is not None:
                    req.trace.abort("cancelled")
                return True
        return False

    def abort_all(self) -> List[Any]:
        """Drop EVERY queued and in-flight request (caller must be the
        engine-owning thread); returns the dropped rids. The supervisor
        rebuild / multi-host epoch-resync helper: slots release cleanly
        (paged pools get their blocks back), per-slot sampling state
        clears through _release_slot, and stale finished_* deposits are
        swept so a rebuilt server cannot hand a new request an old
        generation's logprobs. Device cache rows need no repair — stale
        rows are self-healing (lengths roll back at the next admit)."""
        # Drain the in-flight decode window(s) and prefill flight(s)
        # first (overlapped dispatch): block until the device finishes
        # and DISCARD the results, so a rebuilt/resynced engine can
        # never mis-attribute a stale window's tokens (or a stale
        # prefill's first token) to a new generation's requests, and
        # the device is quiescent when the caller reuses it. The
        # prefill hooks are deliberately NOT consulted — this is
        # failure-path cleanup, not a measured settle.
        while self._windows:
            jax.device_get(self._windows.popleft().arrays)
        while self._pflights:
            jax.device_get(self._pflights.pop().arrays)
        dropped = []
        for req in self._queue:
            dropped.append(req.rid)
            if req.trace is not None:
                req.trace.abort("cancelled")
        self._queue.clear()
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            dropped.append(req.rid)
            if req.trace is not None:
                req.trace.abort("cancelled")
            self._slots[i] = None
            self._release_slot(i)
        self._prefilling.clear()
        self.frozen_prefills.clear()
        self.frozen_decodes.clear()
        self.finished_logprobs.clear()
        self.finished_prompt_logprobs.clear()
        self.finished_top_logprobs.clear()
        # Backend allocator to canonical pristine state (paged purges
        # prefix registries and rebuilds the free list in constructor
        # order — required for multi-host resync convergence).
        self.cache_backend.reset()
        self.stats["requests_cancelled"] += len(dropped)
        return dropped

    def set_decode_ticks(self, k: int) -> None:
        """Rewrite decode_ticks between windows — the auto-tuner's
        write-back. Invalidates the lazily built decode program (the
        window length is baked into its trace); windows already in
        flight keep the tick count they were dispatched with."""
        k = int(k)
        if k < 1:
            raise ValueError(f"decode_ticks must be >= 1, got {k}")
        if k != self.decode_ticks:
            self.decode_ticks = k
            self._decode = None
        self.stats["decode_ticks"] = k

    def set_prefill_chunk(self, chunk: Optional[int]) -> None:
        """Rewrite prefill_chunk between steps — the prefill
        auto-tuner's write-back (None = whole prompts). The chunk jits
        are keyed by pad bucket, so nothing invalidates; rolling
        backends refuse (their ring slack was sized to the
        construction-time chunk and cannot grow post-hoc)."""
        if chunk is not None:
            chunk = int(chunk)
            if chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {chunk}"
                )
        if self.cache_backend.is_rolling and (
            chunk or 1
        ) > self.cache_backend.chunk_slack:
            raise ValueError(
                f"prefill_chunk={chunk} exceeds the rolling ring's "
                f"construction-time chunk slack "
                f"({self.cache_backend.chunk_slack}); pass "
                "prefill_chunk at construction instead"
            )
        self.prefill_chunk = chunk
        self.stats["prefill_chunk"] = chunk or 0

    @property
    def pending(self) -> int:
        return len(self._queue) + sum(r is not None for r in self._slots)

    def run(self, requests=None) -> Dict[Any, List[int]]:
        """Drain: submit (rid, tokens, max_new) triples, step to empty."""
        for r in requests or ():
            self.submit(*r)
        results: Dict[Any, List[int]] = {}
        while self.pending:
            for rid, out in self.step():
                results[rid] = out
        return results

    # ---- beam search (dense caches) ----------------------------------

    def beam_search(self, prompt_tokens, *, num_beams: int = 4,
                    max_new_tokens: int = 32, eos_id=None,
                    length_penalty: float = 1.0, constraint=None):
        """Deterministic beam decode of ONE prompt on this engine's
        params — the HTTP-facing entry point (server `num_beams`).

        Dense/int8/rolling caches delegate to a lazily built
        single-request Engine SHARING the params (jax arrays are
        immutable, so no copy; the delegate allocates its own
        (num_beams, max_len) cache per call and frees it on return —
        the slot batch is untouched). The paged subclass overrides
        this with its copy-on-write block-table search. Caller must be
        the engine-owning thread, like step()/submit(). `constraint`
        (a compiled constraints.TokenDFA) masks every beam through the
        grammar; invalid beams are pruned."""
        if eos_id is None:
            eos_id = self.eos_id
        if self._beam_delegate is None:
            from shellac_tpu.inference.engine import Engine

            self._beam_delegate = Engine(
                self.cfg, self.params, max_len=self.max_len,
                mesh=self.mesh, kv_quant=self.kv_quant,
                rolling_window=self.rolling_window,
            )
        return self._beam_delegate.beam_search(
            prompt_tokens, num_beams=num_beams,
            max_new_tokens=max_new_tokens, eos_id=eos_id,
            length_penalty=length_penalty, constraint=constraint,
        )


class PagedBatchingEngine(BatchingEngine):
    """Continuous batching over a shared block pool (paged KV cache).

    Dense slots reserve n_slots*max_len tokens of KV whether used or
    not; here slots borrow fixed-size blocks from one pool as they grow
    and return them on completion, so resident KV memory tracks the
    tokens actually alive. `pool_tokens` (default: half the dense
    footprint) is the capacity knob; admission blocks — requests wait in
    queue — when the pool can't cover a prompt.

    Block 0 is reserved scratch: unallocated table entries point at it,
    so out-of-range reads/writes land there and are masked downstream.

    prefix_cache=True adds automatic prefix caching (the public
    PagedAttention/vLLM idea, re-built for this pool): full prompt
    blocks are content-hashed with a position-dependent chain, kept in
    the pool after release (refcounted, LRU-evicted only when the free
    list runs dry), and new prompts attach the longest matching block
    chain read-only — prefill then computes only the unmatched suffix,
    attending over the cached prefix KV through the block table. Shared
    blocks are never rewritten: a slot's writes start at its first
    owned block (the match is capped so at least one prompt token is
    computed, which also yields the last-token logits sampling needs).
    """

    _backend_family = ("paged", "paged-int8")

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        n_slots: int = 8,
        max_len: Optional[int] = None,
        block_size: Optional[int] = None,
        pool_tokens: Optional[int] = None,
        prefix_cache: bool = False,
        cache_backend=None,
        kv_quant: Optional[str] = None,
        **kw,
    ):
        from shellac_tpu.inference.cache import (
            CacheBackend,
            make_backend,
            resolve_backend_name,
        )

        if not isinstance(cache_backend, CacheBackend):
            name = (resolve_backend_name(None, paged=True,
                                         kv_quant=kv_quant)
                    if cache_backend is None else
                    resolve_backend_name(cache_backend,
                                         kv_quant=kv_quant))
            if name not in self._backend_family:
                raise ValueError(
                    f"{type(self).__name__} drives cache backends "
                    f"{self._backend_family}; {name!r} needs a "
                    "different engine class — resolve it through "
                    "inference.cache.engine_class"
                )
            if block_size is None:
                # int8 pools need 32-aligned pages (the grouped-gather
                # kernel's sublane tiling); bf16 keeps the finer 16.
                block_size = 64 if name == "paged-int8" else 16
            chunk = kw.get("prefill_chunk")
            cache_backend = make_backend(
                name, cfg, n_slots, max_len or cfg.max_seq_len,
                block_size=block_size, pool_tokens=pool_tokens,
                prefix_cache=prefix_cache,
                # "auto" resolves to whole prompts until tuned — slack
                # like the untuned case (paged slack is advisory).
                chunk_slack=chunk if isinstance(chunk, int) else 1,
            )
        else:
            # A constructed pool carries its own geometry; engine
            # kwargs that would have shaped a registry-built pool are
            # refused instead of silently dropped (a dropped pool size
            # is a capacity incident).
            if block_size is not None \
                    and block_size != cache_backend.block_size:
                raise ValueError(
                    f"block_size={block_size} conflicts with the "
                    f"{cache_backend.name!r} backend instance "
                    f"(block_size={cache_backend.block_size})"
                )
            if pool_tokens is not None:
                raise ValueError(
                    "pool_tokens cannot reshape a constructed backend "
                    "instance; pass pool_tokens to the backend "
                    "constructor instead"
                )
            if prefix_cache and not cache_backend.prefix_cache:
                raise ValueError(
                    f"prefix_cache=True conflicts with the "
                    f"{cache_backend.name!r} backend instance "
                    "(constructed without prefix_cache)"
                )
        super().__init__(cfg, params, n_slots=n_slots, max_len=max_len,
                         cache_backend=cache_backend, **kw)
        self.block_size = self.cache_backend.block_size
        self.prefix_cache = self.cache_backend.prefix_cache
        self._n_blocks = self.cache_backend.n_blocks
        # Keyed (pad_bucket, want_plp), like the dense _chunk_jit.
        self._prefix_prefill_jit: Dict[Any, Any] = {}
        # Beam-search programs, keyed (s_pad, beams, steps, eos,
        # length_penalty, n_gen) — see beam_search below.
        self._beam_jit: Dict[Any, Any] = {}

    # ---- allocator views --------------------------------------------
    # The PagedBackend owns the allocator state; these forward the
    # historical engine surface for the CoW beam search below, tests,
    # and external callers.

    @property
    def _free(self):
        return self.cache_backend._free

    @property
    def _slot_blocks(self):
        return self.cache_backend._slot_blocks

    @property
    def _hash_to_block(self):
        return self.cache_backend._hash_to_block

    @property
    def _block_ref(self):
        return self.cache_backend._block_ref

    def _evictable(self) -> int:
        return self.cache_backend.evictable()

    def _alloc_block(self) -> int:
        return self.cache_backend.alloc_block()

    def _ensure_blocks(self, slot: int, total_tokens: int) -> bool:
        return self.cache_backend.ensure_blocks(slot, total_tokens)

    def _attach_prefix(self, tokens):
        return self.cache_backend.attach_prefix(tokens)

    def _detach_prefix(self, matched) -> None:
        self.cache_backend.detach_prefix(matched)

    def _observe_cache_gauges(self) -> None:
        super()._observe_cache_gauges()
        if self.prefix_cache and self.obs.registry.enabled:
            self.obs.prefix_blocks.set(len(self._hash_to_block))

    # ---- jitted programs --------------------------------------------
    def _chunk_prefill(self, pad, fresh, tokens, chunk_len, offset, slot,
                       key, samp, boundary_next=None, want_plp=False):
        """Paged chunks reuse the continuation program (a chunk is a
        'suffix' past `offset` resident tokens; offset 0 included).
        Prompt logprobs ride the same stitching contract as the dense
        chunked path: per-chunk in-row scores plus the boundary score
        of the next chunk's first token."""
        jkey = (pad, want_plp)
        if jkey not in self._prefix_prefill_jit:
            self._prefix_prefill_jit[jkey] = self._jit_cache_program(
                functools.partial(
                    self._prefix_prefill_impl, want_plp=want_plp
                ), 6,
            )
        if boundary_next is None:
            boundary_next = jnp.zeros((), jnp.int32)
        return self._prefix_prefill_jit[jkey](
            self.params, self._cache, tokens, chunk_len, offset, slot, key,
            samp, boundary_next,
        )

    def _run_prefill(self, slot: int, req):
        """Prefix-cached prefill: compute only the unmatched suffix;
        returns (first sampled token, its raw logprob)."""
        p = self._prefill_start_offset(slot)
        if p == 0:
            return super()._run_prefill(slot, req)
        suffix = req.tokens[p:]
        s = suffix.size  # >= 1 by the match cap
        # Cap the pad at the table space REMAINING past the prefix:
        # writes start at offset p, and padded positions beyond the
        # table would gather-clamp onto the slot's last real block,
        # corrupting just-written suffix KV (s <= max_len - p always,
        # so the cap never cuts real tokens).
        pad = min(_bucket(s), self.max_len - p)
        padded = np.zeros((1, pad), np.int32)
        padded[0, :s] = suffix
        self._key, sub = jax.random.split(self._key)
        # One dispatch path: the chunk-continuation program IS the
        # suffix prefill (a suffix is a chunk past `p` resident tokens).
        cache, first, lp, _, _, tlv, tli = self._chunk_prefill(
            pad, False, jnp.asarray(padded),
            jnp.asarray([s], jnp.int32), jnp.asarray([p], jnp.int32),
            slot, sub, self._slot_samp(slot, req),
        )
        self._cache = cache
        # No plp payload: submit() refuses prompt_logprobs on
        # prefix-cached engines (the hit skips the scoring passes).
        return (first, lp, ((tlv, tli) if self.top_logprobs else None),
                None)

    def _prefix_prefill_impl(
        self, params, cache, tokens, suffix_len, prefix_len, slot, key,
        samp, boundary_next, *, want_plp: bool = False,
    ):
        """Continue from `prefix_len` cached tokens: a batch-1 view of
        the slot's table row over the shared pool, forwarded with
        fresh_cache=False so the suffix attends to the cached prefix KV
        (and itself) through the table. Suffix K/V writes land in the
        slot's own blocks — shared prefix blocks are upstream of every
        written position, so they stay read-only.

        want_plp returns the same (in-chunk scores, boundary score)
        pair as the dense chunked program, so the base class's
        cross-chunk stitching applies unchanged.

        attn_impl is pinned to "ref": the chunked continuation attends
        over the gathered block view once per request; the flash decode
        kernel targets s<=8 steady-state decode and would only fall
        back (warning) on a prefill-sized s.
        """
        row = jax.lax.dynamic_slice_in_dim(cache.tables, slot, 1, 0)
        if self.kv_quant == "int8":
            view = QuantPagedKVCache(
                k=cache.k, v=cache.v, ks=cache.ks, vs=cache.vs,
                tables=row, lengths=prefix_len.astype(jnp.int32),
            )
        else:
            view = PagedKVCache(
                k=cache.k, v=cache.v, tables=row,
                lengths=prefix_len.astype(jnp.int32),
            )
        logits, view = transformer.forward_with_cache(
            self.cfg, params, tokens, view, new_tokens_len=suffix_len,
            fresh_cache=False, attn_impl="ref", mesh=self.mesh,
        )
        last = jnp.take_along_axis(
            logits, (suffix_len - 1)[:, None, None].astype(jnp.int32), axis=1
        )[0, 0]
        first, first_lp = self._sample_first(key, last, samp)
        plp_within = jnp.zeros((tokens.shape[1],), jnp.float32)
        boundary_lp = jnp.zeros((), jnp.float32)
        if want_plp:
            plp_within = self._plp_within(logits, tokens)
            boundary_lp = jax.nn.log_softmax(
                last.astype(jnp.float32)
            )[boundary_next]
        fields = dict(
            k=view.k, v=view.v,
            lengths=jax.lax.dynamic_update_slice(
                cache.lengths, view.lengths, (slot,)
            ),
        )
        if self.kv_quant == "int8":
            fields.update(ks=view.ks, vs=view.vs)
        cache = cache.replace(**fields)
        tlv, tli = self._first_tl(last)
        return (cache, first, first_lp, plp_within, boundary_lp,
                tlv, tli)

    def _prefill_impl(self, params, cache, tokens, prompt_len, slot, key,
                      samp, want_plp: bool = False):
        """Mini-prefill (dense bf16 or int8+scales, matching the pool's
        kind), then scatter through the slot's table. want_plp scores
        the prompt from the mini-prefill's own logits — identical math
        to the dense engine's whole-prompt scoring."""
        s = tokens.shape[1]
        mini = self._fresh_mini(s)
        logits, mini = transformer.forward_with_cache(
            self.cfg, params, tokens, mini, new_tokens_len=prompt_len,
            fresh_cache=True, attn_impl=self.attn_impl, mesh=self.mesh,
        )
        last = jnp.take_along_axis(
            logits, (prompt_len - 1)[:, None, None].astype(jnp.int32), axis=1
        )[0, 0]
        first, first_lp = self._sample_first(key, last, samp)

        bs = self.block_size
        table_row = jax.lax.dynamic_slice_in_dim(cache.tables, slot, 1, 0)[0]
        pos = jnp.arange(s, dtype=jnp.int32)
        blocks = jnp.take(table_row, pos // bs)
        offs = pos % bs
        # mini.k[:, 0] is (L, Hkv, S, Dh); the pool write below indexes
        # (block, off) at dims 1 and 3 with slices at 0 and 2, so the
        # value wants token rows leading: (S, L, Hkv, Dh).
        k_src = mini.k[:, 0].astype(cache.k.dtype).transpose(2, 0, 1, 3)
        v_src = mini.v[:, 0].astype(cache.v.dtype).transpose(2, 0, 1, 3)
        fields = dict(
            k=cache.k.at[:, blocks, :, offs].set(k_src),
            v=cache.v.at[:, blocks, :, offs].set(v_src),
            lengths=jax.lax.dynamic_update_slice(
                cache.lengths, mini.lengths, (slot,)
            ),
        )
        if self.kv_quant == "int8":
            # The quant mini already quantized at write (K post-rope);
            # its scales scatter through the same (block, off) coords —
            # scale pools are (L, nb, Hkv, bs), value rows (S, L, Hkv).
            fields["ks"] = cache.ks.at[:, blocks, :, offs].set(
                mini.ks[:, 0].transpose(2, 0, 1)
            )
            fields["vs"] = cache.vs.at[:, blocks, :, offs].set(
                mini.vs[:, 0].transpose(2, 0, 1)
            )
        cache = cache.replace(**fields)
        plp = (self._plp_within(logits, tokens) if want_plp
               else jnp.zeros((tokens.shape[1],), jnp.float32))
        tlv, tli = self._first_tl(last)
        return cache, first, first_lp, plp, tlv, tli


    # ---- beam search over the pool (copy-on-write tables) ------------

    def beam_search(self, prompt_tokens, *, num_beams: int = 4,
                    max_new_tokens: int = 32, eos_id=None,
                    length_penalty: float = 1.0, constraint=None):
        """Deterministic beam decode of ONE prompt over the block pool.

        Returns (sequences, scores) — the same contract as
        Engine.beam_search, and bit-identical beams to the dense-cache
        implementation (tests/test_beam_search.py paged cases). A
        compiled `constraint` (constraints.TokenDFA) masks each beam
        through its own DFA state exactly like the dense search — the
        shared beam_expand helper owns the math for both.

        Copy-on-write mechanics (the public vLLM CoW idea, expressed
        functionally so the whole search stays one jitted scan):

          - the prompt prefills ONCE into ceil(s/bs) borrowed blocks
            that every beam's table shares READ-ONLY — prompt blocks
            are never written after prefill, so sharing them is free;
            with prefix_cache=True, a cached block chain covering a
            prompt prefix attaches read-only instead (refcounted for
            the search) and only the unmatched suffix is computed;
          - each beam owns one statically-assigned pool block per
            generated logical block (beams advance in lockstep, so
            block boundaries are crossed together and the assignment
            never collides);
          - on beam reorder the adopting beam copies the winning
            beam's PARTIAL tail block into its own block (one
            block-sized copy per beam per step) and repoints its
            table; SEALED full blocks stay shared through the
            gathered tables — never copied.

        Borrowed blocks come from the engine's allocator (evicting LRU
        prefix-cache blocks when the free list is dry) and return on
        completion, so beam searches and live requests share the pool;
        engine slots' tables/lengths are untouched. int8 pools
        compose (the CoW copy moves the scale pools in lockstep with
        the value pools — same block ids), and so do MLA latent-row
        pools (the latent block copies like any value block; the v
        pool is zero-width): both are bit-identical to their
        dense-cache beams.
        """
        from shellac_tpu.inference.engine import check_beam_constraint

        k_beams = int(num_beams)
        steps = int(max_new_tokens)
        if k_beams < 1:
            raise ValueError("num_beams must be >= 1")
        if steps < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if eos_id is None and constraint is not None:
            eos_id = self.eos_id
        ctrans, eos_id = check_beam_constraint(
            constraint, eos_id, self.cfg.vocab_size
        )
        toks = np.asarray(prompt_tokens, np.int32).reshape(-1)
        s = int(toks.size)
        bs = self.block_size
        if s + steps + 1 > self.max_len:
            raise ValueError(
                f"prompt {s} + max_new {steps} exceeds max_len "
                f"{self.max_len}"
            )
        lb0 = s // bs
        # Owned generated blocks must cover every CoW target: writes
        # land at positions s .. s+steps-2, and the post-reorder CoW
        # additionally targets the NEXT write position, up to
        # s+steps-1.
        n_gen = 0 if steps == 1 else ((s + steps - 1) // bs - lb0 + 1)
        # Prefix caching composes: a cached block chain covering a
        # strict prompt prefix attaches READ-ONLY (refcounted for the
        # search's duration, exactly like a slot attach) and only the
        # unmatched suffix is computed. The match cap leaves >= 1
        # suffix token so the last-token logits exist, which also
        # keeps the beams' CoW tail block a borrowed one.
        matched: List[int] = []
        if self.prefix_cache:
            _, matched = self._attach_prefix(toks)
        m_tokens = len(matched) * bs
        prompt_n = -(-s // bs) - len(matched)
        need = prompt_n + k_beams * n_gen
        if need > len(self._free) + self._evictable():
            self._detach_prefix(matched)
            raise RuntimeError(
                f"paged pool exhausted: beam search needs {need} "
                f"blocks ({prompt_n} suffix-prompt past "
                f"{len(matched)} cached prefix blocks + "
                f"{k_beams}x{n_gen} owned tails); free "
                f"{len(self._free)} + evictable {self._evictable()}"
            )
        if self.prefix_cache:
            # Counted only once the attach is certain, matching the
            # slot path's hit-rate accounting under pool pressure.
            self.stats["prefix_hit_tokens"] += m_tokens
            self.stats["prefix_query_tokens"] += s
        borrowed = [self._alloc_block() for _ in range(need)]
        try:
            prompt_ids = matched + borrowed[:prompt_n]
            gen_ids = np.asarray(
                borrowed[prompt_n:], np.int32
            ).reshape(n_gen, k_beams)
            mb = self._cache.max_blocks
            row = np.zeros((mb,), np.int32)
            row[:len(prompt_ids)] = prompt_ids
            tables0 = np.tile(row, (k_beams, 1))
            # Only the suffix past the matched prefix is computed. The
            # pad caps at the table space past the prefix: unclamped
            # pads would gather-clamp onto the row's LAST entry and,
            # when the prompt fills the whole table, cycle garbage
            # into real just-written positions (same hazard
            # _run_prefill's cap guards).
            s_suf = s - m_tokens
            s_pad = min(_bucket(s_suf), self.max_len - m_tokens)
            tokens_pad = np.zeros((1, s_pad), np.int32)
            tokens_pad[0, :s_suf] = toks[m_tokens:]
            jit_key = (s_pad, k_beams, steps, eos_id,
                       float(length_penalty), n_gen, m_tokens > 0,
                       ctrans is not None)
            pool_fields = kv_field_names(self.kv_quant)
            fn = self._beam_jit.get(jit_key)
            if fn is None:
                impl = functools.partial(
                    self._beam_paged_impl, steps=steps, eos_id=eos_id,
                    length_penalty=float(length_penalty),
                    has_prefix=m_tokens > 0,
                )
                jit_kw = {}
                if self._cache_sh is not None:
                    jit_kw["out_shardings"] = (
                        tuple(getattr(self._cache_sh, f)
                              for f in pool_fields),
                        None, None, None,
                    )
                fn = jax.jit(impl, **jit_kw)
                self._beam_jit[jit_key] = fn
            pools, out, norm, lens = fn(
                self.params,
                tuple(getattr(self._cache, f) for f in pool_fields),
                jnp.asarray(tokens_pad),
                jnp.full((1,), s, jnp.int32),
                jnp.full((1,), s_suf, jnp.int32),
                jnp.full((1,), m_tokens, jnp.int32),
                jnp.asarray(tables0), jnp.asarray(gen_ids),
                jnp.int32(lb0), ctrans,
            )
            self._cache = self._cache.replace(
                **dict(zip(pool_fields, pools))
            )
            out, norm, lens = jax.device_get((out, norm, lens))
        finally:
            self._free.extend(borrowed)
            self._detach_prefix(matched)
        from shellac_tpu.inference.engine import beam_filter_invalid

        return beam_filter_invalid(out, norm, lens)

    def _beam_paged_impl(self, params, pools, tokens, prompt_len,
                         suffix_len, prefix_len, tables0, gen_ids, lb0,
                         ctrans=None, *, steps, eos_id, length_penalty,
                         has_prefix=False):
        """Device side of beam_search: prefill once through the shared
        prompt table row, then the dense beam loop with table-gather
        reordering + CoW tail copies instead of cache-row gathers.

        `pools` is (k, v) for bf16 pools or (k, v, ks, vs) for int8
        pools — every array has the block axis at dim 1, so the CoW
        copy and prefill scatter treat them uniformly and the scale
        pools stay in lockstep with the values by construction.

        has_prefix: a cached block chain covers the first prefix_len
        prompt tokens read-only; `tokens` holds only the suffix, which
        forwards as a continuation through the table view (the same
        idiom as _prefix_prefill_impl) and attends to the cached
        prefix KV."""
        cfg = self.cfg
        quant = len(pools) == 4
        k_beams, _ = tables0.shape
        bs = pools[0].shape[3]
        ak = jnp.arange(k_beams)
        mini_fields = kv_field_names(self.kv_quant)

        def make_cache(pools, tables, lengths):
            if quant:
                return QuantPagedKVCache(
                    k=pools[0], v=pools[1], ks=pools[2], vs=pools[3],
                    tables=tables, lengths=lengths,
                )
            return PagedKVCache(k=pools[0], v=pools[1], tables=tables,
                                lengths=lengths)

        s_pad = tokens.shape[1]
        if has_prefix:
            # Suffix continuation through the pool view: writes land
            # in the borrowed prompt blocks past the cached prefix,
            # which stays read-only upstream of every written position.
            view = make_cache(pools, tables0[:1],
                              prefix_len.astype(jnp.int32))
            logits, view = transformer.forward_with_cache(
                cfg, params, tokens, view, new_tokens_len=suffix_len,
                fresh_cache=False, attn_impl="ref", mesh=self.mesh,
            )
            pools = tuple(getattr(view, f) for f in mini_fields)
            last = jnp.take_along_axis(
                logits,
                (suffix_len - 1)[:, None, None].astype(jnp.int32),
                axis=1,
            )[0, 0]
        else:
            # Whole-prompt prefill: mini of the pool's kind once,
            # scattered through the shared prompt blocks (same math as
            # the engine's paged prefill). Pad positions write garbage
            # at tail offsets >= s%bs — overwritten by the beams' own
            # tokens before any read reaches them.
            mini = self._fresh_mini(s_pad)
            logits, mini = transformer.forward_with_cache(
                cfg, params, tokens, mini, new_tokens_len=prompt_len,
                fresh_cache=True, attn_impl=self.attn_impl,
                mesh=self.mesh,
            )
            last = jnp.take_along_axis(
                logits,
                (prompt_len - 1)[:, None, None].astype(jnp.int32),
                axis=1,
            )[0, 0]
            pos = jnp.arange(s_pad, dtype=jnp.int32)
            blocks = jnp.take(tables0[0], pos // bs)
            offs = pos % bs
            scattered = []
            for pool, f in zip(pools, mini_fields):
                src = getattr(mini, f)[:, 0].astype(pool.dtype)
                # Value pools are (L, nb, H, bs, Dh), scale pools
                # (L, nb, H, bs): token rows lead after the transpose.
                src = (src.transpose(2, 0, 1, 3) if src.ndim == 4
                       else src.transpose(2, 0, 1))
                scattered.append(pool.at[:, blocks, :, offs].set(src))
            pools = tuple(scattered)

        from shellac_tpu.inference.engine import (
            beam_expand,
            beam_first_expand,
            beam_rank,
        )

        scores, beam0, tok0, cstate0 = beam_first_expand(
            last, k_beams, ctrans, eos_id
        )
        tables = tables0[beam0]  # rows identical; kept for symmetry
        finished0 = ((tok0 == eos_id) if eos_id is not None
                     else jnp.zeros((k_beams,), bool))
        out0 = jnp.zeros((k_beams, steps), jnp.int32).at[:, 0].set(tok0)
        lens0 = jnp.ones((k_beams,), jnp.int32)
        lengths0 = jnp.broadcast_to(
            prompt_len.astype(jnp.int32), (k_beams,)
        )

        if steps == 1:
            out, norm, lens = beam_rank(scores, out0, lens0,
                                        length_penalty)
            return pools, out, norm, lens

        def scratch_frozen(tables, finished):
            # A frozen beam's cache is dead weight: its logits are
            # replaced by the frozen EOS distribution and no live beam
            # can ever adopt it (finished persists through adoption).
            # Point its WHOLE table at scratch block 0 so its EOS
            # refeed writes land there instead of in a real block —
            # a frozen beam is parked at an old position, and writing
            # through a sealed (shared) block would corrupt live
            # lineages that still read it.
            return jnp.where(finished[:, None], 0, tables)

        def cow(pools, tables, lengths, live):
            # Own the tail block each LIVE beam is about to write: copy
            # the (possibly shared) partial tail into the beam's
            # statically assigned block and repoint its table entry.
            # Live beams advance in lockstep, so `lb` is uniform across
            # them and the (crossing, slot) assignment never reuses a
            # block a sealed table still references; frozen beams are
            # excluded (their lb is stale) and no-op via scratch.
            lb = lengths // bs
            j = jnp.clip(lb - lb0, 0, gen_ids.shape[0] - 1)
            owned = jnp.where(live, gen_ids[j, ak], 0)
            src = jnp.where(live, tables[ak, lb], 0)
            pools = tuple(p.at[:, owned].set(p[:, src]) for p in pools)
            tables = tables.at[ak, lb].set(
                jnp.where(live, owned, tables[ak, lb])
            )
            return pools, tables

        tables = scratch_frozen(tables, finished0)
        pools, tables = cow(pools, tables, lengths0, ~finished0)

        # Named beam_step (not `step`): the module-local lint evidence
        # for scan bodies keys on the NAME, and calling this `step`
        # would mark the host-side engine step() as traced too.
        def beam_step(carry, _):
            (pools, tables, cur, scores, finished, out, lens,
             lengths, cstate, i) = carry
            cache = make_cache(pools, tables, lengths)
            logits, cache = transformer.forward_with_cache(
                cfg, params, cur[:, None], cache,
                attn_impl=self.attn_impl, mesh=self.mesh,
            )
            pools = tuple(getattr(cache, f) for f in mini_fields)
            lengths = cache.lengths
            (scores, beam, tok, out, lens, finished, was_done,
             cstate) = beam_expand(
                logits[:, 0], scores, finished, out, lens, i, eos_id,
                ctrans, cstate,
            )
            tables = tables[beam]
            lengths = lengths[beam]
            # A frozen beam must not grow its cache: the forward wrote
            # its EOS refeed — roll the length back (same as dense).
            lengths = jnp.where(was_done, lengths - 1, lengths)
            tables = scratch_frozen(tables, finished)
            pools, tables = cow(pools, tables, lengths, ~finished)
            return (pools, tables, tok, scores, finished, out, lens,
                    lengths, cstate, i + 1), None

        carry = (pools, tables, tok0, scores, finished0, out0, lens0,
                 lengths0, cstate0, jnp.int32(1))
        (pools, _, _, scores, _, out, lens, _, _, _), _ = jax.lax.scan(
            beam_step, carry, None, length=steps - 1
        )
        out, norm, lens = beam_rank(scores, out, lens, length_penalty)
        return pools, out, norm, lens


# Backward-compatible alias: the exception moved to the cache
# subsystem with the allocator that raises it.
_PoolExhausted = PoolExhausted
