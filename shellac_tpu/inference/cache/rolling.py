"""Rolling (ring-buffer) backend for sliding-window models.

Storage scales with the WINDOW, not the context: each slot's row is a
ring of window + chunk-slack positions and old positions overwrite in
place (layout.RollingKVCache). Patterned local/global stacks get the
mixed cache (rings for "window" layers, dense rows for "full" layers)
automatically — init_cache_for routes by cfg.attn_pattern. kv_quant
composes on both.

Utilization stays token-based but capacity counts what a slot can
actually HOLD resident — min(max_len, ring) per windowed layer does
not change the engine-facing number because lengths still count total
positions seen; the gauge reports live/|slots x max_len| like the
dense backend so the serving tier's load scores stay comparable
across backends.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.cache.base import CacheBackend
from shellac_tpu.inference.cache.layout import (
    cache_logical_axes_for,
    init_cache_for,
    rolling_ring,
)


class RollingBackend(CacheBackend):
    name = "rolling"
    is_rolling = True

    def __init__(self, cfg: ModelConfig, n_slots: int, max_len: int, *,
                 kv_quant: Optional[str] = None, chunk_slack: int = 1):
        super().__init__(cfg, n_slots, max_len, kv_quant=kv_quant,
                         chunk_slack=chunk_slack)
        if cfg.attn_window is None:
            raise ValueError(
                "rolling_window needs a sliding-window model "
                "(attn_window)"
            )
        if kv_quant == "int8":
            self.name = "rolling-int8"

    def init_cache(self):
        return init_cache_for(
            self.cfg, self.n_slots, self.max_len, self.kv_quant,
            rolling=True, chunk_slack=self.chunk_slack,
        )

    def init_mini(self, length: int):
        return init_cache_for(
            self.cfg, 1, length, self.kv_quant,
            rolling=True, chunk_slack=self.chunk_slack,
        )

    def logical_axes(self):
        return cache_logical_axes_for(self.cfg, self.kv_quant,
                                      rolling=True)

    def utilization(self) -> float:
        return sum(self._slot_tokens()) / (self.n_slots * self.max_len)

    def residency(self) -> Dict[str, Any]:
        return {
            "backend": self.name,
            "slot_tokens": self._slot_tokens(),
            "capacity_tokens": self.n_slots * self.max_len,
            "ring": rolling_ring(self.cfg, self.max_len,
                                 self.chunk_slack),
        }
