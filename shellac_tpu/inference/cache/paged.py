"""Paged block-pool backend: slots borrow fixed-size blocks as they
grow and return them on completion, so resident KV memory tracks the
tokens actually alive instead of n_slots x max_len worst case.

All ALLOCATOR state lives here — free list, per-slot block lists, the
prefix-cache hash registry and refcounts — while the device side only
ever sees the block tables the backend writes into the engine's cache
pytree. Block 0 is reserved scratch: unallocated table entries point at
it, so stray writes/reads through them land harmlessly and are masked
downstream.

prefix_cache=True adds automatic prefix caching (the public
PagedAttention/vLLM idea): full prompt blocks are content-hashed with a
position-dependent chain, kept pooled after release (refcounted,
LRU-evicted only when the free list runs dry), and new prompts attach
the longest matching chain read-only — prefill then computes only the
unmatched suffix.

QuantPagedBackend stores the pool int8 with per-token fp32 scale pools
that mirror the value pools block-for-block, so ONE allocator run
covers both and nothing here changes.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference import prefix as prefix_mod
from shellac_tpu.inference.cache.base import CacheBackend, PoolExhausted
from shellac_tpu.inference.cache.layout import (
    init_cache_for,
    init_paged_cache,
    init_quant_paged_cache,
    paged_cache_logical_axes,
    quant_paged_cache_logical_axes,
)


class PagedBackend(CacheBackend):
    name = "paged"
    is_paged = True

    def __init__(self, cfg: ModelConfig, n_slots: int, max_len: int, *,
                 kv_quant: Optional[str] = None, block_size: int = 16,
                 pool_tokens: Optional[int] = None,
                 prefix_cache: bool = False, chunk_slack: int = 1):
        super().__init__(cfg, n_slots, max_len, kv_quant=kv_quant,
                         chunk_slack=chunk_slack)
        if kv_quant == "int8":
            if block_size % 32:
                # The int8 grouped-gather kernel lands each page at
                # sublane offset g*bs of its VMEM tile; int8's native
                # (32, 128) tiling makes 32 the alignment unit. An
                # engine knob, so an error beats a per-tick fallback
                # warning.
                raise ValueError(
                    f"kv_quant='int8' paged pools need block_size % 32 "
                    f"== 0 (got {block_size}); use 32 or 64"
                )
            self.name = "paged-int8"
        self.block_size = block_size
        self.prefix_cache = prefix_cache
        self.max_blocks_per_slot = -(-max_len // block_size)
        if pool_tokens is None:
            pool_tokens = n_slots * max_len // 2
        self.n_blocks = max(
            -(-pool_tokens // block_size), self.max_blocks_per_slot
        ) + 1
        self._free: List[int] = list(range(self.n_blocks - 1, 0, -1))
        self._slot_blocks: List[List[int]] = [[] for _ in range(n_slots)]
        # Prefix cache state (all host-side; empty when disabled):
        # hash -> block id, insertion/touch-ordered so the front is
        # LRU; _block_ref counts slots currently attached to a cached
        # block (membership also marks "cached": release keeps these
        # pooled instead of freeing them); ref == 0 means evictable.
        self._hash_to_block: "OrderedDict[bytes, int]" = OrderedDict()
        self._block_ref: Dict[int, int] = {}
        self._slot_prefix_len: List[int] = [0] * n_slots
        # Registrations deferred until the slot's prefill completes
        # (the blocks hold garbage until then):
        # slot -> [(idx, hash, parent_hash)].
        self._pending_reg: Dict[int, List] = {}
        # Fabric/directory state (host-side, prefix_cache only): chain
        # links child -> parent (b"" roots a chain), per-hash chain
        # depth and last-touch stamps, per-hash attach hit counters
        # keyed by the LAST MATCHED hash of each attach (for the
        # shared-system-prompt shape that is exactly the hot shared
        # prefix's tip), and a monotonic version the /kv/prefixes
        # delta-poll compares against.
        self._hash_parent: Dict[bytes, bytes] = {}
        self._hash_depth: Dict[bytes, int] = {}
        self._hash_touch: Dict[bytes, float] = {}
        self._prefix_hits: Dict[bytes, int] = {}
        self._prefix_version = 0

    # ---- device cache construction ----------------------------------

    def init_cache(self):
        init_pool = (init_quant_paged_cache if self.kv_quant == "int8"
                     else init_paged_cache)
        return init_pool(self.cfg, self.n_slots, self.n_blocks,
                         self.block_size, self.max_blocks_per_slot)

    def init_mini(self, length: int):
        # Prefill computes into a DENSE mini of the pool's kind, then
        # the engine's prefill program scatters it through the slot's
        # block table.
        return init_cache_for(self.cfg, 1, length, self.kv_quant)

    def logical_axes(self):
        if self.kv_quant == "int8":
            return quant_paged_cache_logical_axes(self.cfg)
        return paged_cache_logical_axes(self.cfg)

    # ---- allocator ---------------------------------------------------

    def initial_stats(self) -> Dict[str, int]:
        if not self.prefix_cache:
            return {}
        return {
            "prefix_hit_tokens": 0,
            "prefix_query_tokens": 0,
            "prefix_evictions": 0,
            "prefix_seeded_blocks": 0,
        }

    def evictable(self) -> int:
        return sum(1 for r in self._block_ref.values() if r == 0)

    def alloc_block(self) -> int:
        """Pop a free block, evicting the LRU unreferenced cached block
        when the free list is dry. Caller checks capacity first."""
        if self._free:
            return self._free.pop()
        for h, blk in self._hash_to_block.items():  # front = LRU
            if self._block_ref[blk] == 0:
                del self._hash_to_block[h]
                del self._block_ref[blk]
                self._prune_hash(h)
                self.engine.stats["prefix_evictions"] += 1
                return blk
        raise RuntimeError("alloc_block called with no capacity")

    def _prune_hash(self, h: bytes) -> None:
        """Drop fabric sidecar state for an evicted hash. The parent
        LINK of surviving children is left in place on purpose: a
        child whose ancestor was evicted is unreachable through
        _match_prefix (the walk starts at the root), and chain_blocks
        refuses it loudly — pruning links would instead silently
        re-root a mid-chain block at the wrong position."""
        self._hash_parent.pop(h, None)
        self._hash_depth.pop(h, None)
        self._hash_touch.pop(h, None)
        self._prefix_hits.pop(h, None)
        self._prefix_version += 1

    def ensure_blocks(self, slot: int, total_tokens: int) -> bool:
        """Grow slot's table to cover total_tokens; False if pool
        empty."""
        eng = self.engine
        need = -(-total_tokens // self.block_size)
        have = len(self._slot_blocks[slot])
        if need <= have:
            return True
        if need - have > len(self._free) + self.evictable():
            return False
        new_ids = [self.alloc_block() for _ in range(need - have)]
        self._slot_blocks[slot].extend(new_ids)
        idx = jnp.arange(have, need, dtype=jnp.int32)
        tables = eng._cache.tables.at[slot, idx].set(
            jnp.asarray(new_ids, jnp.int32)
        )
        eng._cache = eng._cache.replace(tables=tables)
        return True

    # ---- prefix cache ------------------------------------------------

    def chain_hashes(self, tokens: np.ndarray) -> List[bytes]:
        """Position-dependent content hashes of the full token blocks
        (see shellac_tpu.inference.prefix.chain_hashes — shared with
        the tier's directory matcher so routing and cache contents key
        identically by construction)."""
        return prefix_mod.chain_hashes(tokens, self.block_size)

    def _match_prefix(self, tokens: np.ndarray) -> Tuple[List[bytes], int]:
        """Longest cached block chain covering a strict prompt prefix
        (shared by slot admission and beam search)."""
        hashes = self.chain_hashes(tokens)
        # Cap: at least one prompt token must be computed (its logits
        # seed sampling; full-match reuse would leave none).
        cap = (tokens.size - 1) // self.block_size
        m = 0
        for h in hashes[:cap]:
            if h not in self._hash_to_block:
                break
            m += 1
        return hashes, m

    def attach_prefix(self, tokens: np.ndarray):
        """Match + attach the longest cached chain READ-ONLY: bumps
        refcounts and touches LRU order. Returns (hashes, matched
        block ids). Callers own the hit-rate stats (count them only
        once the attach is certain) and roll back a failed attach via
        detach_prefix — shared by slot admission and beam search so
        the attach protocol cannot drift between them."""
        hashes, m = self._match_prefix(tokens)
        matched = [self._hash_to_block[h] for h in hashes[:m]]
        now = time.time()
        for h, blk in zip(hashes[:m], matched):
            self._block_ref[blk] += 1
            self._hash_to_block.move_to_end(h)  # LRU touch
            self._hash_touch[h] = now
        if m:
            # Hit counters key on the last matched hash: under the
            # shared-system-prompt shape that is the tip of the shared
            # prefix, which is exactly the chain replication ships.
            tip = hashes[m - 1]
            self._prefix_hits[tip] = self._prefix_hits.get(tip, 0) + 1
            self._prefix_version += 1
        return hashes, matched

    def detach_prefix(self, matched) -> None:
        for blk in matched:
            self._block_ref[blk] -= 1

    # ---- slot lifecycle ---------------------------------------------

    def prepare_slot(self, slot: int, req, footprint: int) -> None:
        # Reserve the FULL footprint (prompt + generation budget +
        # engine slack) at admission: growth mid-decode could exhaust
        # the pool and there is no good victim to evict at that point.
        eng = self.engine
        if not self.prefix_cache:
            if not self.ensure_blocks(slot, footprint):
                raise PoolExhausted()
            return

        hashes, matched = self.attach_prefix(req.tokens)
        m = len(matched)
        if matched:
            self._slot_blocks[slot] = list(matched)
            tables = eng._cache.tables.at[
                slot, jnp.arange(m, dtype=jnp.int32)
            ].set(jnp.asarray(matched, jnp.int32))
            eng._cache = eng._cache.replace(tables=tables)
        if not self.ensure_blocks(slot, footprint):
            # Roll back the attach (blocks stay cached) and requeue.
            self.detach_prefix(matched)
            self._slot_blocks[slot] = []
            row = jnp.zeros((eng._cache.max_blocks,), jnp.int32)
            eng._cache = eng._cache.replace(
                tables=eng._cache.tables.at[slot].set(row)
            )
            raise PoolExhausted()
        # The slot's own full prompt blocks become matchable only once
        # prefill has actually written them — with chunked prefill that
        # is several steps away, and registering early would let a
        # concurrent same-prefix admission attend over unwritten KV.
        # Stash the registrations; on_prefill_complete flushes them.
        self._pending_reg[slot] = [
            (j, hashes[j], hashes[j - 1] if j else b"")
            for j in range(m, req.tokens.size // self.block_size)
        ]
        self._slot_prefix_len[slot] = m * self.block_size
        eng.stats["prefix_hit_tokens"] += m * self.block_size
        eng.stats["prefix_query_tokens"] += req.tokens.size

    def on_prefill_complete(self, slot: int) -> None:
        # The prompt blocks now hold real KV: make them matchable.
        registered = False
        now = time.time()
        for j, h, parent in self._pending_reg.pop(slot, ()):
            if h in self._hash_to_block:
                continue  # identical chain cached by an earlier finisher
            blk = self._slot_blocks[slot][j]
            self._hash_to_block[h] = blk
            self._block_ref[blk] = 1
            self._hash_parent[h] = parent
            self._hash_depth[h] = j + 1
            self._hash_touch[h] = now
            registered = True
        if registered:
            self._prefix_version += 1

    def release_slot(self, slot: int) -> None:
        eng = self.engine
        self._pending_reg.pop(slot, None)
        if self.prefix_cache:
            for blk in self._slot_blocks[slot]:
                if blk in self._block_ref:
                    # Stays cached, evictable at refcount 0.
                    self._block_ref[blk] -= 1
                else:
                    self._free.append(blk)
        else:
            self._free.extend(reversed(self._slot_blocks[slot]))
        self._slot_blocks[slot] = []
        self._slot_prefix_len[slot] = 0
        row = jnp.zeros((eng._cache.max_blocks,), jnp.int32)
        eng._cache = eng._cache.replace(
            tables=eng._cache.tables.at[slot].set(row)
        )

    def pre_window(self, active_rows, advance, span: int) -> None:
        # Backstop only — admission already reserved the full
        # footprint. Lengths are tracked on host (prompt + generated so
        # far, projected past any un-synced in-flight window via
        # `advance`): no device sync in the serving hot loop. A window
        # can write up to `span` positions before the host intervenes;
        # anything past the request's own footprint lands in scratch
        # block 0 (post-finish overshoot), so the reservation is capped
        # at the footprint.
        eng = self.engine
        for i, active in enumerate(active_rows):
            if not active:
                continue
            req = eng._slots[i]
            length = (req.tokens.size + len(req.out)
                      + (advance.get(i, 0) if advance else 0))
            need = min(
                length + span,
                eng._slot_footprint(req),
            )
            if not self.ensure_blocks(i, need):
                raise RuntimeError(
                    "paged KV pool exhausted mid-decode; size "
                    "pool_tokens for n_slots concurrent worst-case "
                    "lengths"
                )

    def prefill_offset(self, slot: int) -> int:
        return self._slot_prefix_len[slot] if self.prefix_cache else 0

    def reset(self) -> None:
        """abort_all: reset the allocator to its canonical pristine
        state — prefix-cache registries purged and the free list
        rebuilt in constructor order. Keeping cached prefix blocks
        (the normal release behavior) would be a correctness bug on
        the multi-host resync path: replicas abort AFTER diverging, so
        their registries/free lists differ, and a later prompt would
        prefix-hit on one host but miss on another — different-shaped
        programs, wedged collective all over again."""
        self._hash_to_block.clear()
        self._block_ref.clear()
        self._pending_reg.clear()
        self._hash_parent.clear()
        self._hash_depth.clear()
        self._hash_touch.clear()
        self._prefix_hits.clear()
        self._prefix_version += 1
        self._free = list(range(self.n_blocks - 1, 0, -1))
        self._slot_blocks = [[] for _ in range(self.n_slots)]
        self._slot_prefix_len = [0] * self.n_slots

    # ---- fabric: directory manifest + chain export/seed -------------

    def prefix_manifest(self, since: int = -1, *, max_blocks: int = 512,
                        max_hot: int = 32) -> Dict[str, Any]:
        """Directory feed for GET /kv/prefixes: the registered block
        hashes (most-recent-first, capped at max_blocks so the payload
        stays bounded) plus the hottest matched hashes with
        depth/hits/age for replication planning. `since` is the
        version a prior poll returned; when nothing changed the reply
        collapses to {"unchanged": true}, keeping the health-sweep
        cadence cheap on an idle fleet. The manifest is possibly stale
        the instant it is serialized — every consumer treats entries
        as hints (a stale hit costs one prefix miss, never an
        error)."""
        if not self.prefix_cache:
            return {"supported": False}
        if since == self._prefix_version:
            return {"supported": True, "version": self._prefix_version,
                    "unchanged": True}
        now = time.time()
        blocks = [
            h.hex()
            for h in list(reversed(self._hash_to_block))[:max_blocks]
        ]
        hot = sorted(self._prefix_hits.items(), key=lambda kv: kv[1],
                     reverse=True)[:max_hot]
        return {
            "supported": True,
            "version": self._prefix_version,
            "block_size": self.block_size,
            "blocks": blocks,
            "blocks_total": len(self._hash_to_block),
            "hot": [
                {"h": h.hex(), "hits": n,
                 "depth": self._hash_depth.get(h, 0),
                 "age_s": round(now - self._hash_touch.get(h, now), 3)}
                for h, n in hot if h in self._hash_to_block
            ],
        }

    def chain_blocks(self, tip: bytes) -> Tuple[List[bytes], List[int]]:
        """Root-first (hashes, pool block ids) of the chain ending at
        `tip`. ValueError when the tip or any ancestor is no longer
        registered — a chain with an evicted link cannot be exported
        (the matcher walks from the root, so a torn chain would never
        be hit; shipping one would seed unreachable blocks)."""
        chain: List[bytes] = []
        h = tip
        while h != b"":
            if h not in self._hash_to_block:
                raise ValueError(
                    f"prefix chain broken at {h.hex()[:12]}…: link "
                    "evicted from the registry"
                )
            chain.append(h)
            h = self._hash_parent.get(h, b"")
        chain.reverse()
        return chain, [self._hash_to_block[h] for h in chain]

    def seed_blocks(self, n: int) -> List[int]:
        """Phase 1 of seeding KV pushed by a peer: allocate n pool
        blocks from the FREE LIST only — seeding is speculative, so
        it never evicts cached blocks, and a full slot's worth of
        headroom stays free so a seed can never starve the next
        admission. Raises PoolExhausted (the retryable class) when the
        pool is too tight."""
        if n > len(self._free) - self.max_blocks_per_slot:
            raise PoolExhausted()
        return [self._free.pop() for _ in range(n)]

    def abort_seed(self, blocks: List[int]) -> None:
        """Return phase-1 blocks to the free list with the registry
        untouched (the device write never happened)."""
        self._free.extend(reversed(blocks))

    def commit_seed(self, entries: List[Tuple[bytes, bytes, int]]) -> None:
        """Phase 2: the device arrays are written — register
        (hash, parent_hash, block) rows at refcount 0, i.e.
        LRU-evictable and never pinned: a seed the local workload
        never hits simply ages out of the pool."""
        now = time.time()
        for h, parent, blk in entries:
            self._hash_to_block[h] = blk
            self._block_ref[blk] = 0
            self._hash_parent[h] = parent
            self._hash_depth[h] = (
                self._hash_depth.get(parent, 0) + 1 if parent else 1
            )
            self._hash_touch[h] = now
        if entries:
            self._prefix_version += 1
            self.engine.stats["prefix_seeded_blocks"] += len(entries)

    # ---- accounting --------------------------------------------------

    def utilization(self) -> float:
        # Pool utilization replaces the dense token-count estimate:
        # blocks out of the free list / pool size (block 0 is scratch).
        pool = self.n_blocks - 1
        return (pool - len(self._free)) / pool

    def residency(self) -> Dict[str, Any]:
        return {
            "backend": self.name,
            "slot_tokens": self._slot_tokens(),
            "slot_blocks": [len(b) for b in self._slot_blocks],
            "block_size": self.block_size,
            "blocks_total": self.n_blocks - 1,  # minus scratch
            "blocks_free": len(self._free),
            "prefix_cached_blocks": len(self._hash_to_block),
        }


class QuantPagedBackend(PagedBackend):
    """Int8 paged pool: PagedBackend's allocator over int8 value pools
    + fp32 scale pools (layout.QuantPagedKVCache). Pure storage swap —
    scale pools mirror the value pools block-for-block, so the free
    list, prefix refcounts, and tables need no changes."""

    name = "paged-int8"

    def __init__(self, cfg: ModelConfig, n_slots: int, max_len: int, *,
                 kv_quant: Optional[str] = "int8", block_size: int = 64,
                 pool_tokens: Optional[int] = None,
                 prefix_cache: bool = False, chunk_slack: int = 1):
        if kv_quant != "int8":
            raise ValueError(
                f"QuantPagedBackend is the int8 pool; kv_quant="
                f"{kv_quant!r} wants PagedBackend"
            )
        super().__init__(
            cfg, n_slots, max_len, kv_quant="int8",
            block_size=block_size, pool_tokens=pool_tokens,
            prefix_cache=prefix_cache, chunk_slack=chunk_slack,
        )
