"""Dense slot backend: one (max_len) cache row per slot, bf16 or int8.

The simplest storage policy — every slot reserves its full row, so
there is nothing to allocate or free; capacity accounting is token
counting. kv_quant="int8" swaps the row storage for int8 values +
per-token fp32 scales (half the resident bytes and half the HBM
stream per decode tick) with no policy change.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.cache.base import CacheBackend
from shellac_tpu.inference.cache.layout import (
    cache_logical_axes_for,
    init_cache_for,
)


class DenseBackend(CacheBackend):
    name = "dense"

    def __init__(self, cfg: ModelConfig, n_slots: int, max_len: int, *,
                 kv_quant: Optional[str] = None, chunk_slack: int = 1):
        super().__init__(cfg, n_slots, max_len, kv_quant=kv_quant,
                         chunk_slack=chunk_slack)
        if kv_quant == "int8":
            self.name = "dense-int8"

    def init_cache(self):
        return init_cache_for(self.cfg, self.n_slots, self.max_len,
                              self.kv_quant)

    def init_mini(self, length: int):
        return init_cache_for(self.cfg, 1, length, self.kv_quant)

    def logical_axes(self):
        return cache_logical_axes_for(self.cfg, self.kv_quant)

    def utilization(self) -> float:
        return sum(self._slot_tokens()) / (self.n_slots * self.max_len)

    def residency(self) -> Dict[str, Any]:
        return {
            "backend": self.name,
            "slot_tokens": self._slot_tokens(),
            "capacity_tokens": self.n_slots * self.max_len,
        }
