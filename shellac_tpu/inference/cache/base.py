"""CacheBackend: the storage-policy interface the serving engines hold.

The separation is TVM's algorithm-vs-schedule split applied to KV
storage: decode ALGORITHMS (the dense window, the speculative verify
round, beam search, chunked prefill) are written once against this
interface, while the STORAGE POLICY — dense slot rows, paged block
pool, int8 quantization, rolling ring — is a pluggable backend behind
it. An engine never branches on cache shape; it asks its backend.

A backend owns two things:

  1. the DEVICE cache construction contract: `init_cache()` builds the
     engine's cache pytree, `init_mini(length)` the batch-1 prefill
     scratch of the matching kind, and `logical_axes()` the sharding
     axes tree — the single place jit `out_shardings` derive from, so
     sharding can never desync from what the backend built;
  2. the HOST-side slot residency policy: `prepare_slot` /
     `release_slot` / `pre_window` / `reset` hooks (the paged block
     allocator and prefix-cache registries live entirely here),
     `utilization()` for the capacity gauge, and `residency()` — a
     JSON-serializable report of what each slot holds, the piece the
     disaggregated prefill/decode split will ship between hosts.

Backends are bound to exactly one engine (`bind`); the engine keeps
rebinding `engine._cache` from its jitted programs' donated outputs,
and the backend reads/writes that attribute for table surgery (paged)
rather than holding its own copy — one owner for the device tree, one
for the host policy.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from shellac_tpu.config import ModelConfig


class PoolExhausted(Exception):
    """Raised by `prepare_slot` when the backend cannot admit the
    request right now (paged: pool has too few free/evictable blocks).
    The engine requeues the request and retries after a release."""


class CacheBackend:
    """Base storage policy: one slot row per request, nothing to
    allocate. Subclasses override the hooks that their policy needs;
    every default below is the dense no-op."""

    #: registry name ("dense", "paged-int8", ...) — exposed at /stats
    #: and as the shellac_engine_cache_backend_info gauge label.
    name: str = "dense"
    #: True for block-pool backends (drives the pp-pipeline gate and
    #: the engines' historical `_swaps_cache` contract).
    is_paged: bool = False
    #: True for ring-buffer backends (the engines' rolling_window
    #: compatibility attribute derives from this).
    is_rolling: bool = False

    def __init__(self, cfg: ModelConfig, n_slots: int, max_len: int, *,
                 kv_quant: Optional[str] = None, chunk_slack: int = 1):
        if kv_quant not in (None, "int8"):
            raise ValueError(f"kv_quant={kv_quant!r}; have None, 'int8'")
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.kv_quant = kv_quant
        self.chunk_slack = chunk_slack
        self.engine: Any = None

    # ---- engine binding ---------------------------------------------

    def bind(self, engine) -> None:
        """Attach to the owning engine. One backend, one engine: the
        slot hooks read engine state (slots, stats, the live cache
        pytree) and a shared backend would alias allocator state."""
        if self.engine is not None and self.engine is not engine:
            raise ValueError(
                f"{self.name} backend is already bound to an engine; "
                "construct one backend per engine"
            )
        self.engine = engine

    # ---- device cache construction ----------------------------------

    def init_cache(self):
        raise NotImplementedError

    def init_mini(self, length: int):
        """Batch-1 prefill scratch of the kind the engine's prefill
        program scatters into this backend's cache."""
        raise NotImplementedError

    def logical_axes(self):
        """Sharding axes tree matching init_cache()'s pytree."""
        raise NotImplementedError

    # ---- slot lifecycle (host-side policy) --------------------------

    def prepare_slot(self, slot: int, req, footprint: int) -> None:
        """Reserve residency for `req` before its prefill. `footprint`
        is the request's worst-case token residency (prompt + budget +
        engine slack). May raise PoolExhausted; the engine requeues."""

    def on_prefill_complete(self, slot: int) -> None:
        """The slot's prompt KV is now real (prefill finished) —
        paged prefix caching registers the prompt blocks here."""

    def release_slot(self, slot: int) -> None:
        """The request left `slot` (finish/cancel/abort)."""

    def pre_window(self, active_rows, advance: Optional[Dict[int, int]],
                   span: int) -> None:
        """About to run one decode window writing up to `span` tokens
        per active slot; `advance` maps slot -> tokens an un-synced
        in-flight window will still append (overlapped dispatch)."""

    def prefill_offset(self, slot: int) -> int:
        """Tokens already resident when prefill starts (paged prefix
        caching returns the matched prefix length)."""
        return 0

    def reset(self) -> None:
        """abort_all: restore the allocator to its canonical pristine
        state (multi-host resync depends on every replica converging
        to identical post-abort state)."""

    def initial_stats(self) -> Dict[str, int]:
        """Backend-owned counters merged into engine.stats at
        construction (paged prefix caching adds its hit counters)."""
        return {}

    def prefix_manifest(self, since: int = -1, **_: Any) -> Dict[str, Any]:
        """Directory feed for GET /kv/prefixes. Backends without a
        prefix-cache registry answer {"supported": false} — an honest
        refusal the tier's directory treats as "never route here for
        cache contents", never an error."""
        return {"supported": False}

    # ---- accounting --------------------------------------------------

    def utilization(self) -> float:
        """Live residency / capacity, in [0, 1] (the kv_utilization
        gauge the serving tier's load scoring reads)."""
        raise NotImplementedError

    def residency(self) -> Dict[str, Any]:
        """JSON-serializable per-slot residency: what each slot holds
        and the pool-level headroom. The engine adds request identity;
        this is the storage view only."""
        raise NotImplementedError

    def bytes_per_token(self) -> int:
        """Resident KV bytes one token costs under this storage policy
        (per-slot view; paged block rounding ignored). Exposed as the
        shellac_engine_kv_bytes_per_token gauge — the tier's
        KV-migration transfer-cost estimate reads it, so the cost
        model tracks the backend (int8 halves it) instead of guessing
        from the model name."""
        import jax.numpy as jnp

        cfg = self.cfg
        width = cfg.cache_head_dim + cfg.cache_v_head_dim
        if self.kv_quant == "int8":
            # int8 values + one fp32 scale per token/head for k and v.
            return cfg.n_layers * cfg.cache_kv_heads * (width + 2 * 4)
        itemsize = jnp.dtype(cfg.compute_dtype).itemsize
        return cfg.n_layers * cfg.cache_kv_heads * width * itemsize

    # ---- shared helpers ---------------------------------------------

    def _slot_tokens(self) -> List[int]:
        """Host-known live tokens per slot (prompt + generated)."""
        eng = self.engine
        return [
            (r.tokens.size + len(r.out)) if r is not None else 0
            for r in eng._slots
        ]
