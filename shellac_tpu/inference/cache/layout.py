"""KV cache for autoregressive decoding.

Layout: stacked over layers and HEAD-MAJOR, (L, B, Hkv, max_len, Dh).
Stacking over layers matches the stacked-layer parameter layout so the
decode forward remains a single `lax.scan`. Head-major (head before
sequence) is a hard requirement of the compiled Pallas decode kernels:
Mosaic block shapes must keep the last two dims tileable, so the kv
stream a kernel DMAs has to be a contiguous (seq_block, head_dim) tile
per head — with seq-major layout the head axis lands second-to-last
with block size 1, which the TPU lowering rejects (and a relayout copy
of a multi-GiB cache every tick is exactly what the kernel exists to
avoid). The cache lives in compute dtype (bf16): it is read-only
bandwidth, and attention logits accumulate in fp32 regardless.

Ragged batches are handled with per-sequence `lengths`: prompts are
right-padded and written from offset 0; `lengths` records how many slots
are real. Decode writes each sequence's next token at its own length
(vmapped dynamic_update_slice), overwriting stale pad slots, so position
ids stay continuous per sequence and pads are never attended.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig


@flax.struct.dataclass
class KVCache:
    k: Any  # (L, B, Hkv, max_len, Dh)
    v: Any  # (L, B, Hkv, max_len, Dh)
    lengths: Any  # (B,) int32 — valid positions per sequence

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    head = (cfg.n_layers, batch, cfg.cache_kv_heads, max_len)
    return KVCache(
        k=jnp.zeros((*head, cfg.cache_head_dim), cfg.compute_dtype),
        # MLA: v is a zero-width placeholder — values re-expand from the
        # latent the k cache already stores (transformer._block).
        v=jnp.zeros((*head, cfg.cache_v_head_dim), cfg.compute_dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def cache_logical_axes(cfg: Optional[ModelConfig] = None):
    """Logical axes for sharding the cache over a mesh.

    Under MLA the cache is one shared latent row per token (head axis
    of size 1) — it replicates over tp instead of sharding; the
    per-head work stays tp-sharded through the q/o projections. Pass
    the cfg to get that right; None keeps the standard kv_heads axes.
    """
    heads = "kv_heads" if cfg is None or cfg.mla is None else None
    return KVCache(
        k=("layers", "batch", heads, None, None),
        v=("layers", "batch", heads, None, None),
        lengths=("batch",),
    )


# ---------------------------------------------------------------------------
# Int8-quantized cache (serving memory/bandwidth: half of bf16)
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class QuantKVCache:
    """KV cache stored int8 with one fp32 scale per written token/head.

    Same head-major layout and write-at-own-length contract as KVCache;
    k/v hold symmetric int8 (scale = amax/127 over the head_dim axis,
    computed at write time — K is quantized AFTER RoPE so dequantized
    reads reproduce the rotated values directly). Decode is HBM-bound
    on cache reads, so int8 halves both the resident footprint (double
    the servable slots*context) and the stream the attention pays per
    tick; the logits dot runs fp32 with the per-token scale folded in
    after (exact algebra: sum_d q*k_int*s == s * sum_d q*k_int).
    """

    k: Any  # (L, B, Hkv, max_len, Dh) int8
    v: Any  # (L, B, Hkv, max_len, Dh) int8
    ks: Any  # (L, B, Hkv, max_len) fp32 — k dequant scale per token
    vs: Any  # (L, B, Hkv, max_len) fp32
    lengths: Any  # (B,) int32

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


def init_quant_cache(cfg: ModelConfig, batch: int, max_len: int) -> QuantKVCache:
    head = (cfg.n_layers, batch, cfg.cache_kv_heads, max_len)
    return QuantKVCache(
        k=jnp.zeros((*head, cfg.cache_head_dim), jnp.int8),
        v=jnp.zeros((*head, cfg.cache_v_head_dim), jnp.int8),
        ks=jnp.zeros(head, jnp.float32),
        vs=jnp.zeros(head, jnp.float32),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def quant_cache_logical_axes(cfg: Optional[ModelConfig] = None):
    heads = "kv_heads" if cfg is None or cfg.mla is None else None
    return QuantKVCache(
        k=("layers", "batch", heads, None, None),
        v=("layers", "batch", heads, None, None),
        ks=("layers", "batch", heads, None),
        vs=("layers", "batch", heads, None),
        lengths=("batch",),
    )


def kv_field_names(kv_quant=None):
    """The value/scale field names shared by the dense and paged cache
    kinds — the ONE definition the engines' field-tuple plumbing
    (pipelined stage splits, paged beam CoW, prefill scatters) keys
    on, so a new cache field cannot silently miss a path."""
    return ("k", "v", "ks", "vs") if kv_quant == "int8" else ("k", "v")


def init_cache_for(cfg: ModelConfig, batch: int, max_len: int,
                   kv_quant=None, rolling: bool = False,
                   chunk_slack: int = 1):
    """The engines' cache constructor: dense bf16, int8, or a rolling
    ring buffer (sliding-window models) by flags."""
    if rolling:
        if kv_quant is not None and kv_quant != "int8":
            raise ValueError(f"kv_quant={kv_quant!r}; have None, 'int8'")
        patterned = (cfg.attn_pattern is not None
                     and "full" in cfg.attn_pattern)
        if kv_quant == "int8":
            if patterned:
                return init_quant_patterned_cache(
                    cfg, batch, max_len, chunk_slack=chunk_slack
                )
            return init_quant_rolling_cache(cfg, batch, max_len,
                                            chunk_slack=chunk_slack)
        if patterned:
            return init_patterned_cache(cfg, batch, max_len,
                                        chunk_slack=chunk_slack)
        return init_rolling_cache(cfg, batch, max_len,
                                  chunk_slack=chunk_slack)
    if kv_quant == "int8":
        return init_quant_cache(cfg, batch, max_len)
    if kv_quant is not None:
        raise ValueError(f"kv_quant={kv_quant!r}; have None, 'int8'")
    return init_cache(cfg, batch, max_len)


def cache_logical_axes_for(cfg: ModelConfig, kv_quant=None,
                           rolling: bool = False):
    """Logical axes matching what init_cache_for builds for the same
    flags — the single place the cache-kind dispatch lives, so jit
    out_shardings can never desync from the cache pytree."""
    if rolling:
        patterned = (cfg.attn_pattern is not None
                     and "full" in cfg.attn_pattern)
        if kv_quant == "int8":
            if patterned:
                return quant_patterned_cache_logical_axes(cfg)
            return quant_rolling_cache_logical_axes(cfg)
        if patterned:
            return patterned_cache_logical_axes(cfg)
        return rolling_cache_logical_axes(cfg)
    if kv_quant == "int8":
        return quant_cache_logical_axes(cfg)
    return cache_logical_axes(cfg)


def quantize_kv(x: jax.Array):
    """(B, S, Hkv, Dh) -> int8 values + (B, S, Hkv) fp32 scales.

    Zero-width inputs (MLA's v placeholder) quantize to a zero-width
    int8 array with unit scales — an empty-axis amax would be -inf.
    """
    if x.shape[-1] == 0:
        return (jnp.zeros(x.shape, jnp.int8),
                jnp.ones(x.shape[:-1], jnp.float32))
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127.0, 127.0
    ).astype(jnp.int8)
    return q, scale


def quant_update_layer(
    cache_k, cache_v, cache_ks, cache_vs,  # one layer's (B, Hkv, len[, Dh])
    k_new, v_new,  # (B, S, Hkv, Dh) unquantized
    index,  # (B,) int32
):
    """Quantize S new positions and write them at per-sequence offsets."""
    kq, ks = quantize_kv(k_new)
    vq, vs = quantize_kv(v_new)
    ck, cv = update_layer(cache_k, cache_v, kq, vq, index)

    def upd(c, n, i):
        return jax.lax.dynamic_update_slice(c, n, (0, i))

    cks = jax.vmap(upd)(cache_ks, ks.transpose(0, 2, 1), index)
    cvs = jax.vmap(upd)(cache_vs, vs.transpose(0, 2, 1), index)
    return ck, cv, cks, cvs


def paged_cache_logical_axes(cfg: Optional[ModelConfig] = None):
    """Logical axes for sharding a paged cache over a mesh.

    The KV pools shard over kv_heads (tensor parallelism), same as the
    dense cache (replicated under MLA — one shared latent row); the
    block axis is scheduler-addressed (host-side free list picks
    arbitrary block ids) so it stays unsharded, and the tables/lengths
    are tiny scheduler metadata, replicated.
    """
    heads = "kv_heads" if cfg is None or cfg.mla is None else None
    return PagedKVCache(
        k=("layers", None, heads, None, None),
        v=("layers", None, heads, None, None),
        tables=(None, None),
        lengths=(None,),
    )


def update_layer(
    cache_k: jax.Array,  # (B, Hkv, max_len, Dh) — one layer's cache
    cache_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) int32 — per-sequence write offset
):
    """Write S new positions at per-sequence offsets; returns (k, v)."""
    k_new = k_new.astype(cache_k.dtype).transpose(0, 2, 1, 3)  # (B,Hkv,S,Dh)
    v_new = v_new.astype(cache_v.dtype).transpose(0, 2, 1, 3)

    def upd(c, n, i):
        return jax.lax.dynamic_update_slice(c, n, (0, i, 0))

    ck = jax.vmap(upd)(cache_k, k_new, index)
    cv = jax.vmap(upd)(cache_v, v_new, index)
    return ck, cv


def scatter_slot(cache, mini, slot):
    """Write a batch-1 mini-cache into `slot` of a slot cache.

    Works for KVCache and QuantKVCache alike (the serving engines use
    it so their prefill programs stay cache-type-agnostic).
    """

    def upd(c, n):
        return jax.lax.dynamic_update_slice_in_dim(c, n, slot, axis=1)

    if isinstance(cache, QuantPatternedKVCache):
        fields = {n: upd(getattr(cache, n), getattr(mini, n))
                  for n in ("kw", "vw", "kws", "vws",
                            "kf", "vf", "kfs", "vfs")}
    elif isinstance(cache, PatternedKVCache):
        fields = {n: upd(getattr(cache, n), getattr(mini, n))
                  for n in ("kw", "vw", "kf", "vf")}
    else:
        fields = {"k": upd(cache.k, mini.k), "v": upd(cache.v, mini.v)}
        if isinstance(cache, (QuantKVCache, QuantRollingKVCache)):
            fields.update(ks=upd(cache.ks, mini.ks),
                          vs=upd(cache.vs, mini.vs))
    fields["lengths"] = jax.lax.dynamic_update_slice(
        cache.lengths, mini.lengths, (slot,))
    return cache.replace(**fields)


def slot_view(cache, slot, lengths):
    """Batch-1 view of one slot's rows, with `lengths` (1,) overriding
    the stored per-slot lengths (chunked-prefill continuations resume
    from an explicit offset)."""

    def sl(c):
        return jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1)

    if isinstance(cache, QuantPatternedKVCache):
        fields = {n: sl(getattr(cache, n))
                  for n in ("kw", "vw", "kws", "vws",
                            "kf", "vf", "kfs", "vfs")}
    elif isinstance(cache, PatternedKVCache):
        fields = {n: sl(getattr(cache, n))
                  for n in ("kw", "vw", "kf", "vf")}
    else:
        fields = {"k": sl(cache.k), "v": sl(cache.v)}
        if isinstance(cache, (QuantKVCache, QuantRollingKVCache)):
            fields.update(ks=sl(cache.ks), vs=sl(cache.vs))
    fields["lengths"] = lengths.astype(jnp.int32)
    return cache.replace(**fields)


# ---------------------------------------------------------------------------
# Paged cache (block pool + per-sequence block tables)
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class PagedKVCache:
    """Block-pool KV cache: slots map to pool blocks via tables.

    A dense slot cache reserves max_len for every slot; the pool is
    sized to the *total* tokens actually resident, so many short
    requests and a few long ones share memory. Block allocation is a
    host-side free list (see PagedBatchingEngine); the device side only
    ever sees the tables.

    k, v: (L, n_blocks, Hkv, block_size, Dh) — head-major inside each
        block, same Pallas tiling requirement as the dense cache.
    tables: (n_slots, max_blocks) int32 — pool block id per logical
        block; unallocated entries MUST point at block 0 (reserved as
        scratch: it is never handed to a slot, so stray writes and reads
        through unallocated table entries land there harmlessly).
    lengths: (n_slots,) int32 — valid tokens per slot.
    """

    k: Any
    v: Any
    tables: Any
    lengths: Any

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_blocks(self) -> int:
        return self.tables.shape[1]


def init_paged_cache(
    cfg: ModelConfig,
    n_slots: int,
    n_blocks: int,
    block_size: int,
    max_blocks_per_slot: int,
) -> PagedKVCache:
    head = (cfg.n_layers, n_blocks, cfg.cache_kv_heads, block_size)
    return PagedKVCache(
        k=jnp.zeros((*head, cfg.cache_head_dim), cfg.compute_dtype),
        # MLA: zero-width v pool (values re-expand from the latent the
        # k pool stores), same convention as the dense cache.
        v=jnp.zeros((*head, cfg.cache_v_head_dim), cfg.compute_dtype),
        tables=jnp.zeros((n_slots, max_blocks_per_slot), jnp.int32),
        lengths=jnp.zeros((n_slots,), jnp.int32),
    )


def paged_update_layer(
    pool_k: jax.Array,  # (n_blocks, Hkv, bs, Dh) — one layer's pool
    pool_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) — per-slot write offsets (token positions)
    tables: jax.Array,  # (B, max_blocks) int32
):
    """Scatter S new positions through the block tables; returns pools.

    Positions index[b] + i map to pool coords
    (tables[b, p // bs], :, p % bs). Slots must have blocks allocated
    for every written position (the scheduler guarantees it); writes
    through unallocated entries land in scratch block 0.
    """
    bs = pool_k.shape[2]
    b, s = k_new.shape[:2]
    pos = index[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]  # (B, S)
    block_ids = jnp.take_along_axis(tables, pos // bs, axis=1)  # (B, S)
    offs = pos % bs
    flat_blocks = block_ids.reshape(-1)
    flat_offs = offs.reshape(-1)
    # Advanced indices at dims 0 and 2 (separated by the head slice):
    # the indexed result is (B*S, Hkv, Dh), matching k_new's token rows.
    pk = pool_k.at[flat_blocks, :, flat_offs].set(
        k_new.astype(pool_k.dtype).reshape(b * s, *k_new.shape[2:])
    )
    pv = pool_v.at[flat_blocks, :, flat_offs].set(
        v_new.astype(pool_v.dtype).reshape(b * s, *v_new.shape[2:])
    )
    return pk, pv


def paged_gather_layer(
    pool_k: jax.Array,  # (n_blocks, Hkv, bs, Dh)
    pool_v: jax.Array,
    tables: jax.Array,  # (B, max_blocks)
):
    """Materialize each slot's logical KV view, head-major:
    (B, Hkv, max_blocks*bs, D) — the same layout as a dense cache layer,
    so the decode fallback consumes it directly."""
    b, mb = tables.shape
    hkv, bs, dh = pool_k.shape[1:]

    def gather(pool):
        x = jnp.take(pool, tables.reshape(-1), axis=0)  # (B*mb, Hkv, bs, Dh)
        x = x.reshape(b, mb, hkv, bs, dh).transpose(0, 2, 1, 3, 4)
        return x.reshape(b, hkv, mb * bs, dh)

    return gather(pool_k), gather(pool_v)


# ---------------------------------------------------------------------------
# Int8-quantized paged cache (pool memory/bandwidth: half of bf16)
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class QuantPagedKVCache:
    """Paged block pool stored int8 with per-token/head dequant scales.

    Same block-table indirection, scratch-block-0 convention, and
    host-side allocator contract as PagedKVCache; same write-time
    symmetric quantization contract as QuantKVCache (K quantized after
    RoPE). Scale pools mirror the value pools block-for-block — one
    allocator run covers both, so the free list and prefix-cache
    refcounts need no changes.

    k, v: (L, n_blocks, Hkv, block_size, Dh) int8
    ks, vs: (L, n_blocks, Hkv, block_size) fp32
    tables: (n_slots, max_blocks) int32
    lengths: (n_slots,) int32
    """

    k: Any
    v: Any
    ks: Any
    vs: Any
    tables: Any
    lengths: Any

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_blocks(self) -> int:
        return self.tables.shape[1]


def init_quant_paged_cache(
    cfg: ModelConfig,
    n_slots: int,
    n_blocks: int,
    block_size: int,
    max_blocks_per_slot: int,
) -> QuantPagedKVCache:
    head = (cfg.n_layers, n_blocks, cfg.cache_kv_heads, block_size)
    return QuantPagedKVCache(
        k=jnp.zeros((*head, cfg.cache_head_dim), jnp.int8),
        v=jnp.zeros((*head, cfg.cache_v_head_dim), jnp.int8),
        ks=jnp.zeros(head, jnp.float32),
        vs=jnp.zeros(head, jnp.float32),
        tables=jnp.zeros((n_slots, max_blocks_per_slot), jnp.int32),
        lengths=jnp.zeros((n_slots,), jnp.int32),
    )


def quant_paged_cache_logical_axes(cfg: Optional[ModelConfig] = None):
    heads = "kv_heads" if cfg is None or cfg.mla is None else None
    return QuantPagedKVCache(
        k=("layers", None, heads, None, None),
        v=("layers", None, heads, None, None),
        ks=("layers", None, heads, None),
        vs=("layers", None, heads, None),
        tables=(None, None),
        lengths=(None,),
    )


def quant_paged_update_layer(
    pool_k, pool_v, pool_ks, pool_vs,  # one layer's int8 pools + scales
    k_new, v_new,  # (B, S, Hkv, Dh) unquantized
    index,  # (B,) int32 — per-slot write offsets (token positions)
    tables,  # (B, max_blocks) int32
):
    """Quantize S new positions, scatter values and scales through the
    block tables (same position->block arithmetic as the bf16 pool)."""
    kq, ks = quantize_kv(k_new)
    vq, vs = quantize_kv(v_new)
    pk, pv = paged_update_layer(pool_k, pool_v, kq, vq, index, tables)
    bs = pool_k.shape[2]
    b, s = k_new.shape[:2]
    pos = index[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    block_ids = jnp.take_along_axis(tables, pos // bs, axis=1)
    flat_blocks = block_ids.reshape(-1)
    flat_offs = (pos % bs).reshape(-1)
    pks = pool_ks.at[flat_blocks, :, flat_offs].set(
        ks.reshape(b * s, -1)
    )
    pvs = pool_vs.at[flat_blocks, :, flat_offs].set(
        vs.reshape(b * s, -1)
    )
    return pk, pv, pks, pvs


def paged_gather_scales(
    pool_s: jax.Array,  # (n_blocks, Hkv, bs)
    tables: jax.Array,  # (B, max_blocks)
):
    """Materialize each slot's logical scale view: (B, Hkv, max_blocks*bs)
    — the dense QuantKVCache scale layout, so the dequant fallback
    consumes it directly."""
    b, mb = tables.shape
    hkv, bs = pool_s.shape[1:]
    x = jnp.take(pool_s, tables.reshape(-1), axis=0)  # (B*mb, Hkv, bs)
    x = x.reshape(b, mb, hkv, bs).transpose(0, 2, 1, 3)
    return x.reshape(b, hkv, mb * bs)


# ---------------------------------------------------------------------------
# Rolling (ring-buffer) cache for sliding-window attention
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class RollingKVCache:
    """Ring-buffer KV cache: storage scales with the WINDOW, not the
    context.

    A sliding-window layer only ever attends the last `window`
    positions, so position p lives at ring slot p % ring and old
    positions are overwritten in place. `lengths` still counts TOTAL
    positions seen (the position arithmetic is identical to the dense
    cache); only the storage wraps. ring must be >= window + the
    largest cache-READING write chunk (decode writes 1; chunked-prefill
    continuations write up to prefill_chunk) — the extra slack keeps a
    chunk's EARLIEST query row's window intact while the chunk's own
    writes land. Fresh prefill attends the incoming chunk directly
    (never the buffer), so whole-prompt prefill needs no slack.

    Same head-major (L, B, Hkv, ring, Dh) layout as KVCache. Reads go
    through the reference attention with reconstructed per-slot
    positions — the ring is window-sized, so the Pallas decode kernel's
    dead-block skipping (its reason to exist on a max_len buffer) has
    nothing left to skip.
    """

    k: Any  # (L, B, Hkv, ring, Dh)
    v: Any  # (L, B, Hkv, ring, Dh)
    lengths: Any  # (B,) int32 — TOTAL positions seen

    @property
    def ring(self) -> int:
        return self.k.shape[3]


def rolling_ring(cfg: ModelConfig, max_len: int, chunk_slack: int) -> int:
    """Ring size for a config: window + slack, sublane-rounded, capped
    at max_len (a ring bigger than the context is just a dense cache)."""
    if cfg.attn_window is None:
        raise ValueError("rolling cache needs cfg.attn_window")
    ring = cfg.attn_window + max(int(chunk_slack), 1)
    ring = ((ring + 7) // 8) * 8
    return min(ring, max_len)


def init_rolling_cache(
    cfg: ModelConfig, batch: int, max_len: int, chunk_slack: int = 1,
) -> RollingKVCache:
    if cfg.mla is not None:
        raise ValueError("MLA models have no sliding window to roll")
    if cfg.attn_window is None:
        raise ValueError(
            "rolling cache needs a sliding-window model (attn_window)"
        )
    if cfg.attn_pattern is not None and "full" in cfg.attn_pattern:
        raise NotImplementedError(
            "patterned local/global stacks roll via the MIXED cache — "
            "use init_patterned_cache (init_cache_for routes there "
            "automatically); this constructor builds the uniform ring"
        )
    ring = rolling_ring(cfg, max_len, chunk_slack)
    head = (cfg.n_layers, batch, cfg.cache_kv_heads, ring)
    return RollingKVCache(
        k=jnp.zeros((*head, cfg.cache_head_dim), cfg.compute_dtype),
        v=jnp.zeros((*head, cfg.cache_head_dim), cfg.compute_dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def rolling_cache_logical_axes(cfg: Optional[ModelConfig] = None):
    return RollingKVCache(
        k=("layers", "batch", "kv_heads", None, None),
        v=("layers", "batch", "kv_heads", None, None),
        lengths=("batch",),
    )


def roll_update_layer(
    cache_k: jax.Array,  # (B, Hkv, ring, Dh) — one layer's ring
    cache_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) int32 — first new position (total count)
    valid_len=None,  # (B,) int32 — REAL rows in the chunk (None = S)
):
    """Write the chunk's REAL positions into the ring at
    (index + i) % ring.

    valid_len masks right-padding: the dense cache can write pad rows
    harmlessly (reads mask by lengths), but a ring write WRAPS — a pad
    row landing at (index + i) % ring would clobber an in-window
    position, so pad rows must never touch the buffer.

    S == 1 (decode) is a plain per-row scatter. For larger chunks the
    write is LAST-WINS per slot, computed by gather-select (a naive
    scatter with duplicate ring indices has unspecified order): ring
    slot j's newest VALID chunk element is c_j = (cm - (cm - j) % ring)
    - index with cm the final real position; slots no valid element
    maps to keep their current rows.
    """
    ring = cache_k.shape[2]
    b, s = k_new.shape[:2]
    kn = k_new.astype(cache_k.dtype).transpose(0, 2, 1, 3)  # (B,Hkv,S,Dh)
    vn = v_new.astype(cache_v.dtype).transpose(0, 2, 1, 3)
    if s == 1 and valid_len is None:
        slot = (index % ring).astype(jnp.int32)
        barange = jnp.arange(b)
        ck = cache_k.at[barange, :, slot].set(kn[:, :, 0])
        cv = cache_v.at[barange, :, slot].set(vn[:, :, 0])
        return ck, cv
    vl = (jnp.full((b,), s, jnp.int32) if valid_len is None
          else jnp.minimum(valid_len.astype(jnp.int32), s))
    cm = index + vl - 1  # (B,) — final REAL position
    j = jnp.arange(ring, dtype=jnp.int32)[None, :]  # (1, ring)
    p = cm[:, None] - ((cm[:, None] - j) % ring)  # newest position per slot
    c = p - index[:, None]  # chunk element index
    valid = (c >= 0) & (c < vl[:, None])
    c_clamped = jnp.clip(c, 0, s - 1)
    take = jnp.take_along_axis(
        kn, c_clamped[:, None, :, None], axis=2
    )  # (B, Hkv, ring, Dh)
    ck = jnp.where(valid[:, None, :, None], take, cache_k)
    take_v = jnp.take_along_axis(vn, c_clamped[:, None, :, None], axis=2)
    cv = jnp.where(valid[:, None, :, None], take_v, cache_v)
    return ck, cv


def rolled_kv_positions(lengths: jax.Array, ring: int):
    """(kv_positions (B, ring) int32, kv_mask (B, ring) bool) for a ring
    whose newest written position is lengths - 1 (post-write)."""
    cm = lengths.astype(jnp.int32)[:, None] - 1  # (B, 1)
    j = jnp.arange(ring, dtype=jnp.int32)[None, :]
    p = cm - ((cm - j) % ring)
    return p, p >= 0


# ---------------------------------------------------------------------------
# Patterned cache: ring buffers for window layers, dense for full layers
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class PatternedKVCache:
    """Mixed cache for attn_pattern models: the "window" layers roll in
    ring buffers while the "full" layers keep the dense max_len stack —
    so a Gemma-2/GPT-OSS-style half-local stack cuts its cache memory
    roughly in half at long context (and far more as max_len grows).

    Layer i of kind "window" is row (number of window layers before i)
    of the kw/vw stacks; "full" layers index kf/vf the same way. The
    stacking order inside each kind follows layer order, so the
    pattern-period reshape in forward_with_cache stays a pure
    view + static in-group indexing.
    """

    kw: Any  # (Lw, B, Hkv, ring, Dh)
    vw: Any
    kf: Any  # (Lf, B, Hkv, max_len, Dh)
    vf: Any
    lengths: Any  # (B,) int32 — TOTAL positions (shared by both kinds)

    @property
    def ring(self) -> int:
        return self.kw.shape[3]

    @property
    def dense_len(self) -> int:
        return self.kf.shape[3]


def pattern_kind_counts(cfg: ModelConfig):
    """(n_window, n_full) per pattern period."""
    pat = cfg.attn_pattern
    nw = sum(1 for k in pat if k == "window")
    return nw, len(pat) - nw


def init_patterned_cache(
    cfg: ModelConfig, batch: int, max_len: int, chunk_slack: int = 1,
) -> PatternedKVCache:
    if cfg.attn_pattern is None or "window" not in cfg.attn_pattern:
        raise ValueError(
            "patterned cache needs an attn_pattern with 'window' layers"
        )
    if "full" not in cfg.attn_pattern:
        raise ValueError(
            "uniformly-windowed patterns use the plain rolling cache"
        )
    ring = rolling_ring(cfg, max_len, chunk_slack)
    nw, nf = pattern_kind_counts(cfg)
    groups = cfg.n_layers // len(cfg.attn_pattern)
    cdt = cfg.compute_dtype
    dh = cfg.cache_head_dim
    hkv = cfg.cache_kv_heads
    return PatternedKVCache(
        kw=jnp.zeros((groups * nw, batch, hkv, ring, dh), cdt),
        vw=jnp.zeros((groups * nw, batch, hkv, ring, dh), cdt),
        kf=jnp.zeros((groups * nf, batch, hkv, max_len, dh), cdt),
        vf=jnp.zeros((groups * nf, batch, hkv, max_len, dh), cdt),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def patterned_cache_logical_axes(cfg: Optional[ModelConfig] = None):
    ax = ("layers", "batch", "kv_heads", None, None)
    return PatternedKVCache(
        kw=ax, vw=ax, kf=ax, vf=ax, lengths=("batch",),
    )


@flax.struct.dataclass
class QuantRollingKVCache:
    """Int8 ring buffer: the rolling cache's window-sized storage AND
    the int8 cache's halved bytes/bandwidth, composed. Same write-time
    symmetric quantization contract as QuantKVCache (K quantized after
    RoPE); same ring position arithmetic as RollingKVCache. Reads
    dequantize the ring (it is window-sized — the dequant is O(window),
    not O(context)) and run the masked reference attention.
    """

    k: Any  # (L, B, Hkv, ring, Dh) int8
    v: Any  # (L, B, Hkv, ring, Dh) int8
    ks: Any  # (L, B, Hkv, ring) fp32
    vs: Any  # (L, B, Hkv, ring) fp32
    lengths: Any  # (B,) int32 — TOTAL positions seen

    @property
    def ring(self) -> int:
        return self.k.shape[3]


def init_quant_rolling_cache(
    cfg: ModelConfig, batch: int, max_len: int, chunk_slack: int = 1,
) -> QuantRollingKVCache:
    if cfg.attn_window is None:
        raise ValueError(
            "rolling cache needs a sliding-window model (attn_window)"
        )
    if cfg.attn_pattern is not None and "full" in cfg.attn_pattern:
        raise ValueError(
            "patterned local/global stacks roll int8 via the quant "
            "MIXED cache — use init_quant_patterned_cache "
            "(init_cache_for routes there automatically); this "
            "constructor builds the uniform int8 ring"
        )
    ring = rolling_ring(cfg, max_len, chunk_slack)
    head = (cfg.n_layers, batch, cfg.cache_kv_heads, ring)
    return QuantRollingKVCache(
        k=jnp.zeros((*head, cfg.cache_head_dim), jnp.int8),
        v=jnp.zeros((*head, cfg.cache_head_dim), jnp.int8),
        ks=jnp.zeros(head, jnp.float32),
        vs=jnp.zeros(head, jnp.float32),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def quant_rolling_cache_logical_axes(cfg: Optional[ModelConfig] = None):
    return QuantRollingKVCache(
        k=("layers", "batch", "kv_heads", None, None),
        v=("layers", "batch", "kv_heads", None, None),
        ks=("layers", "batch", "kv_heads", None),
        vs=("layers", "batch", "kv_heads", None),
        lengths=("batch",),
    )


@flax.struct.dataclass
class QuantPatternedKVCache:
    """Int8 mixed cache: the patterned cache's window-sized rings for
    "window" layers and dense max_len stacks for "full" layers, all
    stored int8 with per-token/head scales. Same layer->row mapping as
    PatternedKVCache, same write-time quantization contract as
    QuantKVCache (K post-rope). Window layers ring-write values AND
    scales (quant_roll_update_layer); full layers take the dense int8
    decode path (scales carried by the kernel or dequant reference).
    """

    kw: Any  # (Lw, B, Hkv, ring, Dh) int8
    vw: Any
    kws: Any  # (Lw, B, Hkv, ring) fp32
    vws: Any
    kf: Any  # (Lf, B, Hkv, max_len, Dh) int8
    vf: Any
    kfs: Any  # (Lf, B, Hkv, max_len) fp32
    vfs: Any
    lengths: Any  # (B,) int32 — TOTAL positions (shared by both kinds)

    @property
    def ring(self) -> int:
        return self.kw.shape[3]

    @property
    def dense_len(self) -> int:
        return self.kf.shape[3]


def init_quant_patterned_cache(
    cfg: ModelConfig, batch: int, max_len: int, chunk_slack: int = 1,
) -> QuantPatternedKVCache:
    if cfg.attn_pattern is None or "window" not in cfg.attn_pattern:
        raise ValueError(
            "patterned cache needs an attn_pattern with 'window' layers"
        )
    if "full" not in cfg.attn_pattern:
        raise ValueError(
            "uniformly-windowed patterns use the plain rolling cache"
        )
    ring = rolling_ring(cfg, max_len, chunk_slack)
    nw, nf = pattern_kind_counts(cfg)
    groups = cfg.n_layers // len(cfg.attn_pattern)
    dh = cfg.cache_head_dim
    hkv = cfg.cache_kv_heads
    return QuantPatternedKVCache(
        kw=jnp.zeros((groups * nw, batch, hkv, ring, dh), jnp.int8),
        vw=jnp.zeros((groups * nw, batch, hkv, ring, dh), jnp.int8),
        kws=jnp.zeros((groups * nw, batch, hkv, ring), jnp.float32),
        vws=jnp.zeros((groups * nw, batch, hkv, ring), jnp.float32),
        kf=jnp.zeros((groups * nf, batch, hkv, max_len, dh), jnp.int8),
        vf=jnp.zeros((groups * nf, batch, hkv, max_len, dh), jnp.int8),
        kfs=jnp.zeros((groups * nf, batch, hkv, max_len), jnp.float32),
        vfs=jnp.zeros((groups * nf, batch, hkv, max_len), jnp.float32),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def quant_patterned_cache_logical_axes(cfg: Optional[ModelConfig] = None):
    val = ("layers", "batch", "kv_heads", None, None)
    sc = ("layers", "batch", "kv_heads", None)
    return QuantPatternedKVCache(
        kw=val, vw=val, kws=sc, vws=sc,
        kf=val, vf=val, kfs=sc, vfs=sc, lengths=("batch",),
    )


def quant_roll_update_layer(
    cache_k, cache_v, cache_ks, cache_vs,  # one layer's ring (+ scales)
    k_new, v_new,  # (B, S, Hkv, Dh) unquantized
    index,  # (B,) int32
    valid_len=None,
):
    """Quantize the chunk, then ring-write values AND scales with the
    same last-wins/pad-mask semantics as roll_update_layer."""
    kq, ks = quantize_kv(k_new)
    vq, vs = quantize_kv(v_new)
    ck, cv = roll_update_layer(cache_k, cache_v, kq, vq, index,
                               valid_len=valid_len)
    # Scales are (B, S, Hkv) -> ring scatter on a 3D buffer: reuse the
    # 4D path with a width-1 head dim (the k and v slots of
    # roll_update_layer are independent, so one call does both rings).
    cks, cvs = roll_update_layer(
        cache_ks[..., None], cache_vs[..., None],
        ks[..., None], vs[..., None], index, valid_len=valid_len,
    )
    return ck, cv, cks[..., 0], cvs[..., 0]
