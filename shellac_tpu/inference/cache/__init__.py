"""The KV-cache subsystem: storage layouts + pluggable backends.

`layout` holds the cache pytrees and their update/gather free functions
(the former inference/kvcache.py, still importable there); `base`
defines the CacheBackend interface the engines hold; `dense` / `paged`
/ `rolling` implement the storage policies. This registry is the ONE
name->backend mapping every consumer resolves through — the engines,
the CLI's --cache-backend flag (and its deprecated legacy aliases
--paged / --kv-quant / --rolling-window), and the tests — so a new
backend registers once and is reachable everywhere.
"""

from __future__ import annotations

from typing import Optional

from shellac_tpu.inference.cache.base import CacheBackend, PoolExhausted
from shellac_tpu.inference.cache.dense import DenseBackend
from shellac_tpu.inference.cache.paged import PagedBackend, QuantPagedBackend
from shellac_tpu.inference.cache.rolling import RollingBackend

__all__ = [
    "BACKENDS",
    "CacheBackend",
    "DenseBackend",
    "PagedBackend",
    "PoolExhausted",
    "QuantPagedBackend",
    "RollingBackend",
    "backend_flags",
    "engine_class",
    "make_backend",
    "resolve_backend_name",
]

# name -> (backend class, pinned ctor kwargs). The int8 variants pin
# kv_quant so one registry name fully determines the storage.
BACKENDS = {
    "dense": (DenseBackend, {}),
    "dense-int8": (DenseBackend, {"kv_quant": "int8"}),
    "paged": (PagedBackend, {}),
    "paged-int8": (QuantPagedBackend, {}),
    "rolling": (RollingBackend, {}),
    "rolling-int8": (RollingBackend, {"kv_quant": "int8"}),
}

# What the legacy engine/CLI flags would have been for each name —
# engines keep exposing .kv_quant / .rolling_window for compatibility.
_FLAGS = {
    "dense": (False, None, False),
    "dense-int8": (False, "int8", False),
    "paged": (True, None, False),
    "paged-int8": (True, "int8", False),
    "rolling": (False, None, True),
    "rolling-int8": (False, "int8", True),
}


def backend_flags(name: str):
    """(is_paged, kv_quant, rolling_window) for a registry name."""
    if name not in _FLAGS:
        raise ValueError(
            f"unknown cache backend {name!r}; have {sorted(BACKENDS)}"
        )
    return _FLAGS[name]


def resolve_backend_name(
    explicit: Optional[str] = None, *,
    paged: bool = False,
    kv_quant: Optional[str] = None,
    rolling_window: bool = False,
) -> str:
    """Canonical backend name from an explicit --cache-backend choice
    and/or the deprecated legacy flags. Legacy flags alone map onto
    the registry; combined with an explicit name they must AGREE —
    a conflict is a config error, not a silent precedence rule."""
    if kv_quant not in (None, "int8"):
        raise ValueError(f"kv_quant={kv_quant!r}; have None, 'int8'")
    if paged and rolling_window:
        raise ValueError(
            "rolling_window is a slot-cache feature; the paged pool "
            "sizes memory via its block pool instead"
        )
    kind = "paged" if paged else ("rolling" if rolling_window else "dense")
    legacy = kind + ("-int8" if kv_quant == "int8" else "")
    if explicit is None:
        return legacy
    if explicit not in BACKENDS:
        raise ValueError(
            f"unknown cache backend {explicit!r}; have {sorted(BACKENDS)}"
        )
    # Each explicitly-set legacy flag must AGREE with the explicit
    # name (unset flags — the dense no-op defaults — impose nothing).
    exp_paged, exp_quant, exp_rolling = _FLAGS[explicit]
    if ((paged and not exp_paged)
            or (rolling_window and not exp_rolling)
            or (kv_quant is not None and kv_quant != exp_quant)):
        raise ValueError(
            f"cache backend {explicit!r} conflicts with legacy flags "
            f"(paged={paged}, kv_quant={kv_quant!r}, "
            f"rolling_window={rolling_window}); drop the legacy flags "
            "— they are deprecated aliases"
        )
    return explicit


def make_backend(name: str, cfg, n_slots: int, max_len: int,
                 **opts) -> CacheBackend:
    """Instantiate a registered backend. `opts` are the policy knobs
    (block_size, pool_tokens, prefix_cache, chunk_slack); knobs a
    backend does not take are rejected by its constructor — loudly,
    because a silently dropped pool size is a capacity incident."""
    if name not in BACKENDS:
        raise ValueError(
            f"unknown cache backend {name!r}; have {sorted(BACKENDS)}"
        )
    cls, pinned = BACKENDS[name]
    return cls(cfg, n_slots, max_len, **{**pinned, **opts})


def engine_class(name: str, speculative: bool = False):
    """The engine class serving a backend name (lazy imports: the
    engines import this package for their backends)."""
    paged, _, _ = backend_flags(name)
    if speculative:
        from shellac_tpu.inference.spec_batching import (
            PagedSpeculativeBatchingEngine,
            SpeculativeBatchingEngine,
        )

        return (PagedSpeculativeBatchingEngine if paged
                else SpeculativeBatchingEngine)
    from shellac_tpu.inference.batching import (
        BatchingEngine,
        PagedBatchingEngine,
    )

    return PagedBatchingEngine if paged else BatchingEngine
