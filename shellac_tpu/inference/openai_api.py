"""OpenAI-compatible API translation for the inference server.

Pure request/response translators: OpenAI `/v1/completions` and
`/v1/chat/completions` payloads map onto the native `/generate` payload
schema (inference/server.py), and native results map back into OpenAI
response shapes — so the whole battle-tested native path (continuous
batching, stop sequences, per-request sampling, n/best_of fan-out,
logprobs, streaming cancel, presence/frequency penalties) is reused
rather than reimplemented.

Scope honesty: knobs the engine genuinely implements translate;
accepted-but-ignored knobs are limited to no-op values (e.g. an empty
`suffix`) — a non-neutral unsupported knob is a loud 400, not a
silently different sampling distribution.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional


def _bad(msg: str):
    raise ValueError(msg)


def stream_error_payload(exc: BaseException,
                         trace_id: Optional[str] = None) -> dict:
    """In-band error record for a stream that already sent its 200.

    Once a stream's headers are gone the HTTP status can no longer
    classify the failure, so the record itself must: `type` follows the
    OpenAI error taxonomy, and `retryable` tells a fronting router
    whether re-issuing the request on ANOTHER replica could succeed.
    Only backpressure outcomes (`ServerUnavailable`: shed deadline,
    draining, recovering — all of which fire before the first token)
    are retryable; a fault or timeout after tokens flowed is not — the
    client has a partial completion a retry would silently duplicate,
    so it must fail loudly instead. ServerUnavailable is duck-typed by
    its `http_status` attribute to keep this module import-free.

    `trace_id` rides the record so a post-200 failure is attributable
    from the client's capture alone: the id resolves to the replica's
    flight-recorder timeline (`GET /debug/request/<id>`) and the
    tier's attempt log."""
    retryable = hasattr(exc, "http_status")
    if retryable:
        etype = "overloaded_error"
    elif isinstance(exc, ValueError):
        etype = "invalid_request_error"
    elif isinstance(exc, TimeoutError):
        etype = "timeout_error"
    else:
        etype = "server_error"
    err: Dict[str, Any] = {"message": str(exc), "type": etype,
                           "retryable": retryable}
    if trace_id is not None:
        err["trace_id"] = trace_id
    return {"error": err}


def _check_unsupported(payload: dict):
    for key, neutral in (
        ("suffix", (None, "")),
    ):
        if key in payload and payload[key] not in neutral:
            _bad(
                f"{key}={payload[key]!r} is not supported by this server "
                "(only the neutral value is accepted)"
            )


def _common_sampling(payload: dict, native: dict):
    if payload.get("temperature") is not None:
        native["temperature"] = float(payload["temperature"])
    if payload.get("top_p") is not None:
        native["top_p"] = float(payload["top_p"])
    if payload.get("top_k") is not None:  # OpenAI-adjacent extension
        native["top_k"] = payload["top_k"]
    if payload.get("seed") is not None:
        native["seed"] = int(payload["seed"])
    stop = payload.get("stop")
    if stop is not None:
        native["stop"] = [stop] if isinstance(stop, str) else list(stop)
    if payload.get("max_tokens") is not None:
        native["max_new"] = int(payload["max_tokens"])
    if payload.get("max_completion_tokens") is not None:
        native["max_new"] = int(payload["max_completion_tokens"])
    n = payload.get("n")
    if n is not None:
        native["n"] = int(n)
    if payload.get("best_of") is not None:
        native["best_of"] = int(payload["best_of"])
    if payload.get("logit_bias") is not None:
        native["logit_bias"] = payload["logit_bias"]
    for key in ("presence_penalty", "frequency_penalty"):
        if payload.get(key) is not None:
            native[key] = float(payload[key])
    if payload.get("num_beams") is not None:
        # OpenAI-adjacent extension (like top_k): deterministic beam
        # search; the ranked beams come back as the choices, each with
        # a `beam_score`. Composes with response_format constraints.
        native["num_beams"] = int(payload["num_beams"])
        if payload.get("length_penalty") is not None:
            native["length_penalty"] = float(payload["length_penalty"])
    if payload.get("timeout") is not None:
        # Native extension: the request deadline. The serving tier
        # forwards each attempt's REMAINING budget through this field
        # so the replica's deadline shedder agrees with the tier on
        # when the request stops being worth prefilling — dropping it
        # here would leave OpenAI-route requests deadline-less on the
        # replica while the tier has already given up and retried.
        native["timeout"] = float(payload["timeout"])
    rf = payload.get("response_format")
    if rf is not None:
        t = rf.get("type") if isinstance(rf, dict) else None
        if t == "json_object":
            native["constraint"] = {"json_object": True}
        elif t == "json_schema":
            js = (rf.get("json_schema") or {})
            schema = js.get("schema") if isinstance(js, dict) else None
            if schema is None:
                _bad(
                    'response_format.json_schema needs a "json_schema": '
                    '{"schema": {...}} block'
                )
            native["constraint"] = {"json_schema": schema}
        elif t not in (None, "text"):
            _bad(f"response_format type {t!r} not supported "
                 "(text, json_object, json_schema)")
    if payload.get("stream"):
        native["stream"] = True


def completion_to_native(payload: dict, tokenizer) -> dict:
    """/v1/completions -> native /generate payload."""
    _check_unsupported(payload)
    for key in ("tools", "tool_choice", "parallel_tool_calls"):
        if payload.get(key) is not None:
            _bad(f"{key} is a chat-completions parameter")
    prompt = payload.get("prompt")
    if prompt is None:
        _bad('"prompt" is required')
    native: Dict[str, Any] = {}
    if isinstance(prompt, str):
        if tokenizer is None:
            _bad("string prompts need a server-side tokenizer")
        native["text"] = prompt
    elif isinstance(prompt, list) and all(
        isinstance(t, int) for t in prompt
    ):
        native["tokens"] = prompt
    else:
        _bad(
            "prompt must be a string or a flat token-id list "
            "(batched prompts are not supported)"
        )
    if payload.get("echo"):
        # Echo returns the prompt in the completion text; with logprobs
        # it additionally scores every prompt token (the engine's
        # prompt_logprobs path). Identity checks: logprobs=0 is a valid
        # OpenAI value (0 == False would silently skip it).
        native["echo"] = True
        _lp = payload.get("logprobs")
        if _lp is not None and _lp is not False:
            native["prompt_logprobs"] = True
    lp = payload.get("logprobs")
    if lp is not None and lp is not False:
        # OpenAI's int-valued logprobs asks for top-k alternatives per
        # position; the engine records them when built with
        # --top-logprobs (the server validates k against that cap).
        # NOTE True == 1 in Python: test booleans FIRST or integer 1
        # would never reach the alternatives branch.
        if lp is True or (not isinstance(lp, bool) and lp == 0):
            native["logprobs"] = True
        elif (not isinstance(lp, bool) and isinstance(lp, int)
              and 1 <= lp <= 5):
            # OpenAI semantics: integer N = the N most-likely tokens
            # per position, N=1 included. "soft": a server that
            # records no alternatives serves N=1 in the pre-top_k
            # sense (chosen token only) instead of 400ing a request
            # shape that always worked.
            native["logprobs"] = True
            native["top_logprobs"] = lp
            native["top_logprobs_soft"] = True
        else:
            _bad(
                f"logprobs={lp!r}: use true/0..5 (k alternatives need "
                "a server built with --top-logprobs >= k)"
            )
    _common_sampling(payload, native)
    return native


# Minimal readable chat rendering for tokenizers without a template
# (the byte tokenizer): stable markers, trailing generation prompt.
_FALLBACK_TEMPLATE_ROLES = ("system", "user", "assistant", "tool")


def render_chat(messages: List[dict], tokenizer,
                tools: Optional[List[dict]] = None) -> str:
    """Messages -> prompt text, via the tokenizer's chat template when
    it has one (HF tokenizers), else a plain fallback format.

    Tool-aware: `tools` (validated function specs) render as a leading
    system turn stating the wire contract (the sentinel + calls-array
    surface the tool grammar enforces — stated explicitly even over an
    HF template, whose own tool format the DFA cannot see). History
    messages compose the other direction: an assistant turn carrying
    `tool_calls` renders back into the exact surface the model emits,
    and `tool` turns carry their `tool_call_id` inline."""
    if not messages:
        _bad('"messages" must be non-empty')
    def content_text(m):
        c = m["content"]
        if isinstance(c, str):
            return c
        if isinstance(c, list):
            # OpenAI content-parts form: text parts concatenate;
            # anything else (images, audio) is refused, not repr()'d
            # into the prompt.
            texts = []
            for part in c:
                if not isinstance(part, dict) or part.get("type") != "text":
                    _bad(
                        "only text content parts are supported; got "
                        f"{part.get('type') if isinstance(part, dict) else part!r}"
                    )
                texts.append(part["text"])
            return "".join(texts)
        _bad(f"message content must be a string or parts list, got {c!r}")

    norm = []
    for m in messages:
        if not isinstance(m, dict) or "role" not in m:
            _bad('each message needs "role" and "content"')
        role = m["role"]
        if role not in _FALLBACK_TEMPLATE_ROLES:
            _bad(f"unknown role {role!r}")
        if role == "assistant" and m.get("tool_calls"):
            # Multi-turn agentic history: the model sees its own past
            # calls in the format it produces (content, when present,
            # precedes them — the "auto" text+call case).
            from shellac_tpu.inference.tools import render_tool_calls

            text = content_text(m) if m.get("content") is not None else ""
            calls = render_tool_calls(m["tool_calls"])
            norm.append({"role": role,
                         "content": (text + "\n" + calls) if text
                         else calls})
            continue
        if m.get("content") is None:
            _bad('each message needs "role" and "content" (content may '
                 'be omitted only on assistant turns with tool_calls)')
        text = content_text(m)
        if role == "tool" and m.get("tool_call_id"):
            text = f"[{m['tool_call_id']}] {text}"
        norm.append({"role": role, "content": text})
    if tools:
        from shellac_tpu.inference.tools import tools_prompt_block

        norm.insert(0, {"role": "system",
                        "content": tools_prompt_block(tools)})
    hf_tok = getattr(tokenizer, "_tok", None)
    if hf_tok is not None and getattr(hf_tok, "chat_template", None):
        try:
            return hf_tok.apply_chat_template(
                norm, tokenize=False, add_generation_prompt=True
            )
        except Exception as e:
            # A template without a `tool` role (or other rendering
            # fault) must surface as a 400, not a 500.
            _bad(f"chat template failed to render: {e}")
    parts = [f"<|{m['role']}|>\n{m['content']}\n" for m in norm]
    return "".join(parts) + "<|assistant|>\n"


def chat_to_native(payload: dict, tokenizer) -> dict:
    """/v1/chat/completions -> native /generate payload."""
    _check_unsupported(payload)
    if tokenizer is None:
        _bad("chat completions need a server-side tokenizer")
    # Tool calling: validate the OpenAI shapes here (clean 400s),
    # render the tool definitions into the prompt, and forward the
    # keys verbatim — the server compiles the grammar through its DFA
    # cache and parses the constrained output back into tool_calls.
    from shellac_tpu.inference.tools import parse_payload_tools

    tool_ctx = parse_payload_tools(payload)
    native: Dict[str, Any] = {
        "text": render_chat(
            payload.get("messages"), tokenizer,
            tools=tool_ctx.functions if tool_ctx is not None else None,
        )
    }
    if tool_ctx is not None:
        native["tools"] = payload["tools"]
        if payload.get("tool_choice") is not None:
            native["tool_choice"] = payload["tool_choice"]
        if payload.get("parallel_tool_calls") is not None:
            native["parallel_tool_calls"] = payload["parallel_tool_calls"]
    if payload.get("logprobs"):
        native["logprobs"] = True
    tl = payload.get("top_logprobs")
    if tl not in (None, 0):
        if not payload.get("logprobs"):
            _bad("top_logprobs needs logprobs=true")
        native["top_logprobs"] = int(tl)
    if payload.get("echo"):
        _bad("echo is a completions-API parameter")
    if payload.get("best_of") is not None:
        _bad("best_of is a completions-API parameter")
    _common_sampling(payload, native)
    return native


def _finish_reason(tokens: list, max_new: int) -> str:
    return "length" if len(tokens) >= max_new else "stop"


def _usage(prompt_tokens: int, completions: List[list]) -> dict:
    out = sum(len(c) for c in completions)
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": out,
        "total_tokens": prompt_tokens + out,
    }


def _lp_block(tokens, lps, tokenizer, tlp=None):
    def tok(t):
        return tokenizer.decode([t]) if tokenizer else str(t)

    top = None
    if tlp is not None:
        # Per position: {token_str: logprob} over the k alternatives
        # (the classic completions-API shape). Distinct ids can decode
        # to the same string (untrained specials, byte fragments) — a
        # plain dict comprehension would silently drop entries, so
        # collide onto an id-tagged key instead.
        def entry_dict(entries):
            d = {}
            for e in entries:
                key = tok(e["id"])
                if not key or key in d:
                    key = f"{key}<id:{e['id']}>"
                d[key] = e["logprob"]
            return d

        top = [entry_dict(entries) for entries in tlp]
    return {
        "tokens": [tok(t) for t in tokens],
        "token_logprobs": list(lps),
        "top_logprobs": top,
        "text_offset": None,
    }


def _chat_content(tokens, lps, tlp, tokenizer):
    """OpenAI chat logprobs content list: {token, logprob[,
    top_logprobs]} per position — the ONE builder both the blocking
    response and the SSE finish chunk use."""
    def tok(t):
        return tokenizer.decode([t]) if tokenizer else str(t)

    content = []
    for j, (t, l) in enumerate(zip(tokens, lps)):
        item = {"token": t, "logprob": l}
        if tlp is not None:
            item["top_logprobs"] = [
                {"token": tok(e["id"]), "logprob": e["logprob"]}
                for e in tlp[j]
            ]
        content.append(item)
    return content


def completion_response(
    native_result: dict, *, model: str, prompt_tokens: int, max_new: int,
    tokenizer, chat: bool, echo: bool = False, prompt_ids=None,
) -> dict:
    """Native handle() result -> OpenAI response object.

    echo (completions only): the prompt text prepends each choice's
    text, and — when the native result carries prompt_logprobs — the
    logprobs block covers prompt tokens too (first token null, the
    OpenAI convention)."""
    raw_choices = native_result.get("choices") or [native_result]
    choices = []
    prompt_text = ""
    if echo and prompt_ids is not None:
        prompt_text = (tokenizer.decode(prompt_ids) if tokenizer
                       else str(prompt_ids))
    for i, c in enumerate(raw_choices):
        toks = c["tokens"]
        text = c.get("text")
        if text is None:
            text = tokenizer.decode(toks) if tokenizer else str(toks)
        entry: Dict[str, Any] = {
            "index": i,
            "finish_reason": _finish_reason(toks, max_new),
        }
        if "beam_score" in c:
            # num_beams extension: the beam's length-penalized score
            # rides its choice.
            entry["beam_score"] = c["beam_score"]
        if chat:
            if c.get("tool_calls") is not None:
                # The DFA-constrained tool branch parsed back into
                # calls: OpenAI shape is a null-content assistant
                # message + finish_reason "tool_calls" (it wins over
                # length/stop — the parse only succeeds on a COMPLETE
                # calls array).
                entry["message"] = {"role": "assistant", "content": None,
                                    "tool_calls": c["tool_calls"]}
                entry["finish_reason"] = "tool_calls"
            else:
                # Tool-enabled requests carry the decided free text in
                # "content" (== the raw text; a truncated tool branch
                # falls back here rather than fabricating a call).
                entry["message"] = {"role": "assistant",
                                    "content": c.get("content", text)}
        else:
            entry["text"] = (prompt_text + text) if echo else text
        if c.get("logprobs") is not None:
            tlp = c.get("top_logprobs")
            lp = _lp_block(toks, c["logprobs"], tokenizer, tlp=tlp)
            if echo and native_result.get("prompt_logprobs") is not None:
                plp = native_result["prompt_logprobs"]
                pl = _lp_block(prompt_ids or [], plp, tokenizer)
                lp = {
                    "tokens": pl["tokens"] + lp["tokens"],
                    "token_logprobs": (pl["token_logprobs"]
                                       + lp["token_logprobs"]),
                    "top_logprobs": ([None] * len(pl["tokens"])
                                     + lp["top_logprobs"]
                                     if lp["top_logprobs"] else None),
                    "text_offset": None,
                }
            if chat:
                entry["logprobs"] = {"content": _chat_content(
                    lp["tokens"], lp["token_logprobs"], tlp, tokenizer
                )}
            else:
                entry["logprobs"] = lp
        choices.append(entry)
    return {
        "id": ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24],
        "object": "chat.completion" if chat else "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": _usage(prompt_tokens, [c["tokens"] for c in raw_choices]),
    }


class StreamTranslator:
    """Accumulates native stream records into OpenAI SSE chunk objects.

    Text deltas come from cumulative decode (decode(all) minus what was
    already emitted) so multi-token characters never split mid-byte.

    tool_mode (chat with tools, tool_choice != "none"): the native
    records' `tool_stream` field — produced by the server's ONE
    incremental scanner — replaces the raw-text delta path entirely:
    decided free text arrives as `delta.content`, call fragments as
    OpenAI `delta.tool_calls` items, and a final record carrying the
    complete `tool_calls` finishes with `finish_reason: "tool_calls"`.
    """

    def __init__(self, *, model: str, tokenizer, chat: bool,
                 tool_mode: bool = False):
        self.model = model
        self.tokenizer = tokenizer
        self.chat = chat
        self.tool_mode = tool_mode
        self.id = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        self.created = int(time.time())
        self._tokens: List[int] = []
        self._emitted = ""
        self.first = True

    def _chunk(self, delta_text: Optional[str], finish: Optional[str]):
        if self.chat:
            delta: Dict[str, Any] = {}
            if self.first and delta_text is not None:
                delta["role"] = "assistant"
            if delta_text:
                delta["content"] = delta_text
            choice = {"index": 0, "delta": delta, "finish_reason": finish}
        else:
            choice = {
                "index": 0, "text": delta_text or "", "finish_reason": finish,
            }
        self.first = False
        return {
            "id": self.id,
            "object": ("chat.completion.chunk" if self.chat
                       else "text_completion"),
            "created": self.created,
            "model": self.model,
            "choices": [choice],
        }

    def _tool_chunk(self, tool_stream: Optional[dict],
                    finish: Optional[str] = None):
        delta: Dict[str, Any] = {}
        if self.first and finish is None:
            delta["role"] = "assistant"
        if tool_stream:
            if tool_stream.get("content"):
                delta["content"] = tool_stream["content"]
            if tool_stream.get("tool_calls"):
                delta["tool_calls"] = tool_stream["tool_calls"]
        choice = {"index": 0, "delta": delta, "finish_reason": finish}
        self.first = False
        return {
            "id": self.id,
            "object": "chat.completion.chunk",
            "created": self.created,
            "model": self.model,
            "choices": [choice],
        }

    def _feed_tools(self, record: dict, max_new: int):
        out = []
        ts = record.get("tool_stream")
        if ts:
            out.append(self._tool_chunk(ts))
        if not record.get("done"):
            return out
        self._tokens = list(record["tokens"])
        finish = self._tool_chunk(
            None,
            ("tool_calls" if record.get("tool_calls") is not None
             else _finish_reason(self._tokens, max_new)),
        )
        if record.get("logprobs") is not None:
            tlp = record.get("top_logprobs")
            lp = _lp_block(self._tokens, record["logprobs"],
                           self.tokenizer, tlp=tlp)
            finish["choices"][0]["logprobs"] = {
                "content": _chat_content(
                    lp["tokens"], lp["token_logprobs"], tlp,
                    self.tokenizer,
                )
            }
        out.append(finish)
        return out

    def feed(self, record: dict, max_new: int):
        """Native stream record -> list of SSE chunk objects."""
        if self.tool_mode:
            return self._feed_tools(record, max_new)
        if record.get("done"):
            # The engine's final record carries the authoritative token
            # list (stop-sequence holdback may have trimmed the tail).
            self._tokens = list(record["tokens"])
            out = []
            if self.tokenizer is not None:
                text = self.tokenizer.decode(self._tokens)
                if len(text) > len(self._emitted):
                    out.append(self._chunk(text[len(self._emitted):], None))
                    self._emitted = text
            # else: per-delta debug strings are not prefix-additive, so
            # there is no reconcilable tail to emit.
            finish = self._chunk(
                None, _finish_reason(self._tokens, max_new)
            )
            if record.get("logprobs") is not None:
                # Requested logprobs ride the finish chunk (the engine
                # delivers them once, on the final record).
                tlp = record.get("top_logprobs")
                lp = _lp_block(self._tokens, record["logprobs"],
                               self.tokenizer, tlp=tlp)
                if self.chat:
                    finish["choices"][0]["logprobs"] = {
                        "content": _chat_content(
                            lp["tokens"], lp["token_logprobs"], tlp,
                            self.tokenizer,
                        )
                    }
                else:
                    finish["choices"][0]["logprobs"] = lp
            out.append(finish)
            return out
        self._tokens.extend(record["tokens"])
        if self.tokenizer is None:
            return [self._chunk(str(record["tokens"]), None)]
        text = self.tokenizer.decode(self._tokens)
        if len(text) <= len(self._emitted):
            return []
        delta, self._emitted = text[len(self._emitted):], text
        return [self._chunk(delta, None)]
