"""KV cache for autoregressive decoding.

Layout: stacked over layers, (L, B, max_len, Hkv, Dh), matching the
stacked-layer parameter layout so the decode forward remains a single
`lax.scan`. The cache lives in compute dtype (bf16): it is read-only
bandwidth, and attention logits accumulate in fp32 regardless.

Ragged batches are handled with per-sequence `lengths`: prompts are
right-padded and written from offset 0; `lengths` records how many slots
are real. Decode writes each sequence's next token at its own length
(vmapped dynamic_update_slice), overwriting stale pad slots, so position
ids stay continuous per sequence and pads are never attended.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig


@flax.struct.dataclass
class KVCache:
    k: Any  # (L, B, max_len, Hkv, Dh)
    v: Any  # (L, B, max_len, Hkv, Dh)
    lengths: Any  # (B,) int32 — valid positions per sequence

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.dim_per_head)
    return KVCache(
        k=jnp.zeros(shape, cfg.compute_dtype),
        v=jnp.zeros(shape, cfg.compute_dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def cache_logical_axes():
    """Logical axes for sharding the cache over a mesh."""
    return KVCache(
        k=("layers", "batch", None, "kv_heads", None),
        v=("layers", "batch", None, "kv_heads", None),
        lengths=("batch",),
    )


def update_layer(
    cache_k: jax.Array,  # (B, max_len, Hkv, Dh) — one layer's cache
    cache_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) int32 — per-sequence write offset
):
    """Write S new positions at per-sequence offsets; returns (k, v)."""
    k_new = k_new.astype(cache_k.dtype)
    v_new = v_new.astype(cache_v.dtype)

    def upd(c, n, i):
        return jax.lax.dynamic_update_slice(c, n, (i, 0, 0))

    ck = jax.vmap(upd)(cache_k, k_new, index)
    cv = jax.vmap(upd)(cache_v, v_new, index)
    return ck, cv


# ---------------------------------------------------------------------------
# Paged cache (block pool + per-sequence block tables)
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class PagedKVCache:
    """Block-pool KV cache: slots map to pool blocks via tables.

    A dense slot cache reserves max_len for every slot; the pool is
    sized to the *total* tokens actually resident, so many short
    requests and a few long ones share memory. Block allocation is a
    host-side free list (see PagedBatchingEngine); the device side only
    ever sees the tables.

    k, v: (L, n_blocks, block_size, Hkv, Dh)
    tables: (n_slots, max_blocks) int32 — pool block id per logical
        block; unallocated entries MUST point at block 0 (reserved as
        scratch: it is never handed to a slot, so stray writes and reads
        through unallocated table entries land there harmlessly).
    lengths: (n_slots,) int32 — valid tokens per slot.
    """

    k: Any
    v: Any
    tables: Any
    lengths: Any

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def max_blocks(self) -> int:
        return self.tables.shape[1]


def init_paged_cache(
    cfg: ModelConfig,
    n_slots: int,
    n_blocks: int,
    block_size: int,
    max_blocks_per_slot: int,
) -> PagedKVCache:
    shape = (cfg.n_layers, n_blocks, block_size, cfg.kv_heads, cfg.dim_per_head)
    return PagedKVCache(
        k=jnp.zeros(shape, cfg.compute_dtype),
        v=jnp.zeros(shape, cfg.compute_dtype),
        tables=jnp.zeros((n_slots, max_blocks_per_slot), jnp.int32),
        lengths=jnp.zeros((n_slots,), jnp.int32),
    )


def paged_update_layer(
    pool_k: jax.Array,  # (n_blocks, bs, Hkv, Dh) — one layer's pool
    pool_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) — per-slot write offsets (token positions)
    tables: jax.Array,  # (B, max_blocks) int32
):
    """Scatter S new positions through the block tables; returns pools.

    Positions index[b] + i map to pool coords
    (tables[b, p // bs], p % bs). Slots must have blocks allocated for
    every written position (the scheduler guarantees it); writes through
    unallocated entries land in scratch block 0.
    """
    bs = pool_k.shape[1]
    b, s = k_new.shape[:2]
    pos = index[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]  # (B, S)
    block_ids = jnp.take_along_axis(tables, pos // bs, axis=1)  # (B, S)
    offs = pos % bs
    flat_blocks = block_ids.reshape(-1)
    flat_offs = offs.reshape(-1)
    pk = pool_k.at[flat_blocks, flat_offs].set(
        k_new.astype(pool_k.dtype).reshape(b * s, *k_new.shape[2:])
    )
    pv = pool_v.at[flat_blocks, flat_offs].set(
        v_new.astype(pool_v.dtype).reshape(b * s, *v_new.shape[2:])
    )
    return pk, pv


def paged_gather_layer(
    pool_k: jax.Array,  # (n_blocks, bs, Hkv, Dh)
    pool_v: jax.Array,
    tables: jax.Array,  # (B, max_blocks)
):
    """Materialize each slot's logical KV view: (B, max_blocks*bs, H, D)."""
    b, mb = tables.shape
    bs = pool_k.shape[1]
    k = jnp.take(pool_k, tables.reshape(-1), axis=0)
    v = jnp.take(pool_v, tables.reshape(-1), axis=0)
    k = k.reshape(b, mb * bs, *pool_k.shape[2:])
    v = v.reshape(b, mb * bs, *pool_v.shape[2:])
    return k, v
