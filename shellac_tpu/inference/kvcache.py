"""KV cache for autoregressive decoding.

Layout: stacked over layers and HEAD-MAJOR, (L, B, Hkv, max_len, Dh).
Stacking over layers matches the stacked-layer parameter layout so the
decode forward remains a single `lax.scan`. Head-major (head before
sequence) is a hard requirement of the compiled Pallas decode kernels:
Mosaic block shapes must keep the last two dims tileable, so the kv
stream a kernel DMAs has to be a contiguous (seq_block, head_dim) tile
per head — with seq-major layout the head axis lands second-to-last
with block size 1, which the TPU lowering rejects (and a relayout copy
of a multi-GiB cache every tick is exactly what the kernel exists to
avoid). The cache lives in compute dtype (bf16): it is read-only
bandwidth, and attention logits accumulate in fp32 regardless.

Ragged batches are handled with per-sequence `lengths`: prompts are
right-padded and written from offset 0; `lengths` records how many slots
are real. Decode writes each sequence's next token at its own length
(vmapped dynamic_update_slice), overwriting stale pad slots, so position
ids stay continuous per sequence and pads are never attended.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig


@flax.struct.dataclass
class KVCache:
    k: Any  # (L, B, Hkv, max_len, Dh)
    v: Any  # (L, B, Hkv, max_len, Dh)
    lengths: Any  # (B,) int32 — valid positions per sequence

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, cfg.kv_heads, max_len, cfg.dim_per_head)
    return KVCache(
        k=jnp.zeros(shape, cfg.compute_dtype),
        v=jnp.zeros(shape, cfg.compute_dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def cache_logical_axes():
    """Logical axes for sharding the cache over a mesh."""
    return KVCache(
        k=("layers", "batch", "kv_heads", None, None),
        v=("layers", "batch", "kv_heads", None, None),
        lengths=("batch",),
    )


def paged_cache_logical_axes():
    """Logical axes for sharding a paged cache over a mesh.

    The KV pools shard over kv_heads (tensor parallelism), same as the
    dense cache; the block axis is scheduler-addressed (host-side free
    list picks arbitrary block ids) so it stays unsharded, and the
    tables/lengths are tiny scheduler metadata, replicated.
    """
    return PagedKVCache(
        k=("layers", None, "kv_heads", None, None),
        v=("layers", None, "kv_heads", None, None),
        tables=(None, None),
        lengths=(None,),
    )


def update_layer(
    cache_k: jax.Array,  # (B, Hkv, max_len, Dh) — one layer's cache
    cache_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) int32 — per-sequence write offset
):
    """Write S new positions at per-sequence offsets; returns (k, v)."""
    k_new = k_new.astype(cache_k.dtype).transpose(0, 2, 1, 3)  # (B,Hkv,S,Dh)
    v_new = v_new.astype(cache_v.dtype).transpose(0, 2, 1, 3)

    def upd(c, n, i):
        return jax.lax.dynamic_update_slice(c, n, (0, i, 0))

    ck = jax.vmap(upd)(cache_k, k_new, index)
    cv = jax.vmap(upd)(cache_v, v_new, index)
    return ck, cv


# ---------------------------------------------------------------------------
# Paged cache (block pool + per-sequence block tables)
# ---------------------------------------------------------------------------


@flax.struct.dataclass
class PagedKVCache:
    """Block-pool KV cache: slots map to pool blocks via tables.

    A dense slot cache reserves max_len for every slot; the pool is
    sized to the *total* tokens actually resident, so many short
    requests and a few long ones share memory. Block allocation is a
    host-side free list (see PagedBatchingEngine); the device side only
    ever sees the tables.

    k, v: (L, n_blocks, Hkv, block_size, Dh) — head-major inside each
        block, same Pallas tiling requirement as the dense cache.
    tables: (n_slots, max_blocks) int32 — pool block id per logical
        block; unallocated entries MUST point at block 0 (reserved as
        scratch: it is never handed to a slot, so stray writes and reads
        through unallocated table entries land there harmlessly).
    lengths: (n_slots,) int32 — valid tokens per slot.
    """

    k: Any
    v: Any
    tables: Any
    lengths: Any

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_blocks(self) -> int:
        return self.tables.shape[1]


def init_paged_cache(
    cfg: ModelConfig,
    n_slots: int,
    n_blocks: int,
    block_size: int,
    max_blocks_per_slot: int,
) -> PagedKVCache:
    shape = (
        cfg.n_layers, n_blocks, cfg.kv_heads, block_size, cfg.dim_per_head,
    )
    return PagedKVCache(
        k=jnp.zeros(shape, cfg.compute_dtype),
        v=jnp.zeros(shape, cfg.compute_dtype),
        tables=jnp.zeros((n_slots, max_blocks_per_slot), jnp.int32),
        lengths=jnp.zeros((n_slots,), jnp.int32),
    )


def paged_update_layer(
    pool_k: jax.Array,  # (n_blocks, Hkv, bs, Dh) — one layer's pool
    pool_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) — per-slot write offsets (token positions)
    tables: jax.Array,  # (B, max_blocks) int32
):
    """Scatter S new positions through the block tables; returns pools.

    Positions index[b] + i map to pool coords
    (tables[b, p // bs], :, p % bs). Slots must have blocks allocated
    for every written position (the scheduler guarantees it); writes
    through unallocated entries land in scratch block 0.
    """
    bs = pool_k.shape[2]
    b, s = k_new.shape[:2]
    pos = index[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]  # (B, S)
    block_ids = jnp.take_along_axis(tables, pos // bs, axis=1)  # (B, S)
    offs = pos % bs
    flat_blocks = block_ids.reshape(-1)
    flat_offs = offs.reshape(-1)
    # Advanced indices at dims 0 and 2 (separated by the head slice):
    # the indexed result is (B*S, Hkv, Dh), matching k_new's token rows.
    pk = pool_k.at[flat_blocks, :, flat_offs].set(
        k_new.astype(pool_k.dtype).reshape(b * s, *k_new.shape[2:])
    )
    pv = pool_v.at[flat_blocks, :, flat_offs].set(
        v_new.astype(pool_v.dtype).reshape(b * s, *v_new.shape[2:])
    )
    return pk, pv


def paged_gather_layer(
    pool_k: jax.Array,  # (n_blocks, Hkv, bs, Dh)
    pool_v: jax.Array,
    tables: jax.Array,  # (B, max_blocks)
):
    """Materialize each slot's logical KV view, head-major:
    (B, Hkv, max_blocks*bs, D) — the same layout as a dense cache layer,
    so the decode fallback consumes it directly."""
    b, mb = tables.shape
    hkv, bs, dh = pool_k.shape[1:]

    def gather(pool):
        x = jnp.take(pool, tables.reshape(-1), axis=0)  # (B*mb, Hkv, bs, Dh)
        x = x.reshape(b, mb, hkv, bs, dh).transpose(0, 2, 1, 3, 4)
        return x.reshape(b, hkv, mb * bs, dh)

    return gather(pool_k), gather(pool_v)
