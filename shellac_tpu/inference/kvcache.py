"""Compatibility shim: the cache layouts moved into the
`shellac_tpu.inference.cache` subsystem (cache/layout.py holds the
pytrees and update/gather free functions; cache/{dense,paged,rolling}.py
hold the CacheBackend storage policies the engines plug in).

Every public name keeps resolving from here so existing imports —
engines, kernels, tests, external callers — stay valid.
"""

from shellac_tpu.inference.cache.layout import *  # noqa: F401,F403
from shellac_tpu.inference.cache.layout import (  # noqa: F401
    cache_logical_axes,
    cache_logical_axes_for,
    init_cache,
    init_cache_for,
    init_paged_cache,
    init_quant_cache,
    init_quant_paged_cache,
    kv_field_names,
    paged_cache_logical_axes,
    paged_gather_layer,
    paged_gather_scales,
    paged_update_layer,
    quant_cache_logical_axes,
    quant_paged_cache_logical_axes,
    quant_paged_update_layer,
    quant_roll_update_layer,
    quant_update_layer,
    quantize_kv,
    roll_update_layer,
    rolled_kv_positions,
    scatter_slot,
    slot_view,
    update_layer,
)
