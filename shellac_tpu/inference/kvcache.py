"""KV cache for autoregressive decoding.

Layout: stacked over layers, (L, B, max_len, Hkv, Dh), matching the
stacked-layer parameter layout so the decode forward remains a single
`lax.scan`. The cache lives in compute dtype (bf16): it is read-only
bandwidth, and attention logits accumulate in fp32 regardless.

Ragged batches are handled with per-sequence `lengths`: prompts are
right-padded and written from offset 0; `lengths` records how many slots
are real. Decode writes each sequence's next token at its own length
(vmapped dynamic_update_slice), overwriting stale pad slots, so position
ids stay continuous per sequence and pads are never attended.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig


@flax.struct.dataclass
class KVCache:
    k: Any  # (L, B, max_len, Hkv, Dh)
    v: Any  # (L, B, max_len, Hkv, Dh)
    lengths: Any  # (B,) int32 — valid positions per sequence

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.dim_per_head)
    return KVCache(
        k=jnp.zeros(shape, cfg.compute_dtype),
        v=jnp.zeros(shape, cfg.compute_dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def cache_logical_axes():
    """Logical axes for sharding the cache over a mesh."""
    return KVCache(
        k=("layers", "batch", None, "kv_heads", None),
        v=("layers", "batch", None, "kv_heads", None),
        lengths=("batch",),
    )


def update_layer(
    cache_k: jax.Array,  # (B, max_len, Hkv, Dh) — one layer's cache
    cache_v: jax.Array,
    k_new: jax.Array,  # (B, S, Hkv, Dh)
    v_new: jax.Array,
    index: jax.Array,  # (B,) int32 — per-sequence write offset
):
    """Write S new positions at per-sequence offsets; returns (k, v)."""
    k_new = k_new.astype(cache_k.dtype)
    v_new = v_new.astype(cache_v.dtype)

    def upd(c, n, i):
        return jax.lax.dynamic_update_slice(c, n, (i, 0, 0))

    ck = jax.vmap(upd)(cache_k, k_new, index)
    cv = jax.vmap(upd)(cache_v, v_new, index)
    return ck, cv
