"""Fleet-wide KV fabric: the per-replica prefix cache, federated.

Three compounding pieces turn PR 12's one-shot KV migration into one
fleet memory hierarchy:

1. **Prefix directory** (tier-side `PrefixDirectory`): the router
   learns which replica holds which prefix hash chains from each
   replica's `GET /kv/prefixes` manifest (delta-polled on the
   health-sweep cadence, `forget()`-cleared on respawn like
   `FleetCollector`), and affinity routing scores a candidate by
   directory-measured chain overlap instead of PR 6's 4×-discounted
   guess. Tier and engine compute chain hashes with ONE shared helper
   (`shellac_tpu.inference.prefix`), so routing and cache contents key
   identically by construction. Every directory entry is a HINT: a
   stale entry (replica died since the last sweep) costs one prefix
   miss on the fallback replica, never an error.

2. **Hot-prefix replication** (`export_chain`/`seed_chain` + the
   tier's push planner): chains hot on one replica but absent on
   routable peers ship as `SHLKV1` blobs (`kind: "prefix-seed"` — pure
   KV, no request state) to `POST /kv/seed`, which registers the
   blocks refcount-0 in the receiver's prefix registry: LRU-evictable,
   never displacing live slots, allocated from free-list headroom
   only. Pushes are gated by PR 12's measured cost rule — transfer
   cost (bytes × measured bandwidth) must beat expected recompute
   (hit rate × measured `prefill_dispatch` phase cost).

3. **KV park/resume** (`KVParkStore`): `export_slot` of a frozen slot
   lands in a host-RAM/disk spool with the event spool's durability
   discipline — atomic tmp+rename write, crc32 verified at read-back
   (the `SHLKV1` chunk crcs), size-capped LRU — so a parked session
   survives replica death and resumes on ANY replica that can reach
   the spool directory.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from shellac_tpu.inference import prefix as prefix_mod
from shellac_tpu.inference.cache import PoolExhausted
from shellac_tpu.inference.disagg import (
    MigrationBlob,
    _check_exportable,
    model_fingerprint,
)
from shellac_tpu.inference.kvcache import kv_field_names

#: Header `kind` distinguishing a prefix-seed blob (pure KV, no
#: request state) from a slot-migration blob on the same wire format.
SEED_KIND = "prefix-seed"


def _check_fabric_engine(engine) -> None:
    _check_exportable(engine)
    backend = engine.cache_backend
    if not (backend.is_paged and backend.prefix_cache):
        raise ValueError(
            "prefix-seed export/import needs a paged backend with "
            f"prefix_cache=True (this engine runs {backend.name!r} "
            "without a prefix registry)"
        )


# ---------------------------------------------------------------------
# Chain export / seed (engine-owning thread on both sides)
# ---------------------------------------------------------------------


def export_chain(engine, tip: bytes,
                 trace_id: Optional[str] = None) -> MigrationBlob:
    """Serialize the cached prefix chain ending at `tip` as a
    prefix-seed blob (caller must be the engine-owning thread).
    Unlike `export_slot` this ships NO request state — just the chain
    hashes and their pool blocks, root-first — so the receiver
    registers pure cache contents. ValueError when the chain has an
    evicted link (a torn chain would seed unreachable blocks)."""
    _check_fabric_engine(engine)
    backend = engine.cache_backend
    chain, blocks = backend.chain_blocks(tip)
    header: Dict[str, Any] = {
        "kind": SEED_KIND,
        "backend": backend.name,
        "kv_quant": engine.kv_quant,
        "model": model_fingerprint(engine),
        "block_size": backend.block_size,
        "chain": [h.hex() for h in chain],
        "trace_id": trace_id,
    }
    fields = kv_field_names(engine.kv_quant)
    cache = engine._cache
    idx = jnp.asarray(blocks, jnp.int32)
    pulls = {f: getattr(cache, f)[:, idx] for f in fields}
    # ONE blocking pull for the whole chain: replication runs on the
    # admission path's margins, never the decode hot loop.
    host = jax.device_get(pulls)  # shellac: ignore[SH002] — the seed export's single batched pull; the KV must reach the host to go on the wire
    return MigrationBlob(header, {f: np.asarray(a)
                                  for f, a in host.items()})


def seed_chain(engine, blob: MigrationBlob) -> int:
    """Adopt a prefix-seed blob into this engine's prefix registry
    (caller must be the engine-owning thread). Returns the number of
    blocks actually seeded (already-registered chain links are
    skipped). Raises ValueError for a blob this engine must refuse
    (wrong kind/backend/geometry/block_size — registry untouched) and
    PoolExhausted when free-list headroom is too tight (retryable;
    seeding never evicts to make room)."""
    _check_fabric_engine(engine)
    backend = engine.cache_backend
    header = blob.header
    if header.get("kind") != SEED_KIND:
        raise ValueError(
            f"blob kind {header.get('kind')!r} is not a prefix seed"
        )
    if header.get("backend") != backend.name:
        raise ValueError(
            f"prefix-seed blob is for backend "
            f"{header.get('backend')!r}; this engine runs "
            f"{backend.name!r}"
        )
    fp = model_fingerprint(engine)
    if header.get("model") != fp:
        raise ValueError(
            f"prefix-seed blob model geometry {header.get('model')} "
            f"does not match this engine's {fp}"
        )
    if header.get("block_size") != backend.block_size:
        raise ValueError(
            f"prefix-seed blob pages are {header.get('block_size')} "
            f"tokens; this pool uses {backend.block_size}"
        )
    try:
        chain = [bytes.fromhex(h) for h in header["chain"]]
    except (KeyError, ValueError, TypeError):
        raise ValueError("prefix-seed blob carries a malformed chain")
    if not chain:
        raise ValueError("prefix-seed blob carries an empty chain")
    fields = kv_field_names(engine.kv_quant)
    for f in fields:
        arr = blob.arrays.get(f)
        if arr is None or arr.shape[1] != len(chain):
            raise ValueError(
                f"prefix-seed blob array {f!r} does not cover its "
                f"{len(chain)}-block chain"
            )

    # Seed only the missing links. Registration is root-first, so the
    # registered part of a chain is always a prefix of it; new links
    # chain onto either b"" or an already-registered parent, keeping
    # every seeded block reachable from the root at the right
    # absolute positions.
    todo = [j for j, h in enumerate(chain)
            if h not in backend._hash_to_block]
    if not todo:
        return 0
    new_blocks = backend.seed_blocks(len(todo))  # may raise PoolExhausted
    try:
        sel = np.asarray(todo, np.int64)
        idx = jnp.asarray(new_blocks, jnp.int32)
        cache = engine._cache
        new = {
            f: getattr(cache, f).at[:, idx].set(
                jnp.asarray(blob.arrays[f][:, sel])
            )
            for f in fields
        }
    except Exception:
        backend.abort_seed(new_blocks)
        raise
    engine._cache = cache.replace(**new)
    backend.commit_seed([
        (chain[j], chain[j - 1] if j else b"", blk)
        for j, blk in zip(todo, new_blocks)
    ])
    return len(todo)


# ---------------------------------------------------------------------
# Prefix directory (tier-side)
# ---------------------------------------------------------------------


class _DirEntry:
    __slots__ = ("supported", "version", "block_size", "blocks", "hot",
                 "hit_delta", "stamp")

    def __init__(self):
        self.supported: Optional[bool] = None  # None = never answered
        self.version = -1
        self.block_size = 0
        self.blocks: set = set()        # hex block hashes
        self.hot: List[Dict[str, Any]] = []
        self.hit_delta: Dict[str, int] = {}  # hex -> hits since prior poll
        self.stamp = 0.0


class PrefixDirectory:
    """Which replica holds which prefix chains — the tier's view of
    fleet cache contents, fed by `GET /kv/prefixes` manifests on the
    health-sweep cadence. Same lifecycle discipline as FleetCollector:
    one lock, `forget()` on respawn (the successor starts cold), and
    every entry treated as possibly stale — the directory ROUTES, it
    never gates correctness, so the worst a stale entry costs is one
    prefix miss."""

    #: Don't hash more prompt than the spill decision can value — the
    #: affinity value saturates at 256 tokens, so walking further buys
    #: routing nothing.
    OVERLAP_CAP_TOKENS = 512

    def __init__(self):
        self._lock = threading.Lock()
        self._by_url: Dict[str, _DirEntry] = {}

    def since(self, url: str) -> int:
        """Version to send as ?since= on the next poll of `url`."""
        with self._lock:
            ent = self._by_url.get(url)
            return ent.version if ent is not None else -1

    def observe(self, url: str, doc: Dict[str, Any]) -> None:
        """Fold one /kv/prefixes reply into the directory."""
        if not isinstance(doc, dict):
            return
        with self._lock:
            ent = self._by_url.setdefault(url, _DirEntry())
            ent.stamp = time.time()
            if not doc.get("supported"):
                ent.supported = False
                ent.blocks = set()
                ent.hot = []
                ent.hit_delta = {}
                return
            ent.supported = True
            if doc.get("unchanged"):
                return
            prev_hits = {h["h"]: int(h.get("hits", 0)) for h in ent.hot}
            ent.version = int(doc.get("version", -1))
            ent.block_size = int(doc.get("block_size", 0))
            ent.blocks = set(doc.get("blocks", ()))
            ent.hot = [h for h in doc.get("hot", ())
                       if isinstance(h, dict) and "h" in h]
            ent.hit_delta = {
                h["h"]: max(0, int(h.get("hits", 0))
                            - prev_hits.get(h["h"], 0))
                for h in ent.hot
            }

    def forget(self, url: str) -> None:
        """Respawned replica: the successor's cache starts cold, so
        the predecessor's advertised contents must stop routing."""
        with self._lock:
            self._by_url.pop(url, None)

    def overlap(self, url: str, tokens: Any) -> int:
        """Directory-measured shared-prefix tokens between a prompt's
        token list and `url`'s advertised cache contents: chain-hash
        the prompt head with the replica's own block size and walk
        until a link the replica does not hold. 0 when the replica
        never answered, does not support manifests, or holds
        nothing."""
        with self._lock:
            ent = self._by_url.get(url)
            if (ent is None or not ent.supported or not ent.blocks
                    or ent.block_size <= 0):
                return 0
            bs = ent.block_size
            blocks = ent.blocks
        head = np.asarray(tokens[:self.OVERLAP_CAP_TOKENS], np.int32)
        m = 0
        for h in prefix_mod.chain_hashes(head, bs):
            if h.hex() not in blocks:
                break
            m += 1
        return m * bs

    def hot_chains(self) -> Dict[str, Dict[str, Any]]:
        """Fleet-wide aggregation for the replication planner:
        tip-hash hex -> {hits, delta, depth, block_size, holders}."""
        agg: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for url, ent in self._by_url.items():
                if not ent.supported:
                    continue
                for h in ent.hot:
                    hh = h["h"]
                    row = agg.setdefault(hh, {
                        "hits": 0, "delta": 0, "depth": 0,
                        "block_size": ent.block_size, "holders": [],
                    })
                    row["hits"] += int(h.get("hits", 0))
                    row["delta"] += ent.hit_delta.get(hh, 0)
                    row["depth"] = max(row["depth"],
                                       int(h.get("depth", 0)))
                    row["holders"].append(url)
        return agg

    def holds(self, url: str, tip_hex: str) -> bool:
        with self._lock:
            ent = self._by_url.get(url)
            return (ent is not None and bool(ent.supported)
                    and tip_hex in ent.blocks)

    def supported(self, url: str) -> bool:
        """True only for a replica that has POSITIVELY advertised a
        prefix registry — a never-answered peer is not a push target."""
        with self._lock:
            ent = self._by_url.get(url)
            return ent is not None and bool(ent.supported)

    def distinct_blocks(self) -> int:
        """Distinct block hashes known fleet-wide (the directory-size
        gauge)."""
        with self._lock:
            seen: set = set()
            for ent in self._by_url.values():
                seen |= ent.blocks
            return len(seen)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                url: {
                    "supported": ent.supported,
                    "version": ent.version,
                    "blocks": len(ent.blocks),
                    "hot": len(ent.hot),
                    "age_s": round(time.time() - ent.stamp, 3),
                }
                for url, ent in self._by_url.items()
            }


# ---------------------------------------------------------------------
# KV park spool (replica-side; directory shared across the fleet)
# ---------------------------------------------------------------------


class KVParkStore:
    """Durable spool for parked KV sessions: serialized `SHLKV1` blobs
    under one directory (shared across replicas, e.g. NFS or a local
    disk both processes mount), with the event spool's durability
    discipline — atomic tmp+rename writes so a crash mid-park leaves
    no half blob under a final name, crc verification at read-back
    (the blob's own chunk crc32s via `MigrationBlob.deserialize`), and
    a size-capped LRU that trims oldest-parked first."""

    SUFFIX = ".shlkv"

    def __init__(self, park_dir: str, max_bytes: int = 256 << 20):
        self.park_dir = park_dir
        self.max_bytes = max_bytes
        self.write_errors = 0
        self.torn_reads = 0
        self._lock = threading.Lock()
        os.makedirs(park_dir, exist_ok=True)

    def _path(self, park_id: str) -> str:
        if not park_id or not all(
                c.isalnum() or c in "-_" for c in park_id):
            raise ValueError(f"bad park id {park_id!r}")
        return os.path.join(self.park_dir, park_id + self.SUFFIX)

    def put(self, park_id: str, data: bytes) -> str:
        """Atomically spool one serialized blob; trims LRU past the
        size cap AFTER the write so the new park is never the victim
        of its own admission. OSError propagates — a park that did not
        land durably must fail loudly, not report success."""
        path = self._path(park_id)
        tmp = path + ".tmp"
        with self._lock:
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError:
                self.write_errors += 1
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self._trim(keep=path)
        return path

    def get(self, park_id: str) -> MigrationBlob:
        """Read + integrity-check one parked blob. KeyError when the
        id is unknown; ValueError when the file is torn or corrupt
        (counted, and the file is quarantined out of the spool so a
        bad disk sector cannot wedge every resume retry)."""
        path = self._path(park_id)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise KeyError(park_id)
        try:
            return MigrationBlob.deserialize(data)
        except ValueError:
            with self._lock:
                self.torn_reads += 1
            try:
                os.replace(path, path + ".torn")
            except OSError:
                pass
            raise

    def delete(self, park_id: str) -> None:
        try:
            os.unlink(self._path(park_id))
        except FileNotFoundError:
            pass

    def list(self) -> List[Dict[str, Any]]:
        out = []
        try:
            names = os.listdir(self.park_dir)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(self.SUFFIX):
                continue
            p = os.path.join(self.park_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append({"park_id": name[:-len(self.SUFFIX)],
                        "bytes": st.st_size, "mtime": st.st_mtime})
        return out

    def _trim(self, keep: Optional[str] = None) -> None:
        entries: List[Tuple[float, int, str]] = []
        try:
            names = os.listdir(self.park_dir)
        except OSError:
            return
        total = 0
        for name in names:
            if not name.endswith(self.SUFFIX):
                continue
            p = os.path.join(self.park_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            total += st.st_size
            entries.append((st.st_mtime, st.st_size, p))
        entries.sort()  # oldest first
        for mtime, size, p in entries:
            if total <= self.max_bytes:
                break
            if p == keep:
                continue
            try:
                os.unlink(p)
            except OSError:
                continue
            total -= size
