"""Rotary position embeddings (non-interleaved / NeoX half-rotation form).

Frequencies are computed in fp32 regardless of compute dtype: bf16 loses
precision at long positions, which shows up as attention drift past ~8k
tokens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, head_dim: int, theta: float = 10000.0):
    """cos/sin tables for given absolute positions.

    positions: int32 array, any shape (typically (B, S) or (S,)).
    Returns (cos, sin) with shape positions.shape + (head_dim // 2,), fp32.
    """
    half = head_dim // 2
    freq = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    ang = positions.astype(jnp.float32)[..., None] * freq
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope_interleaved(
    x: jax.Array, cos: jax.Array, sin: jax.Array
) -> jax.Array:
    """Rotate ADJACENT pairs of the head dim (complex/GPT-J form).

    DeepSeek's MLA rope treats (x[2i], x[2i+1]) as one complex number
    (torch.view_as_complex), unlike the half-rotation above; converted
    checkpoints only reproduce with matching pairing. Shapes as
    apply_rope: x (..., S, H, D), cos/sin broadcastable to
    (..., S, 1, D/2).
    """
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos[..., None, :]
    s = sin[..., None, :]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    # Re-interleave: (..., D/2, 2) -> (..., D)
    out = jnp.stack([out1, out2], axis=-1).reshape(*x.shape)
    return out.astype(x.dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate the head dimension of x.

    x: (..., S, H, D). cos/sin: broadcastable to (..., S, 1, D/2) — e.g.
    shape (B, S, D/2) or (S, D/2).
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    # Insert the heads axis for broadcasting.
    c = cos[..., None, :]
    s = sin[..., None, :]
    x1f = x1.astype(jnp.float32)
    x2f = x2.astype(jnp.float32)
    out1 = x1f * c - x2f * s
    out2 = x2f * c + x1f * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
