"""Rotary position embeddings (non-interleaved / NeoX half-rotation form).

Frequencies are computed in fp32 regardless of compute dtype: bf16 loses
precision at long positions, which shows up as attention drift past ~8k
tokens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, head_dim: int, theta: float = 10000.0,
                yarn=None, llama3=None, linear=None):
    """cos/sin tables for given absolute positions.

    positions: int32 array, any shape (typically (B, S) or (S,)).
    Returns (cos, sin) with shape positions.shape + (head_dim // 2,), fp32.
    With a YarnConfig the inverse frequencies blend interpolation and
    extrapolation per the NTK-by-parts recipe and the tables carry the
    attention (mscale) factor; with a Llama3RopeConfig the frequencies
    scale by wavelength band — both numerics match HF exactly. `linear`
    is classic position interpolation (HF "linear": every inverse
    frequency divides by the factor; Gemma-3 global layers).
    """
    half = head_dim // 2
    scale = 1.0
    if yarn is not None:
        freq, scale = _yarn_inv_freq(head_dim, theta, yarn)
    else:
        freq = 1.0 / (
            theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
        )
        if llama3 is not None:
            freq = _llama3_inv_freq(freq, llama3)
        if linear is not None:
            freq = freq / linear
    ang = positions.astype(jnp.float32)[..., None] * freq
    return jnp.cos(ang) * scale, jnp.sin(ang) * scale


def _llama3_inv_freq(inv_freq: jax.Array, l3):
    """Llama-3.1 banded frequency scaling (HF _compute_llama3_parameters).

    Long wavelengths divide by `factor`, short ones stay, the middle
    band interpolates by a smooth factor in old-context rotations.
    """
    import math

    old = l3.original_max_position_embeddings
    low_wl = old / l3.low_freq_factor
    high_wl = old / l3.high_freq_factor
    wavelen = 2 * math.pi / inv_freq
    scaled = jnp.where(wavelen > low_wl, inv_freq / l3.factor, inv_freq)
    smooth = (old / wavelen - l3.low_freq_factor) / (
        l3.high_freq_factor - l3.low_freq_factor
    )
    smoothed = (1 - smooth) * scaled / l3.factor + smooth * scaled
    medium = (~(wavelen < high_wl)) & (~(wavelen > low_wl))
    return jnp.where(medium, smoothed, scaled)


def _yarn_inv_freq(dim: int, base: float, yarn):
    """Yarn inverse frequencies + attention factor (static, numpy).

    Mirrors transformers' _compute_yarn_parameters step for step so
    converted long-context checkpoints (e.g. DeepSeek) reproduce HF
    logits exactly.
    """
    import math

    import numpy as np

    factor = yarn.factor
    attention_factor = yarn.attention_factor

    def get_mscale(scale, mscale=1.0):
        if scale <= 1:
            return 1.0
        return 0.1 * mscale * math.log(scale) + 1.0

    if attention_factor is None:
        if yarn.mscale and yarn.mscale_all_dim:
            attention_factor = float(
                get_mscale(factor, yarn.mscale)
                / get_mscale(factor, yarn.mscale_all_dim)
            )
        else:
            attention_factor = get_mscale(factor)

    def correction_dim(num_rot):
        return (dim * math.log(
            yarn.original_max_position_embeddings / (num_rot * 2 * math.pi)
        )) / (2 * math.log(base))

    low = correction_dim(yarn.beta_fast)
    high = correction_dim(yarn.beta_slow)
    if yarn.truncate:
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001

    pos_freqs = base ** (np.arange(0, dim, 2, dtype=np.float32) / dim)
    extrap = 1.0 / pos_freqs
    interp = 1.0 / (factor * pos_freqs)
    ramp = np.clip(
        (np.arange(dim // 2, dtype=np.float32) - low) / (high - low), 0, 1
    )
    extrap_factor = 1.0 - ramp
    inv_freq = interp * (1 - extrap_factor) + extrap * extrap_factor
    return jnp.asarray(inv_freq, jnp.float32), float(attention_factor)


def apply_rope_interleaved(
    x: jax.Array, cos: jax.Array, sin: jax.Array
) -> jax.Array:
    """Rotate ADJACENT pairs of the head dim (complex/GPT-J form).

    DeepSeek's MLA rope treats (x[2i], x[2i+1]) as one complex number
    (torch.view_as_complex), unlike the half-rotation above; converted
    checkpoints only reproduce with matching pairing. Shapes as
    apply_rope: x (..., S, H, D), cos/sin broadcastable to
    (..., S, 1, D/2).
    """
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    c = cos[..., None, :]
    s = sin[..., None, :]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    # Re-interleave: (..., D/2, 2) -> (..., D)
    out = jnp.stack([out1, out2], axis=-1).reshape(*x.shape)
    return out.astype(x.dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate the head dimension of x.

    x: (..., S, H, D). cos/sin: broadcastable to (..., S, 1, D/2) — e.g.
    shape (B, S, D/2) or (S, D/2).
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    # Insert the heads axis for broadcasting.
    c = cos[..., None, :]
    s = sin[..., None, :]
    x1f = x1.astype(jnp.float32)
    x2f = x2.astype(jnp.float32)
    out1 = x1f * c - x2f * s
    out2 = x2f * c + x1f * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
