from shellac_tpu.ops.activations import geglu, softcap, swiglu
from shellac_tpu.ops.attention import attention, attention_ref
from shellac_tpu.ops.flash_attention import flash_attention
from shellac_tpu.ops.norms import layer_norm_ref, rms_norm, rms_norm_ref
from shellac_tpu.ops.quant import (
    QTensor,
    dequantize,
    materialize,
    quantize,
    quantize_logical_axes,
    quantize_params,
)
from shellac_tpu.ops.rope import apply_rope, rope_angles

__all__ = [
    "QTensor",
    "dequantize",
    "materialize",
    "quantize",
    "quantize_logical_axes",
    "quantize_params",
    "attention",
    "attention_ref",
    "flash_attention",
    "rms_norm",
    "rms_norm_ref",
    "layer_norm_ref",
    "apply_rope",
    "rope_angles",
    "swiglu",
    "geglu",
    "softcap",
]
