"""Gated activations used by the MLP blocks."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def swiglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    """SwiGLU combine: silu(gate) * up. XLA fuses this into the matmul."""
    return jax.nn.silu(gate) * up


def geglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    return jax.nn.gelu(gate) * up


def softcap(x: jax.Array, cap: float) -> jax.Array:
    """Soft logit cap: cap * tanh(x / cap), computed in fp32."""
    x32 = x.astype(jnp.float32)
    return (cap * jnp.tanh(x32 / cap)).astype(x.dtype)
