"""Attention ops.

`attention(...)` is the single entry point used by every model. It
dispatches between:
  - `attention_ref`: einsum + fp32 softmax. XLA already maps this onto the
    MXU and fuses the mask/softmax; it is the correctness reference and
    the CPU path.
  - `flash_attention` (ops/flash_attention.py): blocked Pallas TPU kernel
    with online softmax, used on TPU for long sequences.

Layout convention everywhere: q (B, Sq, H, D); k, v (B, Sk, Hkv, D) with
grouped-query attention when Hkv < H. Softmax/logits are always fp32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -2.0e38


def _build_mask(
    q_positions: jax.Array,  # (B, Sq) int32
    kv_positions: jax.Array,  # (B, Sk) int32
    causal: bool,
    window: Optional[int],
    kv_mask: Optional[jax.Array],  # (B, Sk) bool — valid kv slots
    q_segments: Optional[jax.Array] = None,  # (B, Sq) int32
    kv_segments: Optional[jax.Array] = None,  # (B, Sk) int32
) -> Optional[jax.Array]:
    """Boolean (B, 1, Sq, Sk) mask; True = attend."""
    parts = []
    qp = q_positions[:, :, None]  # (B, Sq, 1)
    kp = kv_positions[:, None, :]  # (B, 1, Sk)
    if causal:
        parts.append(kp <= qp)
    if window is not None:
        parts.append(qp - kp < window)
    if kv_mask is not None:
        parts.append(kv_mask[:, None, :])
    if q_segments is not None:
        # Packed sequences: attend only within the same document.
        parts.append(q_segments[:, :, None] == kv_segments[:, None, :])
    if not parts:
        return None
    mask = parts[0]
    for p in parts[1:]:
        mask = jnp.logical_and(mask, p)
    return mask[:, None, :, :]  # add heads axis


def attention_ref(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
    sinks: Optional[jax.Array] = None,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    kv_mask: Optional[jax.Array] = None,
    q_segments: Optional[jax.Array] = None,
    kv_segments: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference scaled-dot-product attention with GQA.

    softcap: Gemma-2-style logit soft-capping — scaled scores pass
    through cap*tanh(s/cap) BEFORE masking (masked slots stay NEG_INF,
    matching the HF eager path which caps, then adds the mask).

    sinks: (H,) per-head learned sink logits (GPT-OSS): each row's
    softmax denominator gains exp(sink_h) — a virtual column attending
    a zero value — so real attention mass can drain somewhere. Exactly
    HF's concat-softmax-drop formulation.
    """
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    if h % hkv != 0:
        raise ValueError(f"n_heads={h} not divisible by n_kv_heads={hkv}")
    g = h // hkv
    if scale is None:
        scale = d ** -0.5
    if q_positions is None:
        # Assume q is the tail of the kv sequence (prefill: sq == sk).
        q_positions = jnp.broadcast_to(jnp.arange(sk - sq, sk, dtype=jnp.int32), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32), (b, sk))

    qg = q.reshape(b, sq, hkv, g, d)
    # (B, Hkv, G, Sq, Sk) logits in fp32.
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale
    if softcap is not None:
        logits = softcap * jnp.tanh(logits / softcap)
    mask = _build_mask(
        q_positions, kv_positions, causal, window, kv_mask,
        q_segments, kv_segments,
    )
    if mask is not None:
        logits = jnp.where(mask[:, :, None, :, :], logits, NEG_INF)
    if sinks is not None:
        sink_col = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(1, hkv, g, 1, 1),
            (b, hkv, g, sq, 1),
        )
        probs = jax.nn.softmax(
            jnp.concatenate([logits, sink_col], axis=-1), axis=-1
        )[..., :-1]
    else:
        probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, sq, h, d).astype(q.dtype)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
    sinks: Optional[jax.Array] = None,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    kv_mask: Optional[jax.Array] = None,
    q_segments: Optional[jax.Array] = None,
    kv_segments: Optional[jax.Array] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention. impl: "auto" | "flash" | "ref"."""
    if impl not in ("auto", "flash", "ref"):
        raise ValueError(f"unknown attention impl {impl!r}")
    if impl == "ref":
        return attention_ref(
            q, k, v, causal=causal, window=window, scale=scale,
            softcap=softcap, sinks=sinks,
            q_positions=q_positions, kv_positions=kv_positions, kv_mask=kv_mask,
            q_segments=q_segments, kv_segments=kv_segments,
        )
    from shellac_tpu.ops.flash_attention import flash_attention, flash_supported

    if impl == "flash":
        if q_positions is not None or kv_positions is not None \
                or kv_mask is not None:
            raise ValueError(
                "impl='flash' does not support q_positions/kv_positions/"
                "kv_mask; use impl='auto' or 'ref'"
            )
        if (q_segments is None) != (kv_segments is None) or (
            q_segments is not None and q_segments is not kv_segments
        ):
            raise ValueError(
                "impl='flash' needs q_segments and kv_segments to be the "
                "same packed-segment array"
            )
        return flash_attention(
            q, k, v, causal=causal, scale=scale, window=window,
            softcap=softcap, sinks=sinks, segments=q_segments,
        )
    if impl == "auto" and flash_supported(
        q, k, v, window=window, q_positions=q_positions,
        kv_positions=kv_positions, kv_mask=kv_mask, causal=causal,
        q_segments=q_segments, kv_segments=kv_segments,
    ):
        return flash_attention(
            q, k, v, causal=causal, scale=scale, window=window,
            softcap=softcap, sinks=sinks, segments=q_segments,
        )
    return attention_ref(
        q, k, v, causal=causal, window=window, scale=scale,
        softcap=softcap, sinks=sinks,
        q_positions=q_positions, kv_positions=kv_positions, kv_mask=kv_mask,
        q_segments=q_segments, kv_segments=kv_segments,
    )
