"""Quantized (int8) matmuls for the training step.

TPU MXUs run int8 x int8 -> int32 at twice the bf16 rate (v5e: ~394 vs
~197 TOPS), and the weight/activation reads halve. This module provides
`int8_dot`, a drop-in dot for the transformer's dense projections:

  forward:  dynamic symmetric quantization — activations per-row
            (scale over the contraction axis), weights per-output-
            channel — then an int8 dot with int32 accumulation,
            dequantized by the product of both scales.
  backward: straight-through in the compute dtype (bf16): dx = g @ W^T,
            dW = x^T @ g, both unquantized. Quantizing the backward
            doubles the risk (gradients have heavier tails than
            activations) for another ~2x only on the two grad matmuls;
            forward-only is the standard first rung (the public AQT
            recipe) and keeps the loss-parity budget tight.

`int8_dot_full` is the second rung ("int8_bwd"): both backward matmuls
also run on the int8 MXU path. dx = g @ W^T contracts over the feature
axis, so both operands keep per-row/per-channel scales along the
contraction — the benign case. dW = x^T @ g contracts over the *batch*
axis: both operands get one scale per output channel computed over the
whole batch, so a single outlier token saturates its channel's scale
and the per-element rounding errors sum over the N contraction terms.
That is where gradient-quantization error concentrates, and this
deterministic scheme does NOT mitigate it (the standard mitigation,
stochastic rounding, needs an RNG threaded into the backward pass —
deliberately not done here). The int32 accumulation itself is exact;
all error comes from the two quantization roundings.
The tiny-model parity test bounds the end-to-end effect; real runs
should treat "int8_bwd" the way the AQT recipe does: fine for
pretraining throughput experiments, validate loss before committing.

Master parameters stay fp32 (the optimizer never sees int8); this is a
*compute* quantization, re-derived from the live weights every step, so
it composes with FSDP sharding, remat, and LoRA without checkpoint
format changes.

Opt-in via TrainConfig(quant="int8") -> ModelConfig.quant_training.
Embeddings, the LM head, routers, and MoE expert einsums stay in bf16:
their error sensitivity (softmax logits, top-k routing) is high and
their share of step time is low.

The reference repo is empty (SURVEY.md §0); no upstream scheme exists
to cite.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_QMAX = 127.0


def _quantize_rows(x: jax.Array, axis: int):
    """Symmetric int8 quantization with a scale per slice along `axis`."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


@jax.custom_vjp
def int8_dot(x: jax.Array, w: jax.Array) -> jax.Array:
    """x (..., D) @ w (D, F) with an int8 forward, bf16 backward."""
    return _int8_dot_fwd_impl(x, w)


def _int8_dot_fwd_impl(x, w):
    *lead, d = x.shape
    xf = x.reshape(-1, d)
    qx, sx = _quantize_rows(xf, axis=1)  # (N, 1)
    qw, sw = _quantize_rows(w, axis=0)  # (1, F)
    acc = jax.lax.dot_general(
        qx, qw, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    out = acc.astype(jnp.float32) * sx * sw
    return out.reshape(*lead, w.shape[1]).astype(x.dtype)


def _int8_dot_fwd(x, w):
    return _int8_dot_fwd_impl(x, w), (x, w)


def _int8_dot_bwd(res, g):
    x, w = res
    *lead, d = x.shape
    f = w.shape[1]
    gf = g.reshape(-1, f)
    xf = x.reshape(-1, d)
    dx = (gf @ w.astype(g.dtype).T).reshape(x.shape).astype(x.dtype)
    dw = jax.lax.dot_general(
        xf, gf, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(w.dtype)
    return dx, dw


int8_dot.defvjp(_int8_dot_fwd, _int8_dot_bwd)


def _int8_contract(a, b, a_axis, b_axis, out_shape):
    """int8 a x b contracting (a_axis, b_axis), per-slice dequant scales.

    Each operand is quantized with one scale per slice along its
    contraction axis, so the int32 accumulator is exact and the scale
    product factors out of the sum.
    """
    qa, sa = _quantize_rows(a, axis=a_axis)
    qb, sb = _quantize_rows(b, axis=b_axis)
    acc = jax.lax.dot_general(
        qa, qb, (((a_axis,), (b_axis,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    # dot_general output is (a's free axes, b's free axes); both scales
    # are keepdims over the contraction so squeeze to the free axis.
    sa_free = jnp.squeeze(sa, axis=a_axis).reshape(-1, 1)
    sb_free = jnp.squeeze(sb, axis=b_axis).reshape(1, -1)
    return (acc.astype(jnp.float32) * sa_free * sb_free).reshape(out_shape)


@jax.custom_vjp
def int8_dot_full(x: jax.Array, w: jax.Array) -> jax.Array:
    """x (..., D) @ w (D, F): int8 forward AND int8 backward matmuls."""
    return _int8_dot_fwd_impl(x, w)


def _int8_full_fwd(x, w):
    return _int8_dot_fwd_impl(x, w), (x, w)


def _int8_full_bwd(res, g):
    x, w = res
    *lead, d = x.shape
    f = w.shape[1]
    gf = g.reshape(-1, f).astype(jnp.float32)
    xf = x.reshape(-1, d).astype(jnp.float32)
    wf = w.astype(jnp.float32)
    # dx[n, d] = sum_f g[n, f] w[d, f]: g per-row, w per-d-row (one
    # scale per input channel, amax over the F contraction axis).
    dx = _int8_contract(gf, wf, 1, 1, (len(gf), d))
    # dW[d, f] = sum_n x[n, d] g[n, f]: both per-channel over the batch.
    dw = _int8_contract(xf, gf, 0, 0, (d, f))
    return dx.reshape(x.shape).astype(x.dtype), dw.astype(w.dtype)


int8_dot_full.defvjp(_int8_full_fwd, _int8_full_bwd)


def quant_dot(x: jax.Array, w: jax.Array, quant_training) -> jax.Array:
    """The transformer's dense-projection dot: quantized when asked."""
    if quant_training == "int8":
        return int8_dot(x, w)
    if quant_training == "int8_bwd":
        return int8_dot_full(x, w)
    if quant_training is not None:
        raise ValueError(
            f"unknown quant_training {quant_training!r}; "
            "have 'int8', 'int8_bwd'"
        )
    return x @ w
