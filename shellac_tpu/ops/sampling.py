"""Token sampling: temperature, top-k, nucleus (top-p), greedy.

All filtering happens in fp32 logit space with jnp.where masks — no
data-dependent shapes, so the whole sampler jits into the decode loop.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def top_k_mask(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k largest logits per row.

    k >= vocab degrades to a no-op rather than indexing out of bounds;
    k < 1 is rejected (k is user-supplied via the CLI/engine).
    """
    if k < 1:
        raise ValueError(f"top_k must be >= 1, got {k}")
    k = min(k, logits.shape[-1])
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, NEG_INF, logits)


def top_p_mask(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filtering: keep the smallest set with cumulative prob >= p."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep entries whose *previous* cumulative mass is < p (always keeps top-1).
    keep_sorted = (cum - probs) < p
    # Threshold logit = smallest kept logit.
    kth = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < kth, NEG_INF, logits)


def min_p_mask(logits: jax.Array, p: float) -> jax.Array:
    """Keep tokens whose prob >= p * max prob (scale-adaptive cutoff)."""
    probs = jax.nn.softmax(logits, axis=-1)
    cutoff = p * jnp.max(probs, axis=-1, keepdims=True)
    return jnp.where(probs < cutoff, NEG_INF, logits)


def repetition_penalty(
    logits: jax.Array,  # (..., V)
    seen: jax.Array,  # (..., V) bool — tokens already in the context
    penalty: float,
) -> jax.Array:
    """HF-convention penalty: seen tokens' logits /p if >0 else *p."""
    if penalty == 1.0:
        return logits
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def sample(
    key: jax.Array,
    logits: jax.Array,  # (..., V)
    *,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
) -> jax.Array:
    """Sample token ids. temperature == 0 means greedy."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        logits = top_k_mask(logits, top_k)
    if top_p is not None:
        logits = top_p_mask(logits, top_p)
    if min_p is not None:
        logits = min_p_mask(logits, min_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def filter_logits_batched(
    logits: jax.Array,  # (B, V)
    temperature: jax.Array,  # (B,) fp32; <= 0 rows temper at 1.0
    top_k: jax.Array,  # (B,) int32; >= V disables
    top_p: jax.Array,  # (B,) fp32; 1.0 disables
    min_p: jax.Array,  # (B,) fp32; 0.0 disables
) -> jax.Array:
    """The tempered, top-k/top-p/min-p-masked fp32 logits the batched
    sampler draws from — THE truncation definition, factored out so
    speculative decoding can apply the IDENTICAL filter to both the
    draft and target distributions (rejection sampling then provably
    reproduces the FILTERED target distribution, which is exactly what
    sequential sampling draws from — the spec x top-k/top-p identity).

    Greedy rows (temperature <= 0) are tempered at 1.0 and otherwise
    filtered like any row; callers argmax those rows on their own
    unfiltered logits, matching `sample_batched`.
    """
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    t = jnp.where(temperature <= 0.0, 1.0, temperature)[:, None]
    x = logits / t
    # top-k: per-row kth-largest threshold (ties at the boundary are
    # kept, matching top_k_mask).
    k = jnp.clip(top_k, 1, v)
    asc = jnp.sort(x, axis=-1)
    kth = jnp.take_along_axis(asc, (v - k)[:, None], axis=-1)
    x = jnp.where(x < kth, NEG_INF, x)
    # top-p on the top-k-filtered rows (same order as the scalar path);
    # re-sort so boundary ties behave exactly like top_p_mask.
    desc = jnp.sort(x, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]
    kth_p = jnp.min(
        jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True
    )
    x = jnp.where(x < kth_p, NEG_INF, x)
    # min-p relative to each row's current max.
    probs_x = jax.nn.softmax(x, axis=-1)
    cutoff = min_p[:, None] * jnp.max(probs_x, axis=-1, keepdims=True)
    x = jnp.where(probs_x < cutoff, NEG_INF, x)
    return x


def sample_batched(
    key: jax.Array,
    logits: jax.Array,  # (B, V)
    temperature: jax.Array,  # (B,) fp32; 0 = greedy
    top_k: jax.Array,  # (B,) int32; >= V disables
    top_p: jax.Array,  # (B,) fp32; 1.0 disables
    min_p: jax.Array,  # (B,) fp32; 0.0 disables
    seed: Optional[jax.Array] = None,  # (B,) int32; -1 = unseeded
    gen_idx: Optional[jax.Array] = None,  # (B,) int32 — tokens generated
) -> jax.Array:
    """`sample` with PER-ROW parameters, for serving engines that mix
    requests with different sampling settings in one device batch.

    Same filter semantics as the scalar path (verified token-exact in
    tests when all rows share one setting): disabled values are the
    no-op sentinels above rather than None, so the whole thing stays
    one jittable program with fixed shapes.

    seed/gen_idx: per-request DETERMINISTIC sampling — a seeded row
    draws from fold_in(PRNGKey(seed), gen_idx), so its tokens depend
    only on (seed, logits, position in its own generation), never on
    slot placement, co-tenant requests, or the engine's key state.
    Rows with seed < 0 keep the shared stream.
    """
    logits = logits.astype(jnp.float32)
    greedy = temperature <= 0.0
    x = filter_logits_batched(logits, temperature, top_k, top_p, min_p)
    sampled = jax.random.categorical(key, x, axis=-1)
    if seed is not None:
        def row_draw(s, g, row):
            k = jax.random.fold_in(
                jax.random.PRNGKey(jnp.maximum(s, 0)), g
            )
            return jax.random.categorical(k, row)

        per_row = jax.vmap(row_draw)(seed, gen_idx, x)
        sampled = jnp.where(seed >= 0, per_row, sampled)
    return jnp.where(
        greedy, jnp.argmax(logits, axis=-1), sampled
    ).astype(jnp.int32)
