"""Blocked (flash) attention as a Pallas TPU kernel.

Forward: classic online-softmax tiling. Grid is (batch*heads, q_blocks,
kv_blocks); the kv axis is innermost, so fp32 accumulators live in VMEM
scratch across kv steps. Causal upper-triangle blocks are skipped
entirely (no compute), which halves the work for causal prefill. GQA is
handled in the index map: the kv block for q-head h is head h // group,
so kv tiles are never replicated in HBM.

Sliding windows and packed segments are first-class (they are the
pretraining default, not an exotic): a window additionally skips kv
blocks entirely below the window's reach — compute AND the DMA, via the
same index-map clamping trick as the causal skip — so windowed training
cost scales with O(S*W) not O(S^2). Packed segment ids ride along as
(1, 1, block) int32 tiles and contribute a block-diagonal mask; a tile
whose every entry is masked is handled exactly (the online softmax
update is gated so the accumulator passes through unchanged).

Backward: blocked Pallas kernels as well. The forward additionally
writes the logsumexp rows; backward recomputes tile probabilities from
(q, k, lse) — never materializing the S×S matrix — in two passes:
one over kv blocks producing dk/dv (GQA group summed in-kernel), one
over q blocks producing dq. Causal/window dead blocks are skipped in
both. Segment ids need no gradient (they are an integer mask).

The compiled kernel wants head_dim a multiple of 64 (blocks span the
full head_dim, which Mosaic accepts; dh=64 pays ~2x lane padding but
still beats the O(S^2) reference) and block-divisible sequence lengths;
`flash_supported` gates dispatch and everything else falls back to the
reference implementation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from shellac_tpu.ops.dispatch import pallas_supported

# Tuned on v5e at (B=4, S=2048, H=16, Hkv=8, D=128): 512/1024 beats
# 256/256 by ~30% forward and ~2x on the backward pass.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
NEG_INF = -2.0e38


def sink_rebase(m, l, sink):
    """Fold a sink logit into an online-softmax (m, l) pair.

    Returns (r, l2, m2): rescale the accumulator by r, divide by l2,
    and m2 + log(l2) is the sink-inclusive logsumexp. Shared by the
    flash/decode/ring finalizers so the rebase math cannot drift.
    l2 >= exp(sink - m2) > 0, so fully-masked rows need no zero guard.
    """
    m2 = jnp.maximum(m, sink)
    r = jnp.exp(m - m2)
    return r, l * r + jnp.exp(sink - m2), m2


def _fit_block(seq: int, block: int) -> int:
    """Largest divisor of `seq` that is <= `block` and a multiple of 8
    (TPU sublane tiling); 0 if none exists."""
    b = min(block, seq)
    while b >= 8:
        if seq % b == 0 and b % 8 == 0:
            return b
        b -= 8
    return 0


def flash_supported(
    q, k, v, *, causal, window=None, q_positions=None, kv_positions=None,
    kv_mask=None, q_segments=None, kv_segments=None,
    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
) -> bool:
    """Can the compiled Pallas kernel handle this call?"""
    if not pallas_supported():
        return False
    if q_positions is not None or kv_positions is not None:
        return False
    if kv_mask is not None:
        return False
    if (q_segments is None) != (kv_segments is None):
        return False
    if q_segments is not None and q_segments is not kv_segments:
        # The kernel masks with ONE packed-segment row per batch entry
        # (training packing always has q and kv sharing it); distinct
        # q/kv segment arrays fall back to the reference path.
        return False
    if window is not None and window < 1:
        return False
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    if sq != sk:
        return False
    if not causal and window is not None:
        # One-sided windows without causality are ambiguous; only the
        # reference path defines them.
        return False
    if d % 64 != 0:
        # Head dims below a 128-lane tile are zero-padded up to one at
        # the flash_attention entry (Mosaic rejects block selects on
        # unaligned lane dims); d % 64 bounds that lane waste at ~2x.
        return False
    if _fit_block(sq, block_q) == 0 or _fit_block(sk, block_k) == 0:
        return False
    if h % hkv != 0:
        return False
    return True


def _scores(
    q_blk, k_blk, q_start, k_start, scale, causal, window=None,
    q_seg=None, k_seg=None, softcap=None,
):
    """Scaled (block_q, block_k) fp32 logits with all masks applied.

    q_seg/k_seg: (block_q,), (block_k,) int32 packed document ids, or
    None for unpacked. softcap: Gemma-2 logit capping — the scaled
    scores pass through cap*tanh(s/cap) BEFORE the masks, so masked
    slots keep the NEG_INF sentinel the online softmax gates on.
    """
    q = q_blk.astype(jnp.float32) * scale
    k = k_blk.astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if softcap is not None:
        s = softcap * jnp.tanh(s / softcap)
    shape = s.shape
    if causal or window is not None:
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        if causal:
            s = jnp.where(cols <= rows, s, NEG_INF)
        if window is not None:
            # Valid iff qpos - kpos < window.
            s = jnp.where(rows - cols < window, s, NEG_INF)
    if q_seg is not None:
        s = jnp.where(q_seg[:, None] == k_seg[None, :], s, NEG_INF)
    return s


def _tile_p_ds(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    q_start, k_start, scale, causal, window, q_seg, k_seg, softcap=None,
):
    """Recompute a probability tile and its score gradient from saved lse.

    Shared by both backward kernels so the masking/lse handling cannot
    drift between dq and dk/dv. Returns (p, ds), both (block_q, block_k)
    fp32; ds carries the softmax scale factor (and, with softcap, the
    tanh derivative 1 - (s_cap/cap)^2 of the capping).
    """
    s = _scores(
        q_ref[0], k_ref[0], q_start, k_start, scale, causal, window,
        q_seg, k_seg, softcap,
    )
    # Masked entries carry s = NEG_INF (finite): exp(s - lse) underflows
    # to 0 for any real lse, but a fully-masked row would hit
    # exp(NEG_INF - NEG_INF) = 1, so gate on s itself.
    p = jnp.where(
        s > 0.5 * NEG_INF, jnp.exp(s - lse_ref[0, 0, :][:, None]), 0.0
    )
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0, 0, :][:, None]) * scale
    if softcap is not None:
        # s holds the CAPPED score where live, so tanh(raw/cap) = s/cap
        # and d(cap)/d(raw) = 1 - (s/cap)^2. Masked slots hold NEG_INF;
        # (NEG_INF/cap)^2 overflows fp32 to inf and 0*inf = NaN, so gate
        # the factor on the same sentinel as p (ds is 0 there anyway).
        ds = ds * jnp.where(
            s > 0.5 * NEG_INF, 1.0 - jnp.square(s / softcap), 0.0
        )
    return p, ds


def _first_live_ki(q_start, window, block_k):
    """First kv block any row of this q block can attend (window only)."""
    return jnp.maximum(q_start - window + 1, 0) // block_k


def _make_clamp_ki(causal, window, block_q, block_k):
    """kv-block DMA clamp shared by the forward and dq index maps.

    Clamps dead kv blocks (above the causal diagonal, or wholly below
    the window's reach) onto the live range: the Mosaic pipeline only
    issues a DMA when the block index changes, so skipped blocks cost
    no HBM bandwidth.
    """

    def clamp_ki(qi, ki):
        if causal:
            last = (qi * block_q + block_q - 1) // block_k
            if window is not None:
                ki = jnp.clip(
                    ki, _first_live_ki(qi * block_q, window, block_k), last
                )
            else:
                ki = jnp.minimum(ki, last)
        return ki

    return clamp_ki


def _unpack_refs(refs, has_segments, n_out_scratch, has_sinks=False):
    """Split a kernel's positional refs into
    (main_inputs, segs, sinks, rest)."""
    n_extra = (2 if has_segments else 0) + (1 if has_sinks else 0)
    ins = refs[: len(refs) - n_out_scratch - n_extra]
    extra = refs[len(refs) - n_out_scratch - n_extra:
                 len(refs) - n_out_scratch]
    rest = refs[len(refs) - n_out_scratch:]
    segs = (extra[0], extra[1]) if has_segments else (None, None)
    sinks = extra[-1] if has_sinks else None
    return ins, segs, sinks, rest


def _flash_kernel(
    *refs, scale: float, causal: bool, window: Optional[int],
    block_q: int, block_k: int, num_kv: int, has_segments: bool,
    softcap: Optional[float], has_sinks: bool,
):
    (q_ref, k_ref, v_ref), (qs_ref, ks_ref), sinks_ref, (
        o_ref, lse_ref, acc_ref, m_ref, l_ref,
    ) = _unpack_refs(refs, has_segments, 5, has_sinks)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    q_start = qi * block_q
    k_start = ki * block_k

    if causal:
        # Last kv block this q block attends to (where the output write
        # happens); later blocks are skipped entirely.
        last_ki = jnp.minimum(num_kv - 1, (q_start + block_q - 1) // block_k)
        live = k_start <= q_start + block_q - 1
    else:
        last_ki = num_kv - 1
        live = True
    if window is not None:
        # Blocks wholly below the window's reach are skipped too.
        live &= k_start + block_k - 1 >= q_start - window + 1

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(live)
    def _compute():
        v = v_ref[0]
        q_seg = qs_ref[0, 0, :] if has_segments else None
        k_seg = ks_ref[0, 0, :] if has_segments else None
        s = _scores(
            q_ref[0], k_ref[0], q_start, k_start, scale, causal, window,
            q_seg, k_seg, softcap,
        )
        m_prev = m_ref[:, :1]  # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # A fully-masked tile leaves m_new at NEG_INF; exp(s - m_new)
        # would then be exp(0) = 1 for every masked entry. Gate on s so
        # the tile contributes nothing (alpha = exp(m_prev - m_new) = 1
        # keeps the accumulator intact).
        p = jnp.where(s > 0.5 * NEG_INF, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        if has_sinks:
            # GPT-OSS attention sink: the softmax denominator gains
            # exp(sink_h) — a virtual column over a zero value. The
            # saved lse then INCLUDES the sink, which is exactly what
            # makes the backward kernels correct unchanged (p =
            # exp(s - lse) are the true probabilities, delta =
            # sum(dO*O) still sums only real columns because the
            # sink's value is 0).
            r, l2, m2 = sink_rebase(m, l, sinks_ref[0, 0])
            o_ref[0] = (acc_ref[...] * r / l2).astype(o_ref.dtype)
            lse_ref[0, 0, :] = (m2 + jnp.log(l2))[:, 0]
        else:
            # Guard fully-masked rows (can't happen for causal, cheap
            # anyway).
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
            lse_ref[0, 0, :] = (m + jnp.log(l))[:, 0]


def _flash_forward(
    q, k, v, seg, causal, scale, window, block_q, block_k, interpret,
    softcap=None, sinks=None,
):
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    g = h // hkv
    block_q = _fit_block(sq, block_q) or min(block_q, sq)
    block_k = _fit_block(sk, block_k) or min(block_k, sk)
    num_q = sq // block_q
    num_kv = sk // block_k
    has_segments = seg is not None

    # (B, S, H, D) -> (B*H, S, D)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)

    clamp_ki = _make_clamp_ki(causal, window, block_q, block_k)

    def kv_index(bh, qi, ki):
        kv_bh = (bh // h) * hkv + (bh % h) // g
        return kv_bh, clamp_ki(qi, ki), 0

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    inputs = [qf, kf, vf]
    if has_segments:
        segr = seg.astype(jnp.int32).reshape(b, 1, sq)
        in_specs += [
            pl.BlockSpec(
                (1, 1, block_q), lambda bh, qi, ki: (bh // h, 0, qi)
            ),
            pl.BlockSpec(
                (1, 1, block_k),
                lambda bh, qi, ki: (bh // h, 0, clamp_ki(qi, ki)),
            ),
        ]
        inputs += [segr, segr]
    has_sinks = sinks is not None
    if has_sinks:
        # One scalar per q-head, tiled across a lane row (Mosaic wants
        # a 128-lane trailing dim).
        sinks_arr = jnp.tile(
            sinks.astype(jnp.float32)[:, None], (1, 128)
        )
        in_specs += [
            pl.BlockSpec((1, 128), lambda bh, qi, ki: (bh % h, 0)),
        ]
        inputs += [sinks_arr]

    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            scale=scale,
            causal=causal,
            window=window,
            block_q=block_q,
            block_k=block_k,
            num_kv=num_kv,
            has_segments=has_segments,
            softcap=softcap,
            has_sinks=has_sinks,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            # (B*H, 1, S): the unit middle dim keeps the block's trailing
            # two dims TPU-tileable ((1, block_q) alone is not).
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        grid=(b * h, num_q, num_kv),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3), lse[:, 0, :]



def _flash_bwd_dkdv_kernel(
    *refs, scale: float, causal: bool, window: Optional[int],
    block_q: int, block_k: int, num_q: int, inner: int, has_segments: bool,
    softcap: Optional[float],
):
    """Grid (B*Hkv, kv_blocks, G*q_blocks): one (dk, dv) tile per kv block,
    accumulated over every q block of every q-head in the GQA group."""
    (q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref), (qs_ref, ks_ref), _, (
        dk_ref, dv_ref, dk_acc, dv_acc,
    ) = _unpack_refs(refs, has_segments, 4)
    ki = pl.program_id(1)
    j = pl.program_id(2)
    qi = j % num_q

    k_start = ki * block_k
    q_start = qi * block_q
    live = (not causal) or (q_start + block_q - 1 >= k_start)
    if window is not None:
        # q rows beyond k_start + block_k - 1 + window - 1 can't reach
        # this kv block.
        live &= q_start <= k_start + block_k + window - 2

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(live)
    def _compute():
        q_seg = qs_ref[0, 0, :] if has_segments else None
        k_seg = ks_ref[0, 0, :] if has_segments else None
        p, ds = _tile_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, scale, causal, window, q_seg, k_seg, softcap,
        )
        do = do_ref[0]
        # dv += p^T do
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dk += ds^T q_raw  (ds carries the softmax scale)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0],
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when(j == inner - 1)
    def _write():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    *refs, scale: float, causal: bool, window: Optional[int],
    block_q: int, block_k: int, num_kv: int, has_segments: bool,
    softcap: Optional[float],
):
    """Grid (B*H, q_blocks, kv_blocks): one dq tile per q block."""
    (q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref), (qs_ref, ks_ref), _, (
        dq_ref, dq_acc,
    ) = _unpack_refs(refs, has_segments, 2)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    if causal:
        last_ki = jnp.minimum(num_kv - 1, (q_start + block_q - 1) // block_k)
        live = k_start <= q_start + block_q - 1
    else:
        last_ki = num_kv - 1
        live = True
    if window is not None:
        live &= k_start + block_k - 1 >= q_start - window + 1

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(live)
    def _compute():
        q_seg = qs_ref[0, 0, :] if has_segments else None
        k_seg = ks_ref[0, 0, :] if has_segments else None
        _, ds = _tile_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, scale, causal, window, q_seg, k_seg, softcap,
        )
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when(ki == last_ki)
    def _write():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_backward(
    q, k, v, seg, o, lse, g_out, causal, scale, window, block_q, block_k,
    interpret, softcap=None, sinks=None,
):
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    g = h // hkv
    block_q = _fit_block(sq, block_q) or min(block_q, sq)
    block_k = _fit_block(sk, block_k) or min(block_k, sk)
    num_q = sq // block_q
    num_kv = sk // block_k
    has_segments = seg is not None

    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    dof = g_out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta_i = sum_d dO_id * O_id, per (head, row) — fp32. Shaped with a
    # unit middle dim (like lse) so blocks stay TPU-tileable.
    delta = jnp.einsum(
        "bshd,bshd->bhs", g_out.astype(jnp.float32), o.astype(jnp.float32)
    ).reshape(b * h, 1, sq)
    lse = lse.reshape(b * h, 1, sq)
    segr = (
        seg.astype(jnp.int32).reshape(b, 1, sq) if has_segments else None
    )

    # --- pass 1: dk, dv (GQA group summed in-kernel) ---
    inner = g * num_q

    def clamp_qi(ki, qi):
        if causal:
            # Clamp dead pre-diagonal q blocks to the first live one so
            # the pipeline issues no DMA for skipped blocks.
            qi = jnp.maximum(qi, (ki * block_k) // block_q)
        if window is not None:
            last_qi = jnp.minimum(
                (ki * block_k + block_k + window - 2) // block_q, num_q - 1
            )
            qi = jnp.minimum(qi, last_qi)
        return qi

    def q_row(bkv, ki, j):
        # q-head row for this (kv head, group member) pair.
        return (bkv // hkv) * h + (bkv % hkv) * g + j // num_q

    def q_index(bkv, ki, j):
        return q_row(bkv, ki, j), clamp_qi(ki, j % num_q), 0

    def row_index(bkv, ki, j):
        return q_row(bkv, ki, j), 0, clamp_qi(ki, j % num_q)

    in_specs = [
        pl.BlockSpec((1, block_q, d), q_index),
        pl.BlockSpec((1, block_q, d), q_index),
        pl.BlockSpec((1, 1, block_q), row_index),
        pl.BlockSpec((1, 1, block_q), row_index),
        pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
    ]
    inputs = [qf, dof, lse, delta, kf, vf]
    if has_segments:
        in_specs += [
            pl.BlockSpec(
                (1, 1, block_q),
                lambda bkv, ki, j: (bkv // hkv, 0, clamp_qi(ki, j % num_q)),
            ),
            pl.BlockSpec(
                (1, 1, block_k), lambda bkv, ki, j: (bkv // hkv, 0, ki)
            ),
        ]
        inputs += [segr, segr]

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel, scale=scale, causal=causal,
            window=window, block_q=block_q, block_k=block_k, num_q=num_q,
            inner=inner, has_segments=has_segments, softcap=softcap,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ],
        grid=(b * hkv, num_kv, inner),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)

    # --- pass 2: dq ---
    clamp_ki = _make_clamp_ki(causal, window, block_q, block_k)

    def kv_index(bh, qi, ki):
        kv_bh = (bh // h) * hkv + (bh % h) // g
        return kv_bh, clamp_ki(qi, ki), 0

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    inputs = [qf, dof, lse, delta, kf, vf]
    if has_segments:
        in_specs += [
            pl.BlockSpec(
                (1, 1, block_q), lambda bh, qi, ki: (bh // h, 0, qi)
            ),
            pl.BlockSpec(
                (1, 1, block_k),
                lambda bh, qi, ki: (bh // h, 0, clamp_ki(qi, ki)),
            ),
        ]
        inputs += [segr, segr]

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal, window=window,
            block_q=block_q, block_k=block_k, num_kv=num_kv,
            has_segments=has_segments, softcap=softcap,
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * h, num_q, num_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    unflat = lambda x, hh: x.reshape(b, hh, -1, d).transpose(0, 2, 1, 3)
    d_sinks = None
    if sinks is not None:
        # The sink column's value is zero, so its only gradient path is
        # the softmax denominator: dL/dsink_h = -sum_{b,rows}
        # p_sink * delta_row, with p_sink = exp(sink - lse) (lse already
        # includes the sink) and delta = sum(dO * O).
        lse_r = lse.reshape(b, h, sq)
        delta_r = delta.reshape(b, h, sq)
        d_sinks = -jnp.sum(
            jnp.exp(sinks.astype(jnp.float32)[None, :, None] - lse_r)
            * delta_r,
            axis=(0, 2),
        ).astype(sinks.dtype)
    return unflat(dq, h), unflat(dk, hkv), unflat(dv, hkv), d_sinks


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, seg, sinks, causal, scale, window, block_q, block_k,
           interpret, softcap):
    out, _ = _flash_forward(
        q, k, v, seg, causal, scale, window, block_q, block_k, interpret,
        softcap, sinks,
    )
    return out


def _flash_fwd(q, k, v, seg, sinks, causal, scale, window, block_q, block_k,
               interpret, softcap):
    out, lse = _flash_forward(
        q, k, v, seg, causal, scale, window, block_q, block_k, interpret,
        softcap, sinks,
    )
    return out, (q, k, v, seg, sinks, out, lse)


def _flash_bwd(causal, scale, window, block_q, block_k, interpret, softcap,
               res, g_out):
    q, k, v, seg, sinks, o, lse = res
    dq, dk, dv, d_sinks = _flash_backward(
        q, k, v, seg, o, lse, g_out, causal, scale, window, block_q, block_k,
        interpret, softcap, sinks,
    )
    return dq, dk, dv, None, d_sinks


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, scale: Optional[float] = None,
    window: Optional[int] = None, segments: Optional[jax.Array] = None,
    softcap: Optional[float] = None, sinks: Optional[jax.Array] = None,
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
):
    """Flash attention. q (B,S,H,D); k,v (B,S,Hkv,D).

    `window`: sliding-window size (qpos - kpos < window). `segments`:
    (B, S) int32 packed document ids shared by q and kv; attention is
    block-diagonal over them. `softcap`: Gemma-2-style tanh capping of
    the scaled scores (fwd and both bwd passes chain the derivative).
    """
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = not pallas_supported()
    pad = (-d) % 128
    if pad:
        # Mosaic rejects memref slices (every `ref[0]` block select in
        # the kernels) on refs whose lane dim is not 128-aligned, so
        # dh=64-class models zero-pad the head dim up to a tile. Zero
        # k/v lanes leave the logits and the real output lanes exact;
        # the padded output lanes are sliced off (and autodiff of
        # pad/slice keeps the gradients exact too). ~2x lane waste,
        # still far ahead of the O(S^2) reference path.
        widths = [(0, 0)] * 3 + [(0, pad)]
        q, k, v = (jnp.pad(x, widths) for x in (q, k, v))
    out = _flash(
        q, k, v, segments, sinks, causal, float(scale), window, block_q,
        block_k, interpret, None if softcap is None else float(softcap),
    )
    return out[..., :d] if pad else out
