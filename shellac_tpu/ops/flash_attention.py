"""Blocked (flash) attention as a Pallas TPU kernel.

Forward: classic online-softmax tiling. Grid is (batch*heads, q_blocks,
kv_blocks); the kv axis is innermost, so fp32 accumulators live in VMEM
scratch across kv steps. Causal upper-triangle blocks are skipped
entirely (no compute), which halves the work for causal prefill. GQA is
handled in the index map: the kv block for q-head h is head h // group,
so kv tiles are never replicated in HBM.

Backward: custom VJP that recomputes through the einsum reference. This
is correct and rematerialization-friendly (the model already wraps blocks
in jax.checkpoint); a blocked Pallas backward is a planned optimization.

The compiled kernel wants lane-aligned head_dim (multiple of 128) and
block-divisible sequence lengths; `flash_supported` gates dispatch and
everything else falls back to the reference implementation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from shellac_tpu.ops.dispatch import pallas_supported

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -2.0e38


def flash_supported(
    q, k, v, *, causal, window=None, q_positions=None, kv_positions=None,
    kv_mask=None, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
) -> bool:
    """Can the compiled Pallas kernel handle this call?"""
    if not pallas_supported():
        return False
    if window is not None or q_positions is not None or kv_positions is not None:
        return False
    if kv_mask is not None:
        return False
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    if sq != sk or not causal:
        # The kernel itself supports non-causal; restrict dispatch to the
        # training prefill shape we have test coverage for.
        return False
    if d % 128 != 0:
        return False
    if sq % min(block_q, sq) != 0 or sk % min(block_k, sk) != 0:
        return False
    if h % hkv != 0:
        return False
    return True


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int, num_kv: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    q_start = qi * block_q
    k_start = ki * block_k

    if causal:
        # Last kv block this q block attends to (where the output write
        # happens); later blocks are skipped entirely.
        last_ki = jnp.minimum(num_kv - 1, (q_start + block_q - 1) // block_k)
        live = k_start <= q_start + block_q - 1
    else:
        last_ki = num_kv - 1
        live = True

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_ref[:, :1]
        # Guard fully-masked rows (can't happen for causal, cheap anyway).
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    g = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    num_q = sq // block_q
    num_kv = sk // block_k

    # (B, S, H, D) -> (B*H, S, D)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)

    def kv_index(bh, qi, ki):
        kv_bh = (bh // h) * hkv + (bh % h) // g
        if causal:
            # Clamp dead upper-triangle blocks to the diagonal block: the
            # Mosaic pipeline only issues a DMA when the block index
            # changes, so compute-skipped blocks cost no HBM bandwidth.
            ki = jnp.minimum(ki, (qi * block_q + block_q - 1) // block_k)
        return kv_bh, ki, 0

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            scale=scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            num_kv=num_kv,
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g_out):
    from shellac_tpu.ops.attention import attention_ref

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_ref(q_, k_, v_, causal=causal, scale=scale),
        q, k, v,
    )
    return vjp(g_out)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
):
    """Flash attention. q (B,S,H,D); k,v (B,S,Hkv,D)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not pallas_supported()
    return _flash(q, k, v, causal, float(scale), block_q, block_k, interpret)
