"""Blocked (flash) attention as a Pallas TPU kernel.

Forward: classic online-softmax tiling. Grid is (batch*heads, q_blocks,
kv_blocks); the kv axis is innermost, so fp32 accumulators live in VMEM
scratch across kv steps. Causal upper-triangle blocks are skipped
entirely (no compute), which halves the work for causal prefill. GQA is
handled in the index map: the kv block for q-head h is head h // group,
so kv tiles are never replicated in HBM.

Backward: blocked Pallas kernels as well. The forward additionally
writes the logsumexp rows; backward recomputes tile probabilities from
(q, k, lse) — never materializing the S×S matrix — in two passes:
one over kv blocks producing dk/dv (GQA group summed in-kernel), one
over q blocks producing dq. Causal dead blocks are skipped in both.

The compiled kernel wants lane-aligned head_dim (multiple of 128) and
block-divisible sequence lengths; `flash_supported` gates dispatch and
everything else falls back to the reference implementation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from shellac_tpu.ops.dispatch import pallas_supported

# Tuned on v5e at (B=4, S=2048, H=16, Hkv=8, D=128): 512/1024 beats
# 256/256 by ~30% forward and ~2x on the backward pass.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
NEG_INF = -2.0e38


def _fit_block(seq: int, block: int) -> int:
    """Largest divisor of `seq` that is <= `block` and a multiple of 8
    (TPU sublane tiling); 0 if none exists."""
    b = min(block, seq)
    while b >= 8:
        if seq % b == 0 and b % 8 == 0:
            return b
        b -= 8
    return 0


def flash_supported(
    q, k, v, *, causal, window=None, q_positions=None, kv_positions=None,
    kv_mask=None, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
) -> bool:
    """Can the compiled Pallas kernel handle this call?"""
    if not pallas_supported():
        return False
    if window is not None or q_positions is not None or kv_positions is not None:
        return False
    if kv_mask is not None:
        return False
    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    if sq != sk or not causal:
        # The kernel itself supports non-causal; restrict dispatch to the
        # training prefill shape we have test coverage for.
        return False
    if d % 128 != 0:
        return False
    if _fit_block(sq, block_q) == 0 or _fit_block(sk, block_k) == 0:
        return False
    if h % hkv != 0:
        return False
    return True


def _scores(q_blk, k_blk, q_start, k_start, scale, causal):
    """Scaled (block_q, block_k) fp32 logits with the causal mask applied."""
    q = q_blk.astype(jnp.float32) * scale
    k = k_blk.astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if causal:
        shape = s.shape
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        s = jnp.where(cols <= rows, s, NEG_INF)
    return s


def _tile_p_ds(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    q_start, k_start, scale, causal,
):
    """Recompute a probability tile and its score gradient from saved lse.

    Shared by both backward kernels so the masking/lse handling cannot
    drift between dq and dk/dv. Returns (p, ds), both (block_q, block_k)
    fp32; ds carries the softmax scale factor.
    """
    s = _scores(q_ref[0], k_ref[0], q_start, k_start, scale, causal)
    p = jnp.exp(s - lse_ref[0, 0, :][:, None])  # exact softmax rows
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0, 0, :][:, None]) * scale
    return p, ds


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int, num_kv: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    q_start = qi * block_q
    k_start = ki * block_k

    if causal:
        # Last kv block this q block attends to (where the output write
        # happens); later blocks are skipped entirely.
        last_ki = jnp.minimum(num_kv - 1, (q_start + block_q - 1) // block_k)
        live = k_start <= q_start + block_q - 1
    else:
        last_ki = num_kv - 1
        live = True

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(live)
    def _compute():
        v = v_ref[0]
        s = _scores(q_ref[0], k_ref[0], q_start, k_start, scale, causal)
        m_prev = m_ref[:, :1]  # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_ref[:, :1]
        # Guard fully-masked rows (can't happen for causal, cheap anyway).
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, :] = (m_ref[:, :1] + jnp.log(l))[:, 0]


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    g = h // hkv
    block_q = _fit_block(sq, block_q) or min(block_q, sq)
    block_k = _fit_block(sk, block_k) or min(block_k, sk)
    num_q = sq // block_q
    num_kv = sk // block_k

    # (B, S, H, D) -> (B*H, S, D)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)

    def kv_index(bh, qi, ki):
        kv_bh = (bh // h) * hkv + (bh % h) // g
        if causal:
            # Clamp dead upper-triangle blocks to the diagonal block: the
            # Mosaic pipeline only issues a DMA when the block index
            # changes, so compute-skipped blocks cost no HBM bandwidth.
            ki = jnp.minimum(ki, (qi * block_q + block_q - 1) // block_k)
        return kv_bh, ki, 0

    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            scale=scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            num_kv=num_kv,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            # (B*H, 1, S): the unit middle dim keeps the block's trailing
            # two dims TPU-tileable ((1, block_q) alone is not).
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        grid=(b * h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3), lse[:, 0, :]


def _flash_bwd_dkdv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    num_q: int, inner: int,
):
    """Grid (B*Hkv, kv_blocks, G*q_blocks): one (dk, dv) tile per kv block,
    accumulated over every q block of every q-head in the GQA group."""
    ki = pl.program_id(1)
    j = pl.program_id(2)
    qi = j % num_q

    k_start = ki * block_k
    q_start = qi * block_q
    live = (not causal) or (q_start + block_q - 1 >= k_start)

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(live)
    def _compute():
        p, ds = _tile_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, scale, causal,
        )
        do = do_ref[0]
        # dv += p^T do
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dk += ds^T q_raw  (ds carries the softmax scale)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0],
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when(j == inner - 1)
    def _write():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int, num_kv: int,
):
    """Grid (B*H, q_blocks, kv_blocks): one dq tile per q block."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    if causal:
        last_ki = jnp.minimum(num_kv - 1, (q_start + block_q - 1) // block_k)
        live = k_start <= q_start + block_q - 1
    else:
        last_ki = num_kv - 1
        live = True

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(live)
    def _compute():
        _, ds = _tile_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            q_start, k_start, scale, causal,
        )
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when(ki == last_ki)
    def _write():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_backward(
    q, k, v, o, lse, g_out, causal, scale, block_q, block_k, interpret
):
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    _, sk, hkv, _ = k.shape
    g = h // hkv
    block_q = _fit_block(sq, block_q) or min(block_q, sq)
    block_k = _fit_block(sk, block_k) or min(block_k, sk)
    num_q = sq // block_q
    num_kv = sk // block_k

    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    dof = g_out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta_i = sum_d dO_id * O_id, per (head, row) — fp32. Shaped with a
    # unit middle dim (like lse) so blocks stay TPU-tileable.
    delta = jnp.einsum(
        "bshd,bshd->bhs", g_out.astype(jnp.float32), o.astype(jnp.float32)
    ).reshape(b * h, 1, sq)
    lse = lse.reshape(b * h, 1, sq)

    # --- pass 1: dk, dv (GQA group summed in-kernel) ---
    inner = g * num_q

    def q_row(bkv, ki, j):
        # q-head row for this (kv head, group member) pair.
        return (bkv // hkv) * h + (bkv % hkv) * g + j // num_q

    def q_index(bkv, ki, j):
        qi = j % num_q
        if causal:
            # Clamp dead pre-diagonal q blocks to the first live one so
            # the pipeline issues no DMA for skipped blocks.
            qi = jnp.maximum(qi, (ki * block_k) // block_q)
        return q_row(bkv, ki, j), qi, 0

    def row_index(bkv, ki, j):
        qi = j % num_q
        if causal:
            qi = jnp.maximum(qi, (ki * block_k) // block_q)
        return q_row(bkv, ki, j), 0, qi

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q=num_q, inner=inner,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ],
        grid=(b * hkv, num_kv, inner),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_q), row_index),
            pl.BlockSpec((1, 1, block_q), row_index),
            pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, ki, j: (bkv, ki, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, dof, lse, delta, kf, vf)

    # --- pass 2: dq ---
    def kv_index(bh, qi, ki):
        kv_bh = (bh // h) * hkv + (bh % h) // g
        if causal:
            ki = jnp.minimum(ki, (qi * block_q + block_q - 1) // block_k)
        return kv_bh, ki, 0

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_kv=num_kv,
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, dof, lse, delta, kf, vf)

    unflat = lambda x, hh: x.reshape(b, hh, -1, d).transpose(0, 2, 1, 3)
    return unflat(dq, h), unflat(dk, hkv), unflat(dv, hkv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g_out):
    q, k, v, o, lse = res
    return _flash_backward(
        q, k, v, o, lse, g_out, causal, scale, block_q, block_k, interpret
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
):
    """Flash attention. q (B,S,H,D); k,v (B,S,Hkv,D)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not pallas_supported()
    return _flash(q, k, v, causal, float(scale), block_q, block_k, interpret)
