"""RMSNorm / LayerNorm.

Two implementations with one dispatch point:
  - `rms_norm_ref`: pure jnp, fp32 accumulation — XLA fuses this well and
    it is the autodiff reference.
  - `rms_norm_pallas`: a Pallas TPU kernel (rows blocked into VMEM) with a
    custom VJP whose backward recomputes through the reference (RMSNorm is
    cheap to recompute; this keeps the kernel forward-only and simple).

`rms_norm(..., impl="auto")` picks pallas on TPU when the trailing dim is
lane-aligned (multiple of 128), else the reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from shellac_tpu.ops.dispatch import pallas_supported


def rms_norm_ref(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm with fp32 accumulation, output in x.dtype."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * (1.0 + scale.astype(jnp.float32))).astype(x.dtype)


def layer_norm_ref(
    x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5
) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

_BLOCK_ROWS = 256


def _rms_kernel(x_ref, scale_ref, out_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    out_ref[:] = (y * (1.0 + scale_ref[:].astype(jnp.float32))).astype(out_ref.dtype)


def _rms_forward(x, scale, eps, interpret):
    orig_shape = x.shape
    d = orig_shape[-1]
    rows = 1
    for s in orig_shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, d)
    block = min(_BLOCK_ROWS, rows)
    # Pad rows to a multiple of the block so the grid divides evenly.
    pad = (-rows) % block
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    grid = (x2.shape[0] // block,)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, scale)
    if pad:
        out = out[:rows]
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm_pallas(x, scale, eps: float = 1e-5, interpret: bool = False):
    return _rms_forward(x, scale, eps, interpret)


def _rms_fwd(x, scale, eps, interpret):
    return _rms_forward(x, scale, eps, interpret), (x, scale)


def _rms_bwd(eps, interpret, res, g):
    x, scale = res
    _, vjp = jax.vjp(lambda x_, s_: rms_norm_ref(x_, s_, eps), x, scale)
    return vjp(g)


rms_norm_pallas.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, scale, eps: float = 1e-5, impl: str = "auto"):
    """Dispatching RMSNorm. impl: "auto" | "pallas" | "ref"."""
    if impl == "ref":
        return rms_norm_ref(x, scale, eps)
    if impl == "pallas":
        return rms_norm_pallas(x, scale, eps, not _on_tpu())
    if pallas_supported() and x.shape[-1] % 128 == 0:
        return rms_norm_pallas(x, scale, eps, False)
    return rms_norm_ref(x, scale, eps)


def _on_tpu() -> bool:
    return pallas_supported()
