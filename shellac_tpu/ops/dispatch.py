"""Backend dispatch helpers for ops with both Pallas and XLA paths."""

from __future__ import annotations

import jax


def default_backend() -> str:
    # Deliberately NOT cached: jax.default_backend() is already memoized
    # inside jax, and caching here would freeze the answer for a process
    # that initializes CPU first (e.g. a bench CPU-fallback probe) and
    # only later gains the TPU backend.
    return jax.default_backend()


def pallas_supported() -> bool:
    """True when compiled (non-interpret) Pallas TPU kernels can run."""
    return default_backend() == "tpu"
