"""Backend dispatch helpers for ops with both Pallas and XLA paths."""

from __future__ import annotations

import functools

import jax


@functools.cache
def default_backend() -> str:
    return jax.default_backend()


def pallas_supported() -> bool:
    """True when compiled (non-interpret) Pallas TPU kernels can run."""
    return default_backend() == "tpu"
