"""Flash-decode attention over KV caches as Pallas TPU kernels.

The decode hot loop reads a head-major (B, Hkv, max_len, D) cache (or a
paged block pool) with a tiny q (B, s, H, D). The XLA ref path computes
logits over the whole max_len buffer every tick; these kernels instead
stream the cache in blocks with online softmax and — the actual win —
*skip the blocks beyond each sequence's own length entirely*:
per-sequence lengths are scalar-prefetched into SMEM and both the DMA
index map and the compute are clamped to the live range. A slot at
position 130 of a 4096-token buffer touches one or two KV blocks, not
4096 rows.

Two decode-specific grid decisions, both measured on a v5e (see
BENCH_DECODE.json):
  - Head-major cache layout is load-bearing: Mosaic requires a block's
    trailing two dims to be tileable, so the per-head kv stream must be
    a contiguous (seq_block, head_dim) tile — the kvcache module stores
    caches this way precisely so these kernels never relayout them.
  - The grid iterates (batch, kv_blocks) with ALL kv heads processed
    per step (a static in-kernel loop), not (batch, head, kv_blocks):
    decode tiles are tiny (G*s rows), so a per-head grid drowns in
    per-step DMA/pipeline overhead — the first cut of this kernel ran
    2x SLOWER than the XLA ref exactly this way. Batching heads per
    step makes each DMA hkv times larger and cuts grid steps hkv-fold.

Two entry points:
  - `decode_attention`: dense cache (B, Hkv, L, D). GQA q rows are
    flattened to (H*s, D), kv-head-major, so each head's group shares
    one kv tile and kv is never replicated in HBM.
  - `paged_decode_attention`: block-pool cache (n_blocks, Hkv, bs, D)
    with per-slot tables. Same kernel body; the kv DMA indirects
    through the scalar-prefetched block table, so the dense (B,
    view, H, D) gather the ref path materializes never exists.

Both positions contracts follow forward_with_cache: q row si of batch b
sits at position lengths[b] + si, kv slot p is valid iff p <= that
(causal), optionally windowed. Rows whose scores are all masked in a
block self-correct in the online softmax once a valid block arrives
(alpha underflows to 0), and every real row attends at least its own
token.

The reference repo is empty (SURVEY.md §0); the blocked-decode idea is
the public flash-decoding / PagedAttention pattern, reimplemented for
the TPU memory hierarchy.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from shellac_tpu.ops.attention import attention_ref
from shellac_tpu.ops.dispatch import pallas_supported
from shellac_tpu.ops.flash_attention import _fit_block, sink_rebase

DEFAULT_BLOCK_K = 512
NEG_INF = -2.0e38


class PagedFallbackWarning(UserWarning):
    """Paged decode silently fell back to the dense-gather path."""


class QuantFallbackWarning(UserWarning):
    """Int8-cache decode fell back to the full-dequant reference path."""


# ---------------------------------------------------------------------------
# shared kernel body
# ---------------------------------------------------------------------------


def _decode_tile(
    idx, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale, s, hkv, block_k, window, k_start, ki, last_ki, first_ki,
    ks_ref=None, vs_ref=None, softcap=None, sink_ref=None,
):
    """One online-softmax step over every kv head of one sequence.

    idx: scalar — this sequence's pre-write length (q row si sits at
    position idx + si). q_ref/o_ref: (hkv*G*s, D) rows, kv-head-major.
    k_ref/v_ref: (hkv, block_k, D) kv tile whose first row is global
    position k_start. acc/m/l scratch span all rows; the per-head work
    is a static python loop — tiny decode matmuls cannot amortize a
    per-head grid dimension (see module docstring).

    ks_ref/vs_ref: (hkv, block_k) per-token dequant scales for int8
    caches. The scale folds in AFTER the integer-valued dot (exact:
    sum_d q*k_int*s == s * sum_d q*k_int) and, for v, onto p before the
    pv dot; the int8 stream itself is the bandwidth win.
    """
    live = (ki >= first_ki) & (k_start <= idx + s - 1)
    rows = q_ref.shape[0]
    rph = rows // hkv  # G*s rows per kv head

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(live)
    def _compute():
        r = jax.lax.broadcasted_iota(jnp.int32, (rph, block_k), 0)
        qpos = idx + r % s  # row r is (g, si=r%s) → position idx + si
        kpos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (rph, block_k), 1
        )
        mask = kpos <= qpos
        if window is not None:
            mask &= qpos - kpos < window

        for kh in range(hkv):
            sl = pl.dslice(kh * rph, rph)
            q = q_ref[sl, :].astype(jnp.float32) * scale
            k = k_ref[kh].astype(jnp.float32)
            logits = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (rph, block_k)
            if ks_ref is not None:
                logits = logits * ks_ref[kh][None, :]
            if softcap is not None:
                # Gemma-2 capping, after dequant (the dequantized value
                # IS the real scaled logit), before masking.
                logits = softcap * jnp.tanh(logits / softcap)
            logits = jnp.where(mask, logits, NEG_INF)

            m_prev = m_ref[sl, :1]
            l_prev = l_ref[sl, :1]
            m_cur = jnp.max(logits, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(logits - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[sl, :] = jnp.broadcast_to(
                alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True),
                (rph, l_ref.shape[1]),
            )
            v = v_ref[kh]
            if vs_ref is not None:
                p = p * vs_ref[kh][None, :]
                v = v.astype(jnp.float32)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[sl, :] = acc_ref[sl, :] * alpha + pv
            m_ref[sl, :] = jnp.broadcast_to(m_new, (rph, m_ref.shape[1]))

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        if sink_ref is not None:
            # GPT-OSS sink: the denominator gains exp(sink_row) (a
            # virtual zero-valued column).
            r, l2, _ = sink_rebase(m, l, sink_ref[...][:, :1])
            o_ref[...] = (acc_ref[...] * r / l2).astype(o_ref.dtype)
        else:
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)


def _decode_tile_values(
    idx, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale, s, hkv, block_k, window, k_start, ki, last_ki, first_ki,
    softcap=None, sink_ref=None,
):
    """_decode_tile for head dims whose lane count is not 128-aligned.

    Mosaic rejects ANY memref_slice on a ref whose last dim is not a
    multiple of the 128-lane tiling ("Slice shape along dimension 2
    must be aligned to tiling (128), but is 64" — found compiling the
    dh=64 parity case; interpret mode does not catch it). So this
    variant takes the RAW (1, ...) refs, reads each one whole (full
    loads of padded refs are legal), slices VALUES per kv head, and
    stores whole refs back. Same math as _decode_tile to the last op.
    """
    live = (ki >= first_ki) & (k_start <= idx + s - 1)
    rows = q_ref.shape[1]
    rph = rows // hkv

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(live)
    def _compute():
        r = jax.lax.broadcasted_iota(jnp.int32, (rph, block_k), 0)
        qpos = idx + r % s
        kpos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (rph, block_k), 1
        )
        mask = kpos <= qpos
        if window is not None:
            mask &= qpos - kpos < window

        qall = q_ref[...][0].astype(jnp.float32) * scale  # (rows, d)
        kall = k_ref[...][0]  # (hkv, block_k, d)
        vall = v_ref[...][0]
        acc_all = acc_ref[...]
        m_all = m_ref[...]
        l_all = l_ref[...]
        lanes = m_all.shape[1]
        accs, ms, ls = [], [], []
        for kh in range(hkv):
            lo, hi = kh * rph, (kh + 1) * rph
            q = jax.lax.slice_in_dim(qall, lo, hi, axis=0)
            k = jax.lax.slice_in_dim(kall, kh, kh + 1, axis=0)[0]
            logits = jax.lax.dot_general(
                q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if softcap is not None:
                logits = softcap * jnp.tanh(logits / softcap)
            logits = jnp.where(mask, logits, NEG_INF)

            m_prev = jax.lax.slice(m_all, (lo, 0), (hi, 1))
            l_prev = jax.lax.slice(l_all, (lo, 0), (hi, 1))
            m_cur = jnp.max(logits, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(logits - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
            v = jax.lax.slice_in_dim(vall, kh, kh + 1, axis=0)[0]
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_prev = jax.lax.slice_in_dim(acc_all, lo, hi, axis=0)
            accs.append(acc_prev * alpha + pv)
            ms.append(jnp.broadcast_to(m_new, (rph, lanes)))
            ls.append(jnp.broadcast_to(l_new, (rph, lanes)))
        acc_ref[...] = jnp.concatenate(accs, axis=0)
        m_ref[...] = jnp.concatenate(ms, axis=0)
        l_ref[...] = jnp.concatenate(ls, axis=0)

    @pl.when(ki == last_ki)
    def _finalize():
        l = jax.lax.slice(l_ref[...], (0, 0), (rows, 1))
        if sink_ref is not None:
            m = jax.lax.slice(m_ref[...], (0, 0), (rows, 1))
            sink = jax.lax.slice(sink_ref[...], (0, 0), (rows, 1))
            r, l2, _ = sink_rebase(m, l, sink)
            o_ref[...] = ((acc_ref[...] * r / l2).astype(o_ref.dtype))[None]
        else:
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[...] = ((acc_ref[...] / l).astype(o_ref.dtype))[None]


def _decode_tile_any(
    idx, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, **kw
):
    """Dispatch on head-dim lane alignment (see _decode_tile_values)."""
    if q_ref.shape[-1] % 128 == 0:
        _decode_tile(
            idx, q_ref.at[0], k_ref.at[0], v_ref.at[0], o_ref.at[0],
            acc_ref, m_ref, l_ref, **kw,
        )
    else:
        _decode_tile_values(
            idx, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, **kw
        )


def _live_range(idx, s, block_k, window, num_kv):
    """(first_ki, last_ki) of kv blocks any q row can attend."""
    last_ki = jnp.minimum((idx + s - 1) // block_k, num_kv - 1)
    if window is None:
        first_ki = jnp.int32(0)
    else:
        first_ki = jnp.maximum(idx - window + 1, 0) // block_k
    return first_ki, last_ki


def _split_sink_rest(rest, has_sinks):
    """Split a kernel's trailing refs into (sink_ref, remaining): the
    optional sink operand sits between the inputs and the outputs."""
    if has_sinks:
        return rest[0], rest[1:]
    return None, rest


def _row_sinks(sinks, s):
    """Per-ROW sink tile for the decode kernels: rows are kv-head-major
    q heads x s (matching _flatten_q), tiled to a 128-lane block."""
    return jnp.tile(
        jnp.repeat(sinks.astype(jnp.float32), s)[:, None], (1, 128)
    )


def _flatten_q(q, hkv):
    """(B, s, H, D) -> (B, H*s, D), rows kv-head-major (GQA groups are
    contiguous because q head h belongs to kv head h // G)."""
    b, s, h, d = q.shape
    return q.transpose(0, 2, 1, 3).reshape(b, h * s, d)


def _unflatten_o(o, b, s, h, d):
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# dense cache
# ---------------------------------------------------------------------------


def _dense_kernel(
    idx_ref, q_ref, k_ref, v_ref, *rest,
    scale, s, hkv, block_k, window, num_kv, softcap=None, has_sinks=False,
):
    sink_ref, (o_ref, acc_ref, m_ref, l_ref) = _split_sink_rest(
        rest, has_sinks
    )
    b = pl.program_id(0)
    ki = pl.program_id(1)
    idx = idx_ref[b]
    first_ki, last_ki = _live_range(idx, s, block_k, window, num_kv)
    _decode_tile_any(
        idx, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
        scale=scale, s=s, hkv=hkv, block_k=block_k, window=window,
        k_start=ki * block_k, ki=ki, last_ki=last_ki, first_ki=first_ki,
        softcap=softcap, sink_ref=sink_ref,
    )


def _dense_kernel_quant(
    idx_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, *rest,
    scale, s, hkv, block_k, window, num_kv, softcap=None, has_sinks=False,
):
    """Dense kernel over an int8 cache with per-token dequant scales
    (d % 128 == 0 only; the dispatch gate guarantees it)."""
    sink_ref, (o_ref, acc_ref, m_ref, l_ref) = _split_sink_rest(
        rest, has_sinks
    )
    b = pl.program_id(0)
    ki = pl.program_id(1)
    idx = idx_ref[b]
    first_ki, last_ki = _live_range(idx, s, block_k, window, num_kv)
    _decode_tile(
        idx, q_ref.at[0], k_ref.at[0], v_ref.at[0], o_ref.at[0],
        acc_ref, m_ref, l_ref,
        scale=scale, s=s, hkv=hkv, block_k=block_k, window=window,
        k_start=ki * block_k, ki=ki, last_ki=last_ki, first_ki=first_ki,
        ks_ref=ks_ref.at[0], vs_ref=vs_ref.at[0], softcap=softcap,
        sink_ref=sink_ref,
    )


def _dense_flash(q, cache_k, cache_v, index, scale, window, block_k,
                 interpret, k_scale=None, v_scale=None, softcap=None,
                 sinks=None):
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    _, hkv, max_len, _ = cache_k.shape
    rows = h * s
    num_kv = max_len // block_k
    quant = k_scale is not None

    qf = _flatten_q(q, hkv)

    def kv_map(bi, ki, idx_ref):
        first_ki, last_ki = _live_range(
            idx_ref[bi], s, block_k, window, num_kv
        )
        # Clamp dead blocks onto the live range: Mosaic only issues a
        # DMA when the block index changes, so skipped blocks cost no
        # HBM bandwidth.
        return bi, 0, jnp.clip(ki, first_ki, last_ki), 0

    def scale_map(bi, ki, idx_ref):
        first_ki, last_ki = _live_range(
            idx_ref[bi], s, block_k, window, num_kv
        )
        return bi, 0, jnp.clip(ki, first_ki, last_ki)

    in_specs = [
        pl.BlockSpec((1, rows, d), lambda bi, ki, idx_ref: (bi, 0, 0)),
        pl.BlockSpec((1, hkv, block_k, d), kv_map),
        pl.BlockSpec((1, hkv, block_k, d), kv_map),
    ]
    operands = [qf, cache_k, cache_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, hkv, block_k), scale_map),
            pl.BlockSpec((1, hkv, block_k), scale_map),
        ]
        operands += [k_scale, v_scale]
    has_sinks = sinks is not None
    if has_sinks:
        in_specs += [
            pl.BlockSpec((rows, 128), lambda bi, ki, idx_ref: (0, 0)),
        ]
        operands += [_row_sinks(sinks, s)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, num_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, rows, d), lambda bi, ki, idx_ref: (bi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, d), jnp.float32),
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _dense_kernel_quant if quant else _dense_kernel,
            scale=scale, s=s, hkv=hkv, block_k=block_k,
            window=window, num_kv=num_kv, softcap=softcap,
            has_sinks=has_sinks,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, d), q.dtype),
        interpret=interpret,
    )(index.astype(jnp.int32), *operands)
    return _unflatten_o(out, b, s, h, d)


def _pick_block_k(max_len: int, hkv: int, block_k: int) -> int:
    """Largest workable kv block: divides max_len, and the (hkv,
    block_k, d) k+v tiles stay within a double-buffered VMEM budget."""
    # ~4 MiB for k+v at bf16 with double buffering: hkv*block_k <= 8192.
    cap = max(8, 8192 // max(hkv, 1))
    return _fit_block(max_len, min(block_k, cap))


def decode_supported(
    q, cache_k, *, block_k: Optional[int] = None, quant: bool = False
) -> bool:
    """Can the compiled dense decode kernel handle these shapes?"""
    b, s, h, d = q.shape
    hkv, max_len, dk = cache_k.shape[1], cache_k.shape[2], cache_k.shape[3]
    if d % 64 != 0 or dk != d:
        return False
    if quant and d % 128 != 0:
        # The int8-cache kernel reuses the ref-slicing fast tile, which
        # needs full-lane head dims; dh=64 int8 takes the ref path.
        return False
    if h % hkv != 0:
        return False
    if h * s > 1024:  # VMEM accumulator budget
        return False
    return _pick_block_k(max_len, hkv, block_k or DEFAULT_BLOCK_K) != 0


def decode_attention(
    q, cache_k, cache_v, index, *,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
    sinks=None,
    impl: str = "auto",
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
    k_scale=None, v_scale=None,
):
    """Attention of q (B, s, H, D) against a dense cache (B, Hkv, L, D).

    index: (B,) int32 — per-sequence pre-write length; q row si sits at
    position index + si and attends kv positions <= its own (optionally
    windowed). Dispatches to the Pallas kernel when supported, else the
    masked reference path (bit-identical semantics).

    k_scale/v_scale: (B, Hkv, L) per-token dequant scales for an int8
    cache (see kvcache.QuantKVCache); both or neither.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale come together")
    quant = k_scale is not None
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not pallas_supported()
    shapes_ok = decode_supported(q, cache_k, block_k=block_k, quant=quant)
    if impl == "flash":
        if not shapes_ok:
            raise ValueError(
                f"impl='flash' unsupported for q={q.shape} "
                f"cache={cache_k.shape} quant={quant}"
            )
        use_kernel = True
    else:
        # 'auto' only takes the kernel when compiled Pallas is live —
        # interpret mode exists for tests, not as a dispatch target.
        use_kernel = impl == "auto" and pallas_supported() and shapes_ok
        if (impl == "auto" and pallas_supported() and not shapes_ok
                and quant):
            # An int8 cache whose shape disqualifies the kernel takes a
            # ref path that dequantizes the WHOLE buffer every tick —
            # more HBM traffic than the bf16 cache the operator was
            # trying to halve. Same say-it-once policy as the paged
            # fallback warning.
            warnings.warn(
                "decode_attention: int8-cache Pallas kernel unavailable "
                f"for q={tuple(q.shape)} cache={tuple(cache_k.shape)} — "
                "the reference fallback dequantizes the full cache every "
                "tick (the kv_quant bandwidth win inverts). Kernel "
                "needs head_dim % 128 == 0 for int8 caches.",
                QuantFallbackWarning,
                stacklevel=2,
            )
    if use_kernel:
        bk = _pick_block_k(cache_k.shape[2], cache_k.shape[1], block_k)
        return _dense_flash(
            q, cache_k, cache_v, index, float(scale), window, bk, interpret,
            k_scale=k_scale, v_scale=v_scale,
            softcap=None if softcap is None else float(softcap),
            sinks=sinks,
        )
    return _decode_ref(
        q, cache_k, cache_v, index, window, scale, softcap=softcap,
        sinks=sinks, k_scale=k_scale, v_scale=v_scale,
    )


def _decode_ref(q, cache_k, cache_v, index, window, scale, softcap=None,
                sinks=None, k_scale=None, v_scale=None):
    if k_scale is not None:
        # Dequantize the int8 cache at read; XLA fuses the multiply
        # into the attention contraction's operand read.
        cache_k = cache_k.astype(jnp.float32) * k_scale[..., None]
        cache_v = cache_v.astype(jnp.float32) * v_scale[..., None]
        cache_k = cache_k.astype(q.dtype)
        cache_v = cache_v.astype(q.dtype)
    # cache: (B, Hkv, L, D) head-major -> (B, L, Hkv, D) for the ref.
    cache_k = cache_k.transpose(0, 2, 1, 3)
    cache_v = cache_v.transpose(0, 2, 1, 3)
    b, s = q.shape[:2]
    max_len = cache_k.shape[1]
    cdt = q.dtype
    q_positions = index[:, None] + jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32), (b, s)
    )
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_len, dtype=jnp.int32), (b, max_len)
    )
    kv_mask = kv_positions < (index[:, None] + s)
    return attention_ref(
        q, cache_k.astype(cdt), cache_v.astype(cdt),
        causal=True, window=window, scale=scale, softcap=softcap,
        sinks=sinks,
        q_positions=q_positions, kv_positions=kv_positions, kv_mask=kv_mask,
    )


# ---------------------------------------------------------------------------
# paged cache
# ---------------------------------------------------------------------------


def _paged_group_kernel(
    len_ref, tab_ref, q_ref, k_hbm, v_hbm, *rest,
    scale, s, hkv, bs, group, window, num_kv, softcap=None,
    has_sinks=False, quant=False,
):
    """Grouped paged decode: `group` pages gathered per grid step.

    The one-page-per-grid-step kernel loses to the XLA dense-gather ref
    at serving page sizes (block_size 16 measured 0.61x on v5e,
    BENCH_DECODE.json): each step pays full grid/pipeline overhead to
    DMA a (hkv, 16, d) sliver and feed the MXU a 16-wide dot. Here the
    pool stays in HBM (memory_space=ANY) and the kernel gathers `group`
    pages itself with parallel async copies into one contiguous VMEM
    tile, so per-step overhead amortizes `group`-fold and the dot runs
    group*bs wide. Skipping is page-granular: dead groups issue no DMAs
    at all, and a live boundary group only fetches its live pages —
    dead page slots are ZEROED in VMEM instead (cheaper than HBM
    traffic, and required: unfetched scratch is uninitialized, and a
    stray Inf/NaN bit pattern would poison the accumulator through the
    masked-out p=0 rows as 0*Inf).
    """
    if quant:
        # Int8 pools travel with fp32 scale pools, gathered page-for-
        # page into their own VMEM tiles (sem rows 2/3).
        ks_hbm, vs_hbm = rest[0], rest[1]
        rest = rest[2:]
    sink_ref, rest = _split_sink_rest(rest, has_sinks)
    if quant:
        (o_ref, acc_ref, m_ref, l_ref, k_buf, v_buf, ks_buf, vs_buf,
         sems) = rest
    else:
        o_ref, acc_ref, m_ref, l_ref, k_buf, v_buf, sems = rest
        ks_buf = vs_buf = None
    b = pl.program_id(0)
    gi = pl.program_id(1)
    idx = len_ref[b]
    block_k = group * bs
    num_groups = num_kv // group
    first_gi, last_gi = _live_range(idx, s, block_k, window, num_groups)
    live = (gi >= first_gi) & (gi * block_k <= idx + s - 1)
    # Per-page live range (page granularity, not group granularity).
    last_pg = jnp.minimum((idx + s - 1) // bs, num_kv - 1)
    if window is None:
        first_pg = jnp.int32(0)
    else:
        first_pg = jnp.maximum(idx - window + 1, 0) // bs

    def _pg_live(g):
        pg = gi * group + g
        return (pg >= first_pg) & (pg <= last_pg)

    @pl.when(live)
    def _gather():
        from jax.experimental.pallas import tpu as pltpu

        for g in range(group):
            dst = pl.dslice(g * bs, bs)

            @pl.when(_pg_live(g))
            def _fetch(g=g, dst=dst):
                page = tab_ref[b, gi * group + g]
                pltpu.make_async_copy(
                    k_hbm.at[page], k_buf.at[:, dst, :], sems.at[0, g]
                ).start()
                pltpu.make_async_copy(
                    v_hbm.at[page], v_buf.at[:, dst, :], sems.at[1, g]
                ).start()
                if quant:
                    pltpu.make_async_copy(
                        ks_hbm.at[page], ks_buf.at[:, dst], sems.at[2, g]
                    ).start()
                    pltpu.make_async_copy(
                        vs_hbm.at[page], vs_buf.at[:, dst], sems.at[3, g]
                    ).start()

            @pl.when(~_pg_live(g))
            def _zero(dst=dst):
                k_buf[:, dst, :] = jnp.zeros_like(k_buf[:, dst, :])
                v_buf[:, dst, :] = jnp.zeros_like(v_buf[:, dst, :])
                if quant:
                    # Zero scales keep dead columns exactly zero through
                    # the dequant multiplies (masked anyway; belt and
                    # braces against uninitialized-scratch Inf/NaN).
                    ks_buf[:, dst] = jnp.zeros_like(ks_buf[:, dst])
                    vs_buf[:, dst] = jnp.zeros_like(vs_buf[:, dst])

        for g in range(group):
            dst = pl.dslice(g * bs, bs)

            @pl.when(_pg_live(g))
            def _await(g=g, dst=dst):
                pltpu.make_async_copy(
                    k_hbm.at[0], k_buf.at[:, dst, :], sems.at[0, g]
                ).wait()
                pltpu.make_async_copy(
                    v_hbm.at[0], v_buf.at[:, dst, :], sems.at[1, g]
                ).wait()
                if quant:
                    pltpu.make_async_copy(
                        ks_hbm.at[0], ks_buf.at[:, dst], sems.at[2, g]
                    ).wait()
                    pltpu.make_async_copy(
                        vs_hbm.at[0], vs_buf.at[:, dst], sems.at[3, g]
                    ).wait()

    _decode_tile(
        idx, q_ref.at[0], k_buf, v_buf, o_ref.at[0],
        acc_ref, m_ref, l_ref,
        scale=scale, s=s, hkv=hkv, block_k=block_k, window=window,
        k_start=gi * block_k, ki=gi, last_ki=last_gi, first_ki=first_gi,
        ks_ref=ks_buf, vs_ref=vs_buf, softcap=softcap, sink_ref=sink_ref,
    )


def _paged_group_flash(
    q, pool_k, pool_v, tables, index, scale, window, group, interpret,
    softcap=None, sinks=None, k_scale=None, v_scale=None,
):
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    hkv, bs = pool_k.shape[1], pool_k.shape[2]
    rows = h * s
    num_kv = tables.shape[1]
    num_groups = num_kv // group
    block_k = group * bs
    quant = k_scale is not None

    qf = _flatten_q(q, hkv)

    in_specs = [
        pl.BlockSpec((1, rows, d), lambda bi, gi, lr, tr: (bi, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),  # k pool stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),  # v pool stays in HBM
    ]
    operands = [qf, pool_k, pool_v]
    if quant:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),  # scale pools too
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        operands += [k_scale, v_scale]
    has_sinks = sinks is not None
    if has_sinks:
        in_specs += [
            pl.BlockSpec((rows, 128), lambda bi, gi, lr, tr: (0, 0)),
        ]
        operands += [_row_sinks(sinks, s)]
    scratch = [
        pltpu.VMEM((rows, d), jnp.float32),
        pltpu.VMEM((rows, 128), jnp.float32),
        pltpu.VMEM((rows, 128), jnp.float32),
        pltpu.VMEM((hkv, block_k, d), pool_k.dtype),
        pltpu.VMEM((hkv, block_k, d), pool_v.dtype),
    ]
    if quant:
        scratch += [
            pltpu.VMEM((hkv, block_k), jnp.float32),
            pltpu.VMEM((hkv, block_k), jnp.float32),
        ]
    scratch += [pltpu.SemaphoreType.DMA((4 if quant else 2, group))]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, num_groups),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, rows, d), lambda bi, gi, lr, tr: (bi, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_group_kernel, scale=scale, s=s, hkv=hkv, bs=bs,
            group=group, window=window, num_kv=num_kv, softcap=softcap,
            has_sinks=has_sinks, quant=quant,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, d), q.dtype),
        interpret=interpret,
    )(index.astype(jnp.int32), tables.astype(jnp.int32), *operands)
    return _unflatten_o(out, b, s, h, d)


def _paged_group(tables, pool_k) -> int:
    """Pages per grid step: aim for a ~512-row kv tile, divide the
    table, and respect the VMEM budget the one-page kernel enforces.
    Returns 1 (one-page kernel) when grouping cannot work: the gather
    lands each page at sublane offset g*bs of the VMEM tile, so bs
    must be a multiple of the dtype's sublane tile (fp32 8, bf16 16,
    int8 32) or Mosaic rejects the slice."""
    num_kv = tables.shape[1]
    hkv, bs = pool_k.shape[1], pool_k.shape[2]
    sublane = 8 * max(1, 4 // jnp.dtype(pool_k.dtype).itemsize)
    if bs % sublane:
        return 1
    cap = max(1, 8192 // max(hkv * bs, 1))  # hkv*group*bs <= 8192
    g = min(max(512 // bs, 1), cap, num_kv)
    while g > 1 and num_kv % g:
        g -= 1
    return g


def _paged_kernel(
    len_ref, tab_ref, q_ref, k_ref, v_ref, *rest,
    scale, s, hkv, block_k, window, num_kv, softcap=None, has_sinks=False,
):
    sink_ref, (o_ref, acc_ref, m_ref, l_ref) = _split_sink_rest(
        rest, has_sinks
    )
    b = pl.program_id(0)
    ki = pl.program_id(1)
    idx = len_ref[b]
    first_ki, last_ki = _live_range(idx, s, block_k, window, num_kv)
    _decode_tile_any(
        idx, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
        scale=scale, s=s, hkv=hkv, block_k=block_k, window=window,
        k_start=ki * block_k, ki=ki, last_ki=last_ki, first_ki=first_ki,
        softcap=softcap, sink_ref=sink_ref,
    )


def _paged_flash(q, pool_k, pool_v, tables, index, scale, window, interpret,
                 softcap=None, sinks=None):
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    hkv = pool_k.shape[1]
    bs = pool_k.shape[2]
    rows = h * s
    num_kv = tables.shape[1]  # logical blocks per slot

    qf = _flatten_q(q, hkv)

    def kv_map(bi, ki, len_ref, tab_ref):
        first_ki, last_ki = _live_range(len_ref[bi], s, bs, window, num_kv)
        ki = jnp.clip(ki, first_ki, last_ki)
        # Indirect through the block table: logical block ki of slot bi
        # lives at pool block tables[bi, ki]. Unallocated entries point
        # at scratch block 0 and are never live.
        return tab_ref[bi, ki], 0, 0, 0

    in_specs = [
        pl.BlockSpec((1, rows, d), lambda bi, ki, lr, tr: (bi, 0, 0)),
        pl.BlockSpec((1, hkv, bs, d), kv_map),
        pl.BlockSpec((1, hkv, bs, d), kv_map),
    ]
    operands = [qf, pool_k, pool_v]
    has_sinks = sinks is not None
    if has_sinks:
        in_specs += [
            pl.BlockSpec((rows, 128), lambda bi, ki, lr, tr: (0, 0)),
        ]
        operands += [_row_sinks(sinks, s)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, num_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, rows, d), lambda bi, ki, lr, tr: (bi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, d), jnp.float32),
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=scale, s=s, hkv=hkv, block_k=bs,
            window=window, num_kv=num_kv, softcap=softcap,
            has_sinks=has_sinks,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, rows, d), q.dtype),
        interpret=interpret,
    )(index.astype(jnp.int32), tables.astype(jnp.int32), *operands)
    return _unflatten_o(out, b, s, h, d)


def paged_decode_supported(q, pool_k, *, quant: bool = False) -> bool:
    b, s, h, d = q.shape
    hkv, bs, dk = pool_k.shape[1], pool_k.shape[2], pool_k.shape[3]
    if d % 64 != 0 or dk != d:
        return False
    if quant and (d % 128 != 0 or bs % 32 != 0):
        # Int8 runs through the grouped-gather kernel only: its tile
        # body is the ref-slicing fast path (full-lane head dims) and
        # the page gather lands each page at sublane offset g*bs, which
        # int8's (32, 128) native tile requires to be 32-aligned.
        return False
    if h % hkv != 0 or bs % 8 != 0:
        return False
    if hkv * bs > 8192:
        # Same double-buffered VMEM budget the dense path enforces via
        # _pick_block_k; the paged tile is fixed by the pool's page
        # size, so oversized pages must fall back rather than fail to
        # compile.
        return False
    return h * s <= 1024


def paged_decode_attention(
    q, pool_k, pool_v, tables, index, *,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
    sinks=None,
    impl: str = "auto",
    interpret: Optional[bool] = None,
    k_scale=None, v_scale=None,
):
    """Attention of q (B, s, H, D) against a paged pool via block tables.

    pool_k/v: (n_blocks, Hkv, bs, D); tables: (B, max_blocks) int32;
    index: (B,) pre-write lengths. The kernel walks each slot's table —
    the dense per-slot view is never materialized. Falls back to
    gather + masked reference attention when unsupported.

    k_scale/v_scale: (n_blocks, Hkv, bs) fp32 per-token dequant scale
    pools for an int8 pool (see kvcache.QuantPagedKVCache); both or
    neither. The grouped kernel gathers scale pages alongside value
    pages and folds them in after the integer dots (same exact algebra
    as the dense int8 kernel).
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale come together")
    quant = k_scale is not None
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not pallas_supported()
    shapes_ok = paged_decode_supported(q, pool_k, quant=quant)
    if impl == "flash":
        if not shapes_ok:
            raise ValueError(
                f"impl='flash' unsupported for q={q.shape} "
                f"pool={pool_k.shape} quant={quant}"
            )
        use_kernel = True
    else:
        # 'auto' defaults bf16 pools to the XLA reference path: across
        # three measurement rounds the grouped-gather paged kernel has
        # never beaten the reference on hardware (BENCH_DECODE
        # 2026-07-31 chip run: 284.7 vs 261.9 us/call, 0.92x, at
        # serving page sizes) — and the engine tick is host-bound
        # anyway, so the kernel cannot pay its complexity tax.
        # impl='flash' still forces it (parity tests, future re-
        # measurement). Int8 pools KEEP the kernel under auto: their
        # reference fallback dequantizes gathered pages every tick,
        # inverting the kv_quant bandwidth win.
        use_kernel = (impl == "auto" and pallas_supported() and shapes_ok
                      and quant)
        if (impl == "auto" and pallas_supported() and not shapes_ok
                and quant):
            # The operator asked for paged serving on a TPU but the pool
            # shape silently disqualifies the kernel — the fallback
            # materializes the dense (B, view, Hkv, D) gather every
            # step, which defeats the point of paging. Say so once per
            # shape (warnings' default "once per message+location"
            # dedup), with the actionable constraint named.
            b, s, h, d = q.shape
            hkv, bs, dk = pool_k.shape[1], pool_k.shape[2], pool_k.shape[3]
            warnings.warn(
                "paged_decode_attention: Pallas kernel unavailable for "
                f"q={tuple(q.shape)} pool={tuple(pool_k.shape)} "
                f"quant={quant} — falling back to a dense gather + "
                "reference attention (paging's memory win is lost). "
                "Kernel needs: head_dim % 64 == 0 "
                f"(got {d}), pool head_dim == q head_dim (got {dk} vs {d}), "
                f"page block size % 8 == 0 (got {bs}), "
                f"n_heads % kv_heads == 0 (got {h}/{hkv}), "
                f"H*s <= 1024 (got {h * s})"
                + (", and for int8 pools head_dim % 128 == 0 with "
                   "block size % 32 == 0." if quant else "."),
                PagedFallbackWarning,
                stacklevel=2,
            )
    if use_kernel:
        # Grouped gather kernel when the head dim keeps full-lane tiles
        # (its tile body is the ref-slicing fast path) and grouping
        # actually amortizes anything; one-page kernel otherwise. Int8
        # pools always take the grouped kernel (the support gate
        # guarantees its constraints): the one-page kernel's BlockSpec
        # body has no scale plumbing.
        group = _paged_group(tables, pool_k) if q.shape[-1] % 128 == 0 else 1
        sc = None if softcap is None else float(softcap)
        if group > 1 or quant:
            return _paged_group_flash(
                q, pool_k, pool_v, tables, index, float(scale), window,
                max(group, 1), interpret, softcap=sc, sinks=sinks,
                k_scale=k_scale, v_scale=v_scale,
            )
        return _paged_flash(
            q, pool_k, pool_v, tables, index, float(scale), window, interpret,
            softcap=sc, sinks=sinks,
        )
    from shellac_tpu.inference.kvcache import (
        paged_gather_layer,
        paged_gather_scales,
    )

    k_all, v_all = paged_gather_layer(pool_k, pool_v, tables)
    ks_all = vs_all = None
    if quant:
        ks_all = paged_gather_scales(k_scale, tables)
        vs_all = paged_gather_scales(v_scale, tables)
    return _decode_ref(q, k_all, v_all, index, window, scale, softcap=softcap,
                       sinks=sinks, k_scale=ks_all, v_scale=vs_all)


def rolled_decode_attention(
    q, cache_k, cache_v, start, lengths_after, *,
    window: int,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
    sinks=None,
):
    """Attention of q (B, s, H, D) against a RING buffer
    (B, Hkv, ring, D) whose newest position is lengths_after - 1 (the
    chunk was already written). q row j sits at position start + j —
    padded chunks put their REAL rows first, so the start anchors the
    q positions (rows past lengths_after - start are padding whose
    outputs the caller discards).

    Per-slot positions are reconstructed from the ring arithmetic and
    fed to the reference attention — the ring is window-sized, so the
    Pallas decode kernels' dead-block skipping has nothing to win here
    and the masked reference over O(window) keys IS the fast path.
    """
    from shellac_tpu.inference.kvcache import rolled_kv_positions

    b, s = q.shape[:2]
    ring = cache_k.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    kv_pos, kv_mask = rolled_kv_positions(lengths_after, ring)
    q_pos = start.astype(jnp.int32)[:, None] + jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32), (b, s)
    )

    def ref_dtype(x):
        # fp32 rings (int8 dequant) keep their precision — the fp32
        # logit einsum upcasts the other operand anyway; only widen
        # narrower inputs to the q dtype.
        return x if x.dtype == jnp.float32 else x.astype(q.dtype)

    return attention_ref(
        q, ref_dtype(cache_k.transpose(0, 2, 1, 3)),
        ref_dtype(cache_v.transpose(0, 2, 1, 3)),
        causal=True, window=window, scale=scale, softcap=softcap,
        sinks=sinks,
        q_positions=q_pos, kv_positions=kv_pos, kv_mask=kv_mask,
    )
