"""Mixture-of-experts routing and expert FFN.

TPU-first choices:
  - Static shapes everywhere: per-expert capacity buckets (tokens over
    capacity are dropped, standard Switch/GShard semantics), so the
    whole layer jits with no data-dependent shapes.
  - Scatter/gather dispatch (`.at[slot].add`, `take`): O(T·D) HBM
    traffic, instead of the classic one-hot dispatch einsum whose
    T·E·C·D MXU cost dwarfs the expert matmuls at long sequence.
  - Expert FFNs run as one batched einsum over the expert axis, sharded
    over the mesh's (ep, fsdp) axes; GSPMD inserts the collectives.
  - Expert parallelism is pure sharding: the dispatched capacity
    buckets (E, C, D) are constrained to shard E over the ep axis, so
    the scatter that builds them reshards token-sharded activations to
    expert-sharded buckets — that resharding IS the all-to-all, chosen
    by XLA (an explicit shard_map ppermute would hand-schedule what
    GSPMD already lays out). The expert FFN einsums are then local to
    each ep group, and the combine gather reshards back.
  - Router math in fp32, with load-balance and router-z auxiliary losses.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from shellac_tpu.config import MoEConfig
from shellac_tpu.ops.quant import materialize
from shellac_tpu.parallel.sharding import constrain


def expert_capacity(cfg: MoEConfig, num_tokens: int) -> int:
    cap = int(cfg.capacity_factor * num_tokens * cfg.num_experts_per_token
              / cfg.num_experts)
    return max(cap, 1)


def _group_mask(choice: jax.Array, cfg: MoEConfig, group_rank) -> jax.Array:
    """Zero out experts outside the top `topk_group` groups.

    `group_rank` ranks each group from its members' scores — max for
    softmax (V2), top-2 sum for sigmoid (V3) — matching each HF gate.
    """
    t, e = choice.shape
    g = cfg.n_group
    group_scores = group_rank(choice.reshape(t, g, e // g))
    _, gidx = jax.lax.top_k(group_scores, cfg.topk_group)
    gmask = jnp.zeros((t, g), choice.dtype).at[
        jnp.arange(t)[:, None], gidx
    ].set(1.0)
    return choice * jnp.repeat(gmask, e // g, axis=1)


def _route_scores(
    x: jax.Array,  # (T, D) — flattened tokens
    w_router: jax.Array,  # (D, E)
    cfg: MoEConfig,
    b_router: jax.Array | None = None,  # (E,) sigmoid selection bias
) -> Tuple[jax.Array, jax.Array, jax.Array, dict]:
    """Gate scoring shared by the capacity-bucket and grouped paths:
    returns (expert_idx (T, k) int32, weight (T, k) fp32, aux_loss
    scalar, metrics dict WITHOUT a drop fraction — dropping is the
    capacity path's business)."""
    e, k = cfg.num_experts, cfg.num_experts_per_token

    logits = jnp.einsum(
        "td,de->te", x.astype(jnp.float32), w_router.astype(jnp.float32)
    )
    if cfg.scoring == "softmax_topk" and b_router is not None:
        # GPT-OSS router bias is part of the logits proper (selection
        # AND weights AND the aux losses see it).
        logits = logits + b_router.astype(jnp.float32)[None]
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E) — also feeds aux
    if cfg.scoring == "softmax_topk":
        # GPT-OSS gate: top-k over RAW logits, softmax over just the
        # kept values (not a renormalized slice of the full softmax —
        # the dropped logits never enter the denominator).
        top_vals, expert_idx = jax.lax.top_k(logits, k)
        weight = jax.nn.softmax(top_vals, axis=-1)
    elif cfg.scoring == "sigmoid":
        # DeepSeek-V3 gate: sigmoid scores; an additive per-expert bias
        # steers SELECTION only (load balancing knob trained outside
        # the gradient), combine weights come from the raw scores.
        scores = jax.nn.sigmoid(logits)
        choice = scores + (b_router.astype(jnp.float32)[None]
                           if b_router is not None else 0.0)
        if cfg.n_group > 1:
            choice = _group_mask(
                choice, cfg,
                lambda gsc: jnp.sum(jax.lax.top_k(gsc, 2)[0], axis=-1),
            )
        _, expert_idx = jax.lax.top_k(choice, k)
        weight = jnp.take_along_axis(scores, expert_idx, axis=-1)
        if cfg.norm_topk_prob:
            weight = weight / (jnp.sum(weight, -1, keepdims=True) + 1e-20)
    else:
        probs_sel = probs
        if cfg.n_group > 1:
            # V2's group rank is the max member probability.
            probs_sel = _group_mask(
                probs, cfg, lambda gsc: jnp.max(gsc, axis=-1)
            )
        weight, expert_idx = jax.lax.top_k(probs_sel, k)  # (T, k)
        if cfg.norm_topk_prob:
            # Renormalize the kept probabilities to sum to 1.
            weight = weight / jnp.maximum(
                jnp.sum(weight, -1, keepdims=True), 1e-9
            )
    weight = weight * cfg.routed_scaling_factor

    # Load-balance loss (Switch §2.2 form): E * sum_e f_e * p_e where
    # f_e = fraction of tokens whose top-1 is e, p_e = mean router prob.
    top1 = jax.nn.one_hot(expert_idx[:, 0], e, dtype=jnp.float32)
    f = jnp.mean(top1, axis=0)
    p = jnp.mean(probs, axis=0)
    balance_loss = e * jnp.sum(f * p)
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    aux = (cfg.router_aux_loss_weight * balance_loss
           + cfg.router_z_loss_weight * z_loss)
    metrics = {
        "moe_balance_loss": balance_loss,
        "moe_router_z_loss": z_loss,
    }
    return expert_idx, weight, aux, metrics


def route(
    x: jax.Array,  # (T, D) — flattened tokens
    w_router: jax.Array,  # (D, E)
    cfg: MoEConfig,
    capacity: int | None = None,
    b_router: jax.Array | None = None,  # (E,) sigmoid selection bias
) -> Tuple[jax.Array, jax.Array, jax.Array, dict]:
    """Top-k routing with capacity buckets.

    Returns (slot (T, k) int32 — flat index into E*C, or E*C when
    dropped/overflow; weight (T, k) fp32 combine weights; aux_loss
    scalar; metrics dict).
    """
    t, _ = x.shape
    e = cfg.num_experts
    c = expert_capacity(cfg, t) if capacity is None else capacity
    expert_idx, weight, aux, metrics = _route_scores(
        x, w_router, cfg, b_router
    )

    # Position of each assignment within its expert, in token order:
    # cumsum over the one-hot assignment matrix (T*k, E).
    flat_expert = expert_idx.reshape(-1)  # (T*k,)
    onehot = jax.nn.one_hot(flat_expert, e, dtype=jnp.int32)  # (T*k, E)
    pos = jnp.cumsum(onehot, axis=0) - 1  # position per expert
    pos_in_expert = jnp.take_along_axis(pos, flat_expert[:, None], axis=1)[:, 0]
    ok = pos_in_expert < c
    slot = jnp.where(ok, flat_expert * c + pos_in_expert, e * c)  # overflow -> E*C
    k = expert_idx.shape[1]
    slot = slot.reshape(t, k).astype(jnp.int32)

    dropped = jnp.mean(1.0 - ok.reshape(t, k).astype(jnp.float32))
    metrics = dict(metrics, moe_dropped_frac=dropped)
    return slot, weight, aux, metrics


def _check_expert_shards(e: int, mesh) -> None:
    if mesh is None:
        return
    from shellac_tpu.parallel.mesh import AXIS_EXPERT, AXIS_FSDP

    shards = mesh.shape.get(AXIS_EXPERT, 1) * mesh.shape.get(AXIS_FSDP, 1)
    if e % shards:
        raise ValueError(
            f"num_experts={e} must divide evenly over the expert "
            f"shards (ep*fsdp={shards}); uneven splits silently "
            "pad and waste MXU time"
        )


def _expert_act(gate: jax.Array, up: jax.Array, cfg: MoEConfig):
    """Pre-activation clamp + gated activation, shared by the bucket
    and grouped paths so their math cannot drift (the grouped-vs-
    bucket parity test depends on it)."""
    if cfg.gate_limit is not None:
        # GPT-OSS clamps pre-activation: gate one-sided to limit, up
        # symmetric.
        lim = cfg.gate_limit
        gate = jnp.clip(gate, None, lim)
        up = jnp.clip(up, -lim, lim)
    if cfg.expert_act == "gptoss":
        # glu = gate * sigmoid(1.702 * gate); output (up + 1) * glu.
        return (up + 1.0) * (gate * jax.nn.sigmoid(1.702 * gate))
    return jax.nn.silu(gate) * up


def moe_ffn(
    x: jax.Array,  # (B, S, D) compute dtype
    w_router: jax.Array,  # (D, E)
    w_gate: jax.Array,  # (E, D, F)
    w_up: jax.Array,  # (E, D, F)
    w_down: jax.Array,  # (E, F, D)
    cfg: MoEConfig,
    *,
    drop_tokens: bool = True,
    mesh=None,
    b_router: jax.Array | None = None,
    b_gate: jax.Array | None = None,  # (E, F)
    b_up: jax.Array | None = None,  # (E, F)
    b_down: jax.Array | None = None,  # (E, D)
) -> Tuple[jax.Array, jax.Array, dict]:
    """Returns (out (B, S, D), aux_loss scalar, metrics).

    drop_tokens=False sizes capacity at T (worst case: every token's
    top-1 on one expert) so nothing ever drops — required for decode,
    where a capacity drop would silently zero a token's FFN output and
    make generation diverge from prefill. Only safe for small T.
    """
    b, s, d = x.shape
    e = cfg.num_experts
    t = b * s
    c = expert_capacity(cfg, t) if drop_tokens else t
    cdt = x.dtype
    _check_expert_shards(e, mesh)

    x2 = x.reshape(t, d)
    slot, weight, aux, metrics = route(
        x2, w_router, cfg, capacity=c, b_router=b_router
    )
    k = slot.shape[1]

    # Scatter tokens into capacity buckets; one extra slot absorbs drops.
    buckets = jnp.zeros((e * c + 1, d), cdt)
    flat_slot = slot.reshape(-1)  # (T*k,)
    x_rep = jnp.repeat(x2, k, axis=0)  # (T*k, D) — token for each assignment
    buckets = buckets.at[flat_slot].add(x_rep, mode="drop")
    # Dispatch boundary: constrain the buckets to expert sharding. The
    # scatter's input is token-sharded (batch over dp/fsdp, seq over
    # sp); forcing its output onto the ep axis here is what makes XLA
    # emit the token all-to-all instead of replicating the buckets.
    dispatched = constrain(
        buckets[: e * c].reshape(e, c, d), mesh, ("experts", None, None)
    )

    # Expert FFNs: batched over the expert axis (sharded over 'fsdp').
    gate = jnp.einsum("ecd,edf->ecf", dispatched, materialize(w_gate, cdt),
                      preferred_element_type=jnp.float32).astype(cdt)
    up = jnp.einsum("ecd,edf->ecf", dispatched, materialize(w_up, cdt),
                    preferred_element_type=jnp.float32).astype(cdt)
    if b_gate is not None:
        gate = gate + b_gate.astype(cdt)[:, None, :]
    if b_up is not None:
        up = up + b_up.astype(cdt)[:, None, :]
    act = _expert_act(gate, up, cfg)
    act = constrain(act, mesh, ("experts", None, "mlp"))
    out_e = jnp.einsum("ecf,efd->ecd", act, materialize(w_down, cdt),
                       preferred_element_type=jnp.float32).astype(cdt)
    out_e = constrain(out_e, mesh, ("experts", None, None))
    if b_down is not None:
        # The per-expert output bias applies to every ROUTED token's
        # expert output (dropped tokens still get zeros downstream).
        out_e = out_e + b_down.astype(cdt)[:, None, :]

    # Gather back and combine with router weights (dropped -> zeros row).
    out_flat = jnp.concatenate([out_e.reshape(e * c, d),
                                jnp.zeros((1, d), cdt)], axis=0)
    gathered = jnp.take(out_flat, flat_slot, axis=0).reshape(t, k, d)
    combined = jnp.sum(gathered * weight[..., None].astype(cdt), axis=1)
    return combined.reshape(b, s, d), aux, metrics


def moe_ffn_grouped(
    x: jax.Array,  # (B, S, D) compute dtype
    w_router: jax.Array,  # (D, E)
    w_gate: jax.Array,  # (E, D, F)
    w_up: jax.Array,  # (E, D, F)
    w_down: jax.Array,  # (E, F, D)
    cfg: MoEConfig,
    *,
    mesh=None,
    b_router: jax.Array | None = None,
    b_gate: jax.Array | None = None,  # (E, F)
    b_up: jax.Array | None = None,  # (E, F)
    b_down: jax.Array | None = None,  # (E, D)
) -> Tuple[jax.Array, jax.Array, dict]:
    """DROPLESS MoE via grouped (sorted-segment) expert matmuls.

    Token assignments sort by expert id; each expert's contiguous
    segment feeds `jax.lax.ragged_dot` (XLA's grouped matmul, which
    Mosaic lowers to MXU-tiled per-group GEMMs on TPU). No capacity
    buckets exist, so nothing can drop: `moe_dropped_frac == 0` by
    construction — the loss-sensitive fine-tuning option the
    capacity-bucket path can't provide. Memory is O(T*k*F), the same
    as a dense MLP over the assignments, so it is training-viable at
    large T, unlike the capacity-at-T dropless buckets
    (MoEConfig.dropless), which exist for decode's tiny T.

    Sharding note: ragged group sizes are data-dependent, so the
    expert axis cannot shard the way the capacity buckets do — under
    an ep mesh GSPMD gathers the expert weights to each data shard.
    Correct everywhere (the ep dryrun runs it), but for ep-sharded
    THROUGHPUT training prefer the capacity path; grouped is for
    exactness-sensitive runs.
    """
    b, s, d = x.shape
    e, k = cfg.num_experts, cfg.num_experts_per_token
    t = b * s
    cdt = x.dtype
    _check_expert_shards(e, mesh)

    x2 = x.reshape(t, d)
    expert_idx, weight, aux, metrics = _route_scores(
        x2, w_router, cfg, b_router
    )
    flat_e = expert_idx.reshape(-1)  # (T*k,)
    order = jnp.argsort(flat_e, stable=True)
    x_sorted = jnp.take(x2, order // k, axis=0)  # (T*k, D) by expert
    seg_e = jnp.take(flat_e, order)  # sorted expert id per row
    group_sizes = jnp.zeros((e,), jnp.int32).at[flat_e].add(1)

    def gdot(lhs, rhs):
        return jax.lax.ragged_dot(
            lhs, materialize(rhs, cdt), group_sizes,
            preferred_element_type=jnp.float32,
        ).astype(cdt)

    gate = gdot(x_sorted, w_gate)
    up = gdot(x_sorted, w_up)
    if b_gate is not None:
        gate = gate + jnp.take(b_gate, seg_e, axis=0).astype(cdt)
    if b_up is not None:
        up = up + jnp.take(b_up, seg_e, axis=0).astype(cdt)
    act = _expert_act(gate, up, cfg)
    down = gdot(act, w_down)  # (T*k, D)
    if b_down is not None:
        down = down + jnp.take(b_down, seg_e, axis=0).astype(cdt)

    # Unsort and combine with router weights.
    inv = jnp.argsort(order)
    out_assign = jnp.take(down, inv, axis=0).reshape(t, k, d)
    combined = jnp.sum(out_assign * weight[..., None].astype(cdt), axis=1)
    metrics = dict(metrics, moe_dropped_frac=jnp.zeros((), jnp.float32))
    return combined.reshape(b, s, d), aux, metrics
