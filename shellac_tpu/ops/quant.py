"""Int8 weight-only quantization for inference.

Decode is HBM-bandwidth bound: every generated token re-reads the whole
weight set, so halving (bf16) or quartering (fp32) the bytes per weight
is a direct decode-throughput win. Weights are stored as a `QTensor`
pytree node — int8 values plus a per-output-channel fp32 scale — and
dequantized on the fly right at the matmul: XLA fuses the
`convert + multiply` into the dot's operand read, so no full-size fp
copy of the weight ever lands in HBM.

Symmetric per-channel scheme: for a stacked weight (L, in, out), the
scale is max|W| / 127 over the `in` (reduction) axis, shape (L, 1, out).
Per-channel (not per-tensor) keeps the quantization error of any one
output feature independent of outlier magnitudes elsewhere.

`QTensor` is registered as a pytree node, so quantized layer stacks flow
through `lax.scan` exactly like plain arrays, and the model code only
changes at one choke point: `materialize(w, dtype)` replaces
`w.astype(dtype)` and handles both plain and quantized weights.

The reference repo for this project is empty (SURVEY.md §0); there is no
upstream quantization scheme to cite.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.struct
import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig

# Per-layer stacked matrices eligible for weight-only quantization.
DENSE_TARGETS: Tuple[str, ...] = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
)


@flax.struct.dataclass
class QTensor:
    """Int8 weight + fp32 per-output-channel scale (reduction axis static)."""

    q: jax.Array  # int8, same shape as the original weight
    scale: jax.Array  # fp32, 1 on the reduction axis, broadcastable to q

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def size(self):
        return self.q.size


def materialize(w, dtype):
    """Dequantize a QTensor (or cast a plain array) to `dtype`.

    The single choke point model code calls instead of `.astype`; XLA
    fuses the convert+scale into the consuming matmul's operand read.
    """
    if isinstance(w, QTensor):
        return (w.q.astype(dtype) * w.scale.astype(dtype))
    return w.astype(dtype)


# Max representable magnitude per storage dtype (fp8-e4m3 tops out at 448).
_QMAX = {
    jnp.dtype(jnp.int8): 127.0,
    jnp.dtype(jnp.float8_e4m3fn): 448.0,
}


def quantize(w: jax.Array, reduction_axis: int = -2, dtype=jnp.int8) -> QTensor:
    """Symmetric quantization with per-channel scales.

    dtype: jnp.int8 (rounded) or jnp.float8_e4m3fn (cast; keeps relative
    precision for small weights at the same byte width).
    reduction_axis: the matmul contraction axis of `w` (for a stacked
    (L, in, out) weight that is -2); the scale is constant along it.
    """
    qdt = jnp.dtype(dtype)
    if qdt not in _QMAX:
        raise ValueError(
            f"unsupported quantization dtype {qdt}; "
            f"have {sorted(str(d) for d in _QMAX)}"
        )
    qmax = _QMAX[qdt]
    w = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=reduction_axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    scaled = w / scale
    if qdt == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.round(scaled), -qmax, qmax).astype(qdt)
    else:
        q = scaled.astype(qdt)
    return QTensor(q=q, scale=scale)


def dequantize(qt: QTensor, dtype=jnp.float32) -> jax.Array:
    return materialize(qt, dtype)


def quantize_params(
    cfg: ModelConfig,
    params,
    targets: Tuple[str, ...] = DENSE_TARGETS,
    dtype=jnp.int8,
) -> Any:
    """Quantize the per-layer matrices of a parameter pytree.

    Embeddings (and the tied LM head) stay in their original dtype: the
    embedding is read by gather (no matmul to fuse dequant into) and the
    final projection's fp32 accumulation dominates its cost. MoE expert
    weights (E, in, out)-stacked are quantized along their contraction
    axis too.
    """
    unknown = set(targets) - set(DENSE_TARGETS)
    if unknown:
        raise ValueError(
            f"unknown quantization targets {sorted(unknown)}; "
            f"have {sorted(DENSE_TARGETS)}"
        )

    def quantize_stack(stack, _name):
        out = dict(stack)
        for t in targets:
            if t not in out:
                continue
            # Stacked dense: (L, in, out) → axis -2. Stacked MoE experts:
            # (L, E, in, out) → also axis -2. Router stays fp (tiny, and
            # its logits feed a top-k where small errors flip routing).
            out[t] = quantize(out[t], reduction_axis=-2, dtype=dtype)
        return out

    from shellac_tpu.models.transformer import map_layer_stacks

    out = dict(params)
    out["layers"] = map_layer_stacks(params["layers"], quantize_stack)
    return out


def quantize_logical_axes(axes, targets: Tuple[str, ...] = DENSE_TARGETS):
    """Mirror `quantize_params` on a logical-axes pytree.

    Each targeted weight's axes tuple becomes a QTensor of axes: `q`
    keeps the weight's axes; `scale` (1 on the reduction axis) keeps the
    leading/output axes so it shards with the channels it scales.
    """
    def axes_stack(stack, _name):
        out = dict(stack)
        for t in targets:
            if t not in out:
                continue
            wa = out[t]
            out[t] = QTensor(q=wa, scale=(*wa[:-2], None, wa[-1]))
        return out

    from shellac_tpu.models.transformer import map_layer_stacks

    out = dict(axes)
    out["layers"] = map_layer_stacks(axes["layers"], axes_stack)
    return out
