import sys

from shellac_tpu.cli import main

sys.exit(main())
