"""Flight recorder + trace-id plumbing for distributed request tracing.

Two small, dependency-free pieces:

  trace ids — every request is identified by one W3C-traceparent-shaped
    id (`00-<32 hex>-<16 hex>-01`) minted by whichever layer sees the
    request first (the tier router, the HTTP handler, or `_submit` for
    direct library callers). The id travels replica-ward in an
    `x-shellac-trace` request header carrying the tier's attempt
    number (`<traceparent>;attempt=N`) and client-ward in an
    `x-request-id` response header and inside ndjson/SSE stream
    records — so the tier's attempt log, the replica's request span,
    the flight-recorder timeline, and the client's error report all
    quote the SAME id.

  `FlightRecorder` — a bounded ring buffer of structured lifecycle
    events (admit / queue / prefill / first-token / window-dispatch /
    window-settle / finish / shed / cancelled / error / fault, plus the
    tier's tier-attempt / retry / eject family). Appends are a lock +
    deque op; when the ring is full the OLDEST event is dropped and a
    counter (`shellac_flight_recorder_dropped_total`) says so — the
    recorder degrades by forgetting history, never by blocking the
    serving path. `GET /debug/requests` reads the ring's stats and
    tail; `GET /debug/request/<trace_id>` filters it into one
    request's timeline.

Events deliberately carry NO prompt or generated text unless the
server was started with `--debug-include-text` (redaction by default:
a debug endpoint must not become a transcript exfiltration path).
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

#: Request-header name the tier forwards (and any front-end may set).
TRACE_HEADER = "x-shellac-trace"
#: Response-header name every layer echoes the trace id back on.
REQUEST_ID_HEADER = "x-request-id"

_TRACEPARENT_RE = re.compile(r"^00-[0-9a-f]{32}-[0-9a-f]{16}-[0-9a-f]{2}$")


def new_trace_id() -> str:
    """Mint a W3C-traceparent-shaped trace id: version 00, a 16-byte
    random trace-id field, an 8-byte random parent-id field, sampled
    flag set. Shaped like traceparent so a fronting proxy that speaks
    W3C trace context can adopt it verbatim; no OpenTelemetry
    dependency is involved."""
    return f"00-{os.urandom(16).hex()}-{os.urandom(8).hex()}-01"


def is_trace_id(value: str) -> bool:
    return bool(_TRACEPARENT_RE.match(value or ""))


def format_trace_header(trace_id: str, attempt: int = 0) -> str:
    """The `x-shellac-trace` wire value: the id plus the tier attempt
    number (0 = first attempt), so a replica's logs say not just WHICH
    request hit it but which retry leg it served."""
    return f"{trace_id};attempt={int(attempt)}"


def parse_trace_header(value: Optional[str]) -> Tuple[Optional[str], int]:
    """Parse an `x-shellac-trace` value -> (trace_id, attempt).
    Returns (None, 0) when absent or malformed — the caller mints a
    fresh id instead of 400ing: tracing must never reject traffic."""
    if not value:
        return None, 0
    parts = str(value).strip().split(";")
    tid = parts[0].strip().lower()
    if not is_trace_id(tid):
        return None, 0
    attempt = 0
    for part in parts[1:]:
        part = part.strip()
        if part.startswith("attempt="):
            try:
                attempt = max(0, int(part[len("attempt="):]))
            except ValueError:
                pass
    return tid, attempt


def adopt_trace(value: Optional[str]) -> Tuple[str, int]:
    """Adopt the incoming header's (trace_id, attempt), minting a fresh
    id when the header is absent or malformed."""
    tid, attempt = parse_trace_header(value)
    if tid is None:
        return new_trace_id(), attempt
    return tid, attempt


class FlightRecorder:
    """Bounded ring of structured lifecycle events.

    Writers (admission, the scheduler/engine thread, tier request
    threads, the health poller) call `record()`; readers (the /debug
    endpoints, tests) call `events_for()` / `tail()` / `stats()`.
    Everything is guarded by one lock — appends are O(1) and reads
    copy, so a scrape can never tear a writer.

    `enabled=False` (serve --no-debug) turns every record() into a
    single attribute check, mirroring the disabled-Registry pattern.

    `spool` (an obs.spool.EventSpool) is the durable half: every
    recorded event is also appended to the on-disk JSONL spool, so a
    SIGKILL'd replica's in-flight timelines survive to disk and can
    be recovered (`top --trace <id> --spool <dir>`). The ring stays
    authoritative for the live /debug endpoints; the spool is the
    black-box recording an incident review reads after the crash.
    """

    def __init__(self, capacity: int = 2048, registry=None,
                 enabled: bool = True, spool=None):
        if capacity < 1:
            raise ValueError("recorder capacity must be >= 1")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self.spool = spool
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._seq = 0
        self.dropped = 0
        # Exposition: the ring forgetting history is an operator-visible
        # condition (a timeline may be truncated), so the drop count
        # rides /metrics next to everything else.
        self._dropped_c = None
        self._recorded_c = None
        if registry is not None:
            self._dropped_c = registry.counter(
                "shellac_flight_recorder_dropped_total",
                "Flight-recorder events evicted because the ring was "
                "full (a /debug/request timeline may be truncated)",
            )
            self._recorded_c = registry.counter(
                "shellac_flight_recorder_events_total",
                "Flight-recorder events appended",
            )

    def record(self, trace_id: Optional[str], event: str,
               **fields: Any) -> None:
        """Append one event. `trace_id=None` records a system-scoped
        event (e.g. a tier ejection) that appears in the tail feed but
        belongs to no request timeline. Extra fields must be
        JSON-serializable — they are served verbatim by /debug."""
        if not self.enabled:
            return
        with self._lock:
            self._seq += 1
            if len(self._events) >= self.capacity:
                self._events.popleft()
                self.dropped += 1
                if self._dropped_c is not None:
                    self._dropped_c.inc()
            rec: Dict[str, Any] = {
                "seq": self._seq,
                "ts": time.time(),
                "trace": trace_id,
                "event": event,
            }
            rec.update(fields)
            self._events.append(rec)
        if self._recorded_c is not None:
            self._recorded_c.inc()
        if self.spool is not None:
            # Outside the ring lock: the spool serializes itself, and
            # file IO must not extend the ring's critical section. The
            # `seq` field keeps global order recoverable either way.
            self.spool.append(rec)

    def events_for(self, trace_id: str) -> List[Dict[str, Any]]:
        """Every retained event for one trace id, oldest first ([] for
        unknown ids — and for None: system events are not a timeline).
        Falls back to the lowercased id on a miss: header adoption
        normalizes ids to lowercase, so a client that sent (and then
        queries with) uppercase hex still finds its timeline."""
        if not trace_id:
            return []
        with self._lock:
            evs = [dict(e) for e in self._events
                   if e["trace"] == trace_id]
            if not evs and trace_id.lower() != trace_id:
                low = trace_id.lower()
                evs = [dict(e) for e in self._events
                       if e["trace"] == low]
        return evs

    def tail(self, n: int = 256) -> List[Dict[str, Any]]:
        """The most recent `n` events, oldest first."""
        with self._lock:
            evs = list(self._events)
        return [dict(e) for e in evs[-max(0, int(n)):]]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "events": len(self._events),
                "capacity": self.capacity,
                "dropped": self.dropped,
                "recorded": self._seq,
            }
