"""Scrape-side Prometheus text parsing and histogram math.

One parser for everyone who reads a replica's `/metrics` over the
wire: the tier's load scorer, the fleet federation collector
(`obs/fleet.py`), the `top` dashboard, and the tests that assert
against expositions. Before this module each consumer grew its own
ad-hoc line splitter (the tier's dropped every label but `le`, which
silently merged the bucket series of *labeled* histograms — e.g. the
per-phase step-time histogram — into one garbage quantile).

Deliberately NOT a general Prometheus client:

  - only the 0.0.4 text format our own `Registry.render()` emits
    (plus anything shaped like it) — `# HELP`/`# TYPE` comments,
    `name{labels} value` samples, optional trailing timestamps
    ignored;
  - malformed lines are skipped, never raised on: a scrape must
    degrade to "fewer series", not take the scraper down;
  - values parse as floats; label values un-escape the three escapes
    the exposition format defines (backslash, quote, newline).

`histogram_quantile` is the scrape-side mirror of
`obs.Histogram.percentile`: it interpolates inside the containing
bucket from cumulative `(le, count)` pairs, and treats the `+Inf`
edge consistently — the TOTAL is the `+Inf` cumulative count (the
family's `_count`), and a quantile landing in the overflow bucket
reports the last finite edge, the honest upper bound a scrape can
state (the host side reports its observed max; a scrape never sees
one).
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Optional, Tuple

#: A parsed sample: (metric name, labels, value). Labels keep their
#: exposition order in the dict (insertion-ordered).
Sample = Tuple[str, Dict[str, str], float]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*,?'
)


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
             .replace("\\\\", "\\"))


def _parse_value(s: str) -> Optional[float]:
    s = s.strip()
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    try:
        return float(s)
    except ValueError:
        return None


class ParsedMetrics:
    """The result of one parsed exposition: every sample with its
    labels intact, plus the `# TYPE` / `# HELP` metadata, behind the
    read helpers the scrapers actually need."""

    __slots__ = ("samples", "types", "helps")

    def __init__(self) -> None:
        self.samples: List[Sample] = []
        self.types: Dict[str, str] = {}
        self.helps: Dict[str, str] = {}

    # ---- reads -------------------------------------------------------

    def value(self, name: str, **labels: str) -> Optional[float]:
        """First sample of `name` whose labels CONTAIN the given pairs
        (an unlabeled lookup matches the first sample of any labeling);
        None when absent."""
        want = {k: str(v) for k, v in labels.items()}
        for n, ls, v in self.samples:
            if n != name:
                continue
            if all(ls.get(k) == v for k, v in want.items()):
                return v
        return None

    def series(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        """Every (labels, value) sample of `name`."""
        return [(ls, v) for n, ls, v in self.samples if n == name]

    def names(self) -> List[str]:
        seen: Dict[str, None] = {}
        for n, _, _ in self.samples:
            seen.setdefault(n, None)
        return list(seen)

    def buckets(self, family: str,
                **labels: str) -> List[Tuple[float, float]]:
        """Cumulative `(le, count)` pairs of `family`'s histogram,
        SUMMED per edge over every label set matching the given pairs
        (exclusive of `le`). Summing cumulative counts edge-wise is
        exact histogram aggregation when the bucket layouts agree —
        which ours do by construction (fixed log-spaced layouts). The
        returned pairs are sorted by edge with `+Inf` last."""
        want = {k: str(v) for k, v in labels.items()}
        per_edge: Dict[float, float] = {}
        for n, ls, v in self.samples:
            if n != family + "_bucket" or "le" not in ls:
                continue
            if not all(ls.get(k) == val for k, val in want.items()):
                continue
            le = _parse_value(ls["le"])
            if le is None:
                continue
            per_edge[le] = per_edge.get(le, 0.0) + v
        return sorted(per_edge.items())

    def histogram_sum_count(self, family: str, **labels: str
                            ) -> Tuple[float, float]:
        """(sum of `_sum`, sum of `_count`) over matching label sets."""
        want = {k: str(v) for k, v in labels.items()}
        s = c = 0.0
        for n, ls, v in self.samples:
            if not all(ls.get(k) == val for k, val in want.items()):
                continue
            if n == family + "_sum":
                s += v
            elif n == family + "_count":
                c += v
        return s, c

    def label_values(self, name: str, label: str) -> List[str]:
        """Distinct values `label` takes across `name`'s samples, in
        first-seen order."""
        seen: Dict[str, None] = {}
        for n, ls, _ in self.samples:
            if n == name and label in ls:
                seen.setdefault(ls[label], None)
        return list(seen)


def parse_prometheus_text(text: str) -> ParsedMetrics:
    """Parse a 0.0.4 text exposition. Lines that do not parse are
    skipped (scrapers must degrade, not raise)."""
    out = ParsedMetrics()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                out.types[parts[2]] = parts[3].strip()
            elif len(parts) >= 4 and parts[1] == "HELP":
                out.helps[parts[2]] = _unescape(parts[3])
            continue
        name, labels, rest = _split_sample(line)
        if name is None:
            continue
        # `rest` may carry an optional timestamp after the value.
        value = _parse_value(rest.split()[0]) if rest.split() else None
        if value is None:
            continue
        out.samples.append((name, labels, value))
    return out


def _split_sample(line: str):
    """-> (name, labels dict, value+timestamp remainder) or
    (None, None, None) on malformed input."""
    if "{" in line:
        name, _, tail = line.partition("{")
        name = name.strip()
        if not _NAME_RE.match(name):
            return None, None, None
        labels: Dict[str, str] = {}
        pos = 0
        while pos < len(tail) and tail[pos] != "}":
            m = _LABEL_RE.match(tail, pos)
            if m is None:
                return None, None, None
            labels[m.group(1)] = _unescape(m.group(2))
            pos = m.end()
        if pos >= len(tail):
            return None, None, None
        return name, labels, tail[pos + 1:]
    parts = line.split(None, 1)
    if len(parts) != 2 or not _NAME_RE.match(parts[0]):
        return None, None, None
    return parts[0], {}, parts[1]


def histogram_quantile(buckets: Iterable[Tuple[float, float]],
                       q: float) -> Optional[float]:
    """Estimated q-quantile (0 < q <= 1) from cumulative `(le, count)`
    pairs; None when empty or count-free.

    The `+Inf` edge is handled with the same cumulative counts as
    every other edge: the TOTAL is the `+Inf` cumulative when present
    (the family's true `_count` — the last finite bucket understates
    it whenever observations overflowed), and a target landing past
    the last finite cumulative reports the last finite edge — the
    honest upper bound a scrape can state."""
    pairs = sorted(buckets)
    if not pairs:
        return None
    if not 0 < q <= 1:
        raise ValueError(f"q must be in (0, 1], got {q}")
    total = pairs[-1][1]
    if total <= 0:
        return None
    target = q * total
    lo, prev_cum = 0.0, 0.0
    for le, cum in pairs:
        if cum >= target:
            if not math.isfinite(le):
                return lo  # overflow bucket: the last finite edge
            in_bucket = cum - prev_cum
            frac = (target - prev_cum) / in_bucket if in_bucket else 1.0
            return lo + (le - lo) * frac
        if math.isfinite(le):
            lo, prev_cum = le, cum
    return lo


def cumulative_at(buckets: Iterable[Tuple[float, float]],
                  threshold: float) -> float:
    """Estimated cumulative COUNT of observations <= `threshold`,
    interpolating inside the containing bucket — the good-event count
    a latency SLO reads off a scraped histogram ("how many requests
    beat 500ms"). Exact at bucket edges; a linear estimate inside."""
    pairs = sorted(buckets)
    if not pairs:
        return 0.0
    lo, prev_cum = 0.0, 0.0
    for le, cum in pairs:
        if threshold < le or not math.isfinite(le):
            if not math.isfinite(le):
                # Threshold beyond every finite edge: overflow
                # observations cannot be split, so the defensible
                # (lower-bound) good count is the last finite cum.
                return prev_cum
            width = le - lo
            if width <= 0:
                return cum
            frac = (threshold - lo) / width
            if frac <= 0:
                return prev_cum
            return prev_cum + (cum - prev_cum) * min(1.0, frac)
        lo, prev_cum = le, cum
    return prev_cum


def merge_buckets(series: Iterable[Iterable[Tuple[float, float]]]
                  ) -> List[Tuple[float, float]]:
    """Merge cumulative bucket lists edge-wise (sum per `le`). Exact
    when the layouts agree; with disagreeing layouts every edge is
    kept and the merged curve is still monotone in the inputs, merely
    coarser between foreign edges."""
    per_edge: Dict[float, float] = {}
    for pairs in series:
        for le, cum in pairs:
            per_edge[le] = per_edge.get(le, 0.0) + cum
    return sorted(per_edge.items())
