"""Durable event spool: a rotating, size-capped JSONL spill sink for
the flight recorder.

The recorder's ring is deliberately volatile — a bounded in-memory
deque that dies with the process. That is the right cost model for a
healthy replica, and exactly wrong for the replica that matters in an
incident review: a SIGKILL'd pod takes every in-flight timeline with
it. The spool is the durability half: when configured (`serve
--spool-dir`), every recorder event is ALSO appended as one JSON line
to an on-disk file, flushed per write, so `kill -9` mid-stream leaves
the request's admit/prefill/first-token/delta history readable from
disk (`top --trace <id> --spool <dir>`, or `read_spool()` directly).

Durability model: `flush()` per event pushes the line into the OS
page cache — that survives PROCESS death (the incident-review case),
not machine power loss. No fsync: the spool rides the serving path
and a per-event fsync would turn every lifecycle event into a disk
round-trip.

Size model: one active file plus one rotated predecessor, each capped
at `max_bytes // 2` — total on-disk footprint <= max_bytes however
long the replica runs, mirroring the ring's bounded-memory contract.
Rotation is `os.replace` of the whole file, so a reader never sees a
half-truncated file, and the torn LAST line a kill can leave behind
is skipped (not fatal) at read time.

Redaction: the PR 10 rule applies on the way to disk too. Unless the
spool was built with `include_text=True` (the server wires its own
`--debug-include-text` through), prompt/output text keys are stripped
from every record — a crash dump must not become a transcript
exfiltration path any more than the live /debug endpoints may.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterable, Iterator, List, Optional

#: Event fields that may carry prompt/generated text (the PR 10
#: redaction surface); stripped unless the spool opts into text.
TEXT_FIELDS = ("prompt_text", "output_text", "text")

#: Default on-disk footprint cap (active + rotated file together).
DEFAULT_MAX_BYTES = 8 << 20

#: Active spool file name under a spool directory.
SPOOL_NAME = "events.jsonl"


def spool_path(spool_dir: str) -> str:
    return os.path.join(spool_dir, SPOOL_NAME)


class EventSpool:
    """Append-only JSONL sink with one-file rotation.

    Thread-safe; writers pay one lock + one buffered write + flush per
    event. A spool that hits an OSError (disk full, permissions)
    disables itself and counts the failure rather than raising into
    the serving path — durability is best-effort, serving is not.
    """

    def __init__(self, path: str, *, max_bytes: int = DEFAULT_MAX_BYTES,
                 include_text: bool = False):
        if max_bytes < 4096:
            raise ValueError("spool max_bytes must be >= 4096")
        self.path = path
        self.max_bytes = int(max_bytes)
        self.include_text = bool(include_text)
        # Per-process run token stamped into every record (`_run`):
        # the recorder's `seq` restarts at 1 with the process, so a
        # spool spanning a restart (the SIGKILL-then-respawn scenario)
        # needs run identity to order the two runs — readers order by
        # (run first-appearance, seq) and then strip the field.
        self.run_id = os.urandom(4).hex()
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0
        self.write_errors = 0
        self.rotations = 0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    # ---- write side --------------------------------------------------

    def _open_locked(self) -> None:
        if self._fh is None:
            # Binary append: the size cap is a BYTE budget, and a
            # text-mode len(str) would undercount multibyte UTF-8
            # (non-ASCII prompt text under include_text) ~3x.
            self._fh = open(self.path, "ab")
            self._size = self._fh.tell()

    def _rotate_locked(self) -> None:
        """Active file -> `<path>.1` (clobbering the previous rotation)
        atomically; a fresh active file starts empty. Keeping exactly
        one predecessor bounds the footprint at max_bytes while a
        reader still sees up to a full cap of history."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(self.path, self.path + ".1")
        self.rotations += 1
        self._open_locked()

    def append(self, event: Dict[str, Any]) -> None:
        """Write one recorder event as a JSON line (redacted unless
        include_text). Errors disable the spool for the process — a
        full disk degrades durability, never serving."""
        if self._fh is None and self.write_errors:
            return  # disabled after a write failure
        if self.include_text:
            event = dict(event)
        else:
            event = {k: v for k, v in event.items()
                     if k not in TEXT_FIELDS}
        event["_run"] = self.run_id
        line = (json.dumps(event, default=str) + "\n").encode("utf-8")
        if len(line) > self.max_bytes // 2:
            # One record must never exceed a whole file's budget
            # (rotation could not bound it). Keep the skeleton —
            # losing the oversized payload honestly beats breaking
            # the footprint contract.
            event = {k: event[k] for k in
                     ("seq", "ts", "trace", "event", "_run")
                     if k in event}
            event["truncated"] = True
            line = (json.dumps(event, default=str) + "\n").encode(
                "utf-8")
        with self._lock:
            try:
                self._open_locked()
                if self._size + len(line) > self.max_bytes // 2:
                    self._rotate_locked()
                self._fh.write(line)
                # Per-event flush into the page cache: the line must
                # survive a SIGKILL that lands between events.
                self._fh.flush()
                self._size += len(line)
            except OSError:
                self.write_errors += 1
                try:
                    if self._fh is not None:
                        self._fh.close()
                finally:
                    self._fh = None

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "max_bytes": self.max_bytes,
                "size": self._size,
                "rotations": self.rotations,
                "write_errors": self.write_errors,
                "include_text": self.include_text,
            }

    # ---- read side ---------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        return read_spool(self.path)

    def events_for(self, trace_id: str) -> List[Dict[str, Any]]:
        return spool_events_for(self.path, trace_id)


def _read_lines(path: str) -> Iterable[Dict[str, Any]]:
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # A SIGKILL between write() and flush() can leave a
                    # torn final line; everything before it is intact.
                    continue
                if isinstance(rec, dict):
                    yield rec
    except OSError:
        return


def resolve_spool_path(path: str) -> str:
    """Accept the active spool file OR the directory holding it (the
    serve --spool-dir value an operator remembers)."""
    if os.path.isdir(path):
        return spool_path(path)
    return path


def _iter_spool(path: str) -> Iterator[Dict[str, Any]]:
    path = resolve_spool_path(path)
    yield from _read_lines(path + ".1")
    yield from _read_lines(path)


def _order(out: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Restore event order and strip the `_run` bookkeeping field.

    `seq` is assigned under the recorder's ring lock but the spool
    append happens outside it, so racing writers can land out of file
    order — seq is the authority WITHIN one process run. Across runs
    (a restarted replica reusing its --spool-dir) seq resets to 1, so
    runs are ordered by first appearance in the file and seq sorts
    within each."""
    runs: Dict[str, int] = {}
    for e in out:
        r = str(e.get("_run", ""))
        if r not in runs:
            runs[r] = len(runs)
    if all("seq" in e for e in out):
        out.sort(key=lambda e: (runs[str(e.get("_run", ""))],
                                e["seq"]))
    for e in out:
        e.pop("_run", None)
    return out


def read_spool(path: str) -> List[Dict[str, Any]]:
    """Every retained event, oldest first: the rotated predecessor
    (if any) then the active file. `path` is the active spool file or
    its directory."""
    return _order(list(_iter_spool(path)))


def spool_events_for(path: str, trace_id: Optional[str]
                     ) -> List[Dict[str, Any]]:
    """One trace id's timeline recovered from disk (the dead-replica
    path behind `top --trace <id> --spool <dir>`, and the live
    server's ring-miss fallback). Filters WHILE parsing so a lookup
    holds only the matching events, not the whole spool — though
    every line is still scanned (the spool is an append log, not an
    index); treat this as a debug path, not a hot one. Case-
    normalizes like FlightRecorder.events_for."""
    if not trace_id:
        return []
    low = trace_id.lower()
    hits: List[Dict[str, Any]] = []
    low_hits: List[Dict[str, Any]] = []
    for e in _iter_spool(path):
        t = e.get("trace")
        if t == trace_id:
            hits.append(e)
        elif low != trace_id and t == low:
            low_hits.append(e)
    return _order(hits or low_hits)
