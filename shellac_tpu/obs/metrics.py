"""Dependency-free metrics core: counters, gauges, histograms, and a
process-global registry with Prometheus text exposition.

Design constraints (this is serving/training observability, not a
general TSDB client):

  - stdlib only — the serving path must not grow a dependency;
  - writes are cheap and host-side: an `observe()` is a bisect plus a
    few adds under a per-instrument lock, so instrumenting once per
    engine STEP (never per token, never inside jitted code) costs
    nothing measurable;
  - a disabled registry turns every write into a single attribute
    check, so `serve --no-metrics` has near-zero overhead without any
    call-site branching;
  - registration is idempotent: asking for the same (name, kind,
    labels) returns the same instrument, so engines and servers built
    repeatedly in one process (tests, supervisor rebuilds) share
    series instead of colliding.

Histograms use fixed log-spaced buckets (`log_buckets`): latency
distributions span decades, and fixed buckets mean exposition never
reshapes under load (Prometheus requires bucket stability to compute
rates across scrapes).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def log_buckets(lo: float = 0.001, hi: float = 60.0,
                per_decade: int = 4) -> Tuple[float, ...]:
    """Log-spaced bucket upper bounds from `lo` to >= `hi`.

    Bounds land on 10^(k/per_decade): with the defaults that is ~1ms to
    60s at 4 buckets per decade (~20 buckets) — wide enough for TTFT on
    a cold compile and fine enough that p50/p99 interpolation is
    meaningful.
    """
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi, got lo={lo}, hi={hi}")
    if per_decade < 1:
        raise ValueError("per_decade must be >= 1")
    out: List[float] = []
    k = math.floor(math.log10(lo) * per_decade + 0.5)
    while True:
        b = 10.0 ** (k / per_decade)
        out.append(float(f"{b:.6g}"))  # kill float noise: 0.001, not 0.00099..
        if b >= hi:
            break
        k += 1
    return tuple(out)


def linear_buckets(lo: float, width: float, count: int) -> Tuple[float, ...]:
    """`count` upper bounds: lo, lo+width, ... (occupancy-style ratios)."""
    if count < 1:
        raise ValueError("count must be >= 1")
    return tuple(float(f"{lo + i * width:.6g}") for i in range(count))


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats print as integers."""
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Instrument:
    """Common base: every instrument knows its registry so a disabled
    registry short-circuits writes with one attribute check."""

    __slots__ = ("_registry", "_lock")

    def __init__(self, registry: "Registry"):
        self._registry = registry
        self._lock = threading.Lock()


class Counter(_Instrument):
    kind = "counter"
    __slots__ = ("value",)

    def __init__(self, registry: "Registry"):
        super().__init__(registry)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self.value += amount


class Gauge(_Instrument):
    kind = "gauge"
    __slots__ = ("value",)

    def __init__(self, registry: "Registry"):
        super().__init__(registry)
        self.value = 0.0

    def set(self, v: float) -> None:
        if not self._registry.enabled:
            return
        self.value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram(_Instrument):
    """Fixed-bucket histogram with cumulative Prometheus exposition and
    host-side percentile estimates (for /stats summaries)."""

    kind = "histogram"
    __slots__ = ("uppers", "counts", "sum", "count", "_max", "exemplars")

    def __init__(self, registry: "Registry", buckets: Sequence[float]):
        super().__init__(registry)
        ups = tuple(float(b) for b in buckets)
        if not ups:
            raise ValueError("histogram needs at least one bucket")
        if any(b2 <= b1 for b1, b2 in zip(ups, ups[1:])):
            raise ValueError(f"buckets must strictly increase: {ups}")
        if any(not math.isfinite(b) for b in ups):
            raise ValueError("buckets must be finite (+Inf is implicit)")
        self.uppers = ups
        self.counts = [0] * (len(ups) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self._max = float("-inf")
        # Last exemplar (an opaque label, in practice a trace id) per
        # bucket — allocated lazily on the first exemplar'd observe, so
        # histograms that never carry exemplars pay one None check.
        self.exemplars: Optional[List[Optional[str]]] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        if not self._registry.enabled:
            return
        v = float(v)
        idx = bisect.bisect_left(self.uppers, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1
            if v > self._max:
                self._max = v
            if exemplar is not None:
                if self.exemplars is None:
                    self.exemplars = [None] * (len(self.uppers) + 1)
                self.exemplars[idx] = exemplar

    def bucket_exemplars(self) -> Dict[str, str]:
        """{bucket le: last exemplar observed into that bucket} for
        buckets that have one. The resolution path from a latency
        outlier to a concrete request: the `+Inf`/top-bucket entry of a
        TTFT histogram is a trace id whose flight-recorder timeline
        (`GET /debug/request/<id>`) explains the outlier."""
        with self._lock:
            if self.exemplars is None:
                return {}
            out: Dict[str, str] = {}
            for i, ex in enumerate(self.exemplars):
                if ex is None:
                    continue
                le = ("+Inf" if i == len(self.uppers)
                      else _fmt(self.uppers[i]))
                out[le] = ex
            return out

    def cumulative_pairs(self) -> List[Tuple[float, float]]:
        """Cumulative `(le, count)` pairs including the `+Inf` edge —
        the same shape a scrape-side parser produces, so host-side
        histograms and scraped ones feed one quantile/SLO code path."""
        with self._lock:
            counts = list(self.counts)
        out: List[Tuple[float, float]] = []
        cum = 0
        for upper, c in zip(self.uppers, counts[:-1]):
            cum += c
            out.append((float(upper), float(cum)))
        out.append((float("inf"), float(cum + counts[-1])))
        return out

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0 < q <= 1) by linear interpolation
        within the containing bucket; None when empty. Values in the
        +Inf overflow bucket report the observed max (the honest upper
        edge a fixed-bucket histogram can state)."""
        if not 0 < q <= 1:
            raise ValueError(f"q must be in (0, 1], got {q}")
        with self._lock:
            n = self.count
            if n == 0:
                return None
            target = q * n
            cum = 0
            lo = 0.0
            for i, c in enumerate(self.counts):
                if c and cum + c >= target:
                    if i == len(self.uppers):  # overflow bucket
                        return self._max
                    hi = self.uppers[i]
                    frac = (target - cum) / c
                    return min(lo + (hi - lo) * frac, self._max)
                cum += c
                if i < len(self.uppers):
                    lo = self.uppers[i]
            return self._max  # unreachable in practice (counts sum to n)

    def summary(self) -> Dict[str, Optional[float]]:
        """The /stats-style digest: count, mean, p50/p90/p99."""
        with self._lock:
            n, s = self.count, self.sum
        return {
            "count": n,
            "mean": (s / n) if n else None,
            "p50": self.percentile(0.50),
            "p90": self.percentile(0.90),
            "p99": self.percentile(0.99),
        }


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One metric name: a kind, a help string, label names, and a
    series per label-value tuple. With no labels there is exactly one
    series, keyed by the empty tuple."""

    __slots__ = ("name", "kind", "help", "labelnames", "buckets",
                 "series", "_registry", "_lock")

    def __init__(self, registry: "Registry", name: str, kind: str,
                 help: str, labelnames: Tuple[str, ...],
                 buckets: Optional[Tuple[float, ...]]):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        self.buckets = buckets
        self.series: Dict[Tuple[str, ...], _Instrument] = {}
        self._registry = registry
        self._lock = threading.Lock()

    def labels(self, **labelvalues) -> _Instrument:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labelvalues))}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        inst = self.series.get(key)
        if inst is None:
            with self._lock:
                inst = self.series.get(key)
                if inst is None:
                    inst = self._make()
                    self.series[key] = inst
        return inst

    def _make(self) -> _Instrument:
        if self.kind == "histogram":
            return Histogram(self._registry, self.buckets)
        return _KINDS[self.kind](self._registry)

    def _default(self) -> _Instrument:
        """The unlabeled series (only valid for label-free families)."""
        return self.labels()


_DEFAULT_BUCKETS = log_buckets()


class Registry:
    """Named metric families with thread-safe idempotent registration,
    Prometheus text exposition, and a JSON-able snapshot."""

    def __init__(self, enabled: bool = True):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self.enabled = enabled

    def disable(self) -> None:
        """Turn every write into a no-op (`serve --no-metrics`).
        Registration still works, so call sites need no branching."""
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    # ---- registration ------------------------------------------------

    def _family(self, name: str, kind: str, help: str,
                labels: Iterable[str],
                buckets: Optional[Sequence[float]] = None) -> _Family:
        labelnames = tuple(labels)
        bk = tuple(float(b) for b in buckets) if buckets is not None else None
        if kind == "histogram" and bk is None:
            bk = _DEFAULT_BUCKETS
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(self, name, kind, help, labelnames, bk)
                self._families[name] = fam
                return fam
        if fam.kind != kind:
            raise ValueError(
                f"{name} already registered as {fam.kind}, not {kind}"
            )
        if fam.labelnames != labelnames:
            raise ValueError(
                f"{name} already registered with labels {fam.labelnames}, "
                f"not {labelnames}"
            )
        if kind == "histogram" and fam.buckets != bk:
            raise ValueError(
                f"{name} already registered with buckets {fam.buckets}"
            )
        return fam

    def counter(self, name: str, help: str = "", labels: Iterable[str] = ()):
        """A Counter (no labels) or a labeled family exposing
        `.labels(**values)`."""
        fam = self._family(name, "counter", help, labels)
        return fam if fam.labelnames else fam._default()

    def gauge(self, name: str, help: str = "", labels: Iterable[str] = ()):
        fam = self._family(name, "gauge", help, labels)
        return fam if fam.labelnames else fam._default()

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Optional[Sequence[float]] = None):
        fam = self._family(name, "histogram", help, labels, buckets)
        return fam if fam.labelnames else fam._default()

    # ---- reads -------------------------------------------------------

    def get(self, name: str, **labelvalues) -> Optional[_Instrument]:
        """The live instrument for (name, labels), or None. A read-side
        helper for tests and /stats — never creates series."""
        fam = self._families.get(name)
        if fam is None:
            return None
        key = tuple(str(labelvalues.get(ln, "")) for ln in fam.labelnames)
        return fam.series.get(key)

    def value(self, name: str, **labelvalues) -> Optional[float]:
        inst = self.get(name, **labelvalues)
        if inst is None:
            return None
        return inst.count if isinstance(inst, Histogram) else inst.value

    def total(self, name: str) -> Optional[float]:
        """Sum of a family's series values across ALL label
        combinations (counters/gauges: value; histograms: observation
        count). None when the family was never registered. The read
        surface for "how many X happened, regardless of label" — the
        tier's /stats uses it — so callers never walk internals."""
        fam = self._families.get(name)
        if fam is None:
            return None
        with fam._lock:
            insts = list(fam.series.values())
        return float(sum(
            i.count if isinstance(i, Histogram) else i.value
            for i in insts
        ))

    def family_names(self) -> List[str]:
        """Registered family names (the tier's federated exposition
        uses this to avoid duplicate # TYPE headers for families both
        the tier and its replicas expose)."""
        with self._lock:
            return list(self._families)

    # ---- exposition --------------------------------------------------

    @staticmethod
    def _labelstr(names: Tuple[str, ...], values: Tuple[str, ...],
                  extra: str = "") -> str:
        parts = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            # Snapshot the series dict under the family lock: another
            # thread settling a request can insert a new labeled series
            # (first 'cancelled' outcome, say) mid-scrape, and
            # iterating the live dict would raise.
            with fam._lock:
                series = sorted(fam.series.items())
            if not series:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, inst in series:
                if isinstance(inst, Histogram):
                    with inst._lock:
                        counts = list(inst.counts)
                        total, s = inst.count, inst.sum
                    cum = 0
                    for upper, c in zip(fam.buckets, counts):
                        cum += c
                        ls = self._labelstr(fam.labelnames, key,
                                            f'le="{_fmt(upper)}"')
                        lines.append(f"{fam.name}_bucket{ls} {cum}")
                    ls = self._labelstr(fam.labelnames, key, 'le="+Inf"')
                    lines.append(f"{fam.name}_bucket{ls} {total}")
                    ls = self._labelstr(fam.labelnames, key)
                    lines.append(f"{fam.name}_sum{ls} {_fmt(s)}")
                    lines.append(f"{fam.name}_count{ls} {total}")
                else:
                    ls = self._labelstr(fam.labelnames, key)
                    lines.append(f"{fam.name}{ls} {_fmt(inst.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able dump (bench output files): per family, the kind
        and every series' value — histograms carry their full bucket
        counts plus a p50/p90/p99 digest so distribution shape survives
        into BENCH_* artifacts."""
        out: Dict[str, dict] = {}
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            series = []
            with fam._lock:  # same insertion race as render()
                rows_src = sorted(fam.series.items())
            for key, inst in rows_src:
                row: Dict[str, object] = {
                    "labels": dict(zip(fam.labelnames, key)),
                }
                if isinstance(inst, Histogram):
                    with inst._lock:
                        row["buckets"] = {
                            _fmt(u): c
                            for u, c in zip(fam.buckets, inst.counts)
                        }
                        row["overflow"] = inst.counts[-1]
                        row["sum"] = inst.sum
                    row.update(inst.summary())
                else:
                    row["value"] = inst.value
                series.append(row)
            if series:
                out[fam.name] = {"type": fam.kind, "series": series}
        return out


# Process-global default: every engine, server, and training loop in a
# process deposits into one registry unless handed its own, so a single
# /metrics scrape (or snapshot) sees the whole picture.
_default_registry = Registry()


def get_registry() -> Registry:
    return _default_registry


def set_default_registry(registry: Registry) -> Registry:
    """Swap the process default (tests); returns the previous one."""
    global _default_registry
    old = _default_registry
    _default_registry = registry
    return old
