"""Request-trace spans and the serving/engine instrument bundles.

A `RequestTrace` rides one request through the serving pipeline —
submit -> queue wait -> prefill -> first token -> per-token decode ->
finish/shed/abort — and deposits the derived latency histograms
(queue-wait, TTFT, time-per-output-token, end-to-end) on settlement.
Every timestamp is host-side `time.monotonic()` captured at an event
the host already observes (queue pop, post-sync token arrival), so
tracing adds no host-device syncs anywhere, let alone inside jitted
code (the SH002 contract).

`ServeMetrics` / `EngineMetrics` bundle the instruments each layer
writes so the metric names and bucket layouts are defined exactly once;
both are cheap to construct repeatedly over the same registry
(registration is idempotent).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from shellac_tpu.obs.metrics import (
    Registry,
    linear_buckets,
    log_buckets,
)

#: Latency buckets shared by the request-span histograms: ~1ms..60s.
LATENCY_BUCKETS = log_buckets(0.001, 60.0, per_decade=4)
#: Per-output-token pace is faster than request latency: ~0.1ms..10s.
TPOT_BUCKETS = log_buckets(0.0001, 10.0, per_decade=4)
#: Batch occupancy is a ratio; eighths resolve typical slot counts.
OCCUPANCY_BUCKETS = linear_buckets(0.125, 0.125, 8)

#: Step-time phases (the `phase` label of shellac_step_phase_seconds).
#: Every engine step's wall time decomposes into exactly these, so
#: sum-over-phases ≈ step wall time and "where does the tick go" is a
#: committed measurement (the disaggregation question's input):
#:   admission        — queue pops, slot prep, finish checks in the
#:                      fill loop (everything admission-side that is
#:                      NOT the prefill programs themselves)
#:   prefill_dispatch — prefill/chunk program dispatches (host-side
#:                      dispatch cost only; with overlapped prefill
#:                      the sync moved to prefill_settle)
#:   prefill_settle   — time blocked in the prefill settle's one
#:                      batched device_get plus its host bookkeeping
#:                      (inline per admission without overlap_prefill;
#:                      one batched pull per step boundary with it)
#:   decode_sync      — time blocked in the decode window's one
#:                      packed device_get
#:   settle           — applying synced window results: detokenize
#:                      appends, finish checks, slot release
#:   host_bookkeeping — the remainder (dispatch bookkeeping, gauge
#:                      updates, scheduler glue)
STEP_PHASES = ("admission", "prefill_dispatch", "prefill_settle",
               "decode_sync", "settle", "host_bookkeeping")

#: Request outcomes (the `outcome` label of shellac_requests_total).
#: ok: completed; shed: deadline expired before prefill; cancelled:
#: client abandoned it; error: bad request; fault: server-side failure
#: (scheduler death, wedge, close) — the supervisor's loud-failure arm.
OUTCOMES = ("ok", "shed", "cancelled", "error", "fault")


class ServeMetrics:
    """The serving-layer instruments over one registry."""

    def __init__(self, registry: Registry):
        self.registry = registry
        h, c, g = registry.histogram, registry.counter, registry.gauge
        self.ttft = h(
            "shellac_ttft_seconds",
            "Time from request submit to its first generated token",
            buckets=LATENCY_BUCKETS,
        )
        self.tpot = h(
            "shellac_tpot_seconds",
            "Mean time per output token after the first, per request",
            buckets=TPOT_BUCKETS,
        )
        self.queue_wait = h(
            "shellac_queue_wait_seconds",
            "Time from request submit to the start of its prefill",
            buckets=LATENCY_BUCKETS,
        )
        self.e2e = h(
            "shellac_e2e_seconds",
            "End-to-end request latency (submit to completion)",
            buckets=LATENCY_BUCKETS,
        )
        # The tenant label is "" for traffic that carried no tenant id
        # (matching Registry.get's empty-string default, so untenanted
        # deployments keep their exact-key lookups and dashboards).
        self.requests = c(
            "shellac_requests_total",
            "Requests settled, by outcome (ok|shed|cancelled|error|"
            "fault) and tenant (empty for untenanted traffic)",
            labels=("outcome", "tenant"),
        )
        self.sheds = c(
            "shellac_requests_shed_total",
            "Requests shed on an expired deadline before prefill",
        )
        self.rejects = c(
            "shellac_admission_rejects_total",
            "Submissions refused at admission, by reason "
            "(overloaded|recovering|draining|throttled) and tenant "
            "(empty for untenanted traffic)",
            labels=("reason", "tenant"),
        )
        self.restarts = c(
            "shellac_supervisor_restarts_total",
            "Engine generations rebuilt by the serving supervisor",
        )
        self.generation = g(
            "shellac_engine_generation",
            "Current engine generation (bumps on supervisor rebuild)",
        )
        self.draining = g(
            "shellac_draining",
            "1 while a graceful drain is in progress (admission "
            "refused, in-flight requests completing), else 0",
        )
        self.uptime = g(
            "shellac_uptime_seconds", "Seconds since the server started"
        )
        self.pending = g(
            "shellac_pending_requests", "Requests currently pending"
        )
        self.constraint_compile = h(
            "shellac_constraint_compile_seconds",
            "Schema/regex -> token-DFA compile latency (paid on "
            "constraint-cache misses only)",
            buckets=LATENCY_BUCKETS,
        )
        self.constraint_cache = c(
            "shellac_constraint_cache_total",
            "Constraint DFA cache lookups, by result (hit|miss)",
            labels=("result",),
        )
        self.cache_backend_info = g(
            "shellac_engine_cache_backend_info",
            "Info gauge: always 1, labeled with the engine's active "
            "KV-cache storage backend (registry name, e.g. dense, "
            "paged-int8) so dashboards can group replicas by storage "
            "policy",
            labels=("backend",),
        )
        self.tool_requests = c(
            "shellac_tool_requests_total",
            "Tool-enabled requests by resolution: call (tool_calls "
            "parsed), text (auto chose free text), truncated (tool "
            "branch cut by the token budget)",
            labels=("outcome",),
        )
        self.role_info = g(
            "shellac_engine_role_info",
            "Info gauge: always 1, labeled with this replica's serving "
            "role (prefill | decode | monolith) — the tier's "
            "disaggregated pair scheduler groups replicas by it",
            labels=("role",),
        )
        self.migrations = c(
            "shellac_migrations_total",
            "KV-migration legs by outcome. Replica-side: export / "
            "export_failed (serialize+push from a prefill replica), "
            "import / import_failed (adoption on a decode replica). "
            "Tier-side: ok (full disaggregated path served), "
            "fallback_* (served monolithically: no_pair | cost | "
            "feature | failed)",
            labels=("outcome",),
        )
        self.kv_transfer_seconds = h(
            "shellac_kv_transfer_seconds",
            "Wall time of one KV-migration push (serialize excluded: "
            "POST /kv/import dispatch to the decode replica's ack)",
            buckets=LATENCY_BUCKETS,
        )
        self.kv_transfer_bytes = h(
            "shellac_kv_transfer_bytes",
            "Serialized size of one KV-migration blob (header + "
            "chunked device-block payload)",
            buckets=log_buckets(1e3, 1e9, per_decade=2),
        )
        self.fabric_seeded = c(
            "shellac_fabric_seeded_blocks_total",
            "Prefix-cache blocks registered from fleet seed pushes "
            "(POST /kv/seed) — KV this replica now serves without "
            "ever having prefilled it",
        )
        self.fabric_seed_rejects = c(
            "shellac_fabric_seed_rejects_total",
            "Seed blobs refused at the door with the registry "
            "untouched, by reason (corrupt|mismatch|exhausted|fault)",
            labels=("reason",),
        )
        self.fabric_parked = c(
            "shellac_fabric_parked_total",
            "Frozen sessions exported to the KV park spool",
        )
        self.fabric_resumed = c(
            "shellac_fabric_resumed_total",
            "Park-spool resume attempts, by outcome (ok: imported and "
            "adopted; missing: unknown park id; torn: blob failed "
            "integrity read-back and was quarantined)",
            labels=("outcome",),
        )
        self.fabric_park_bytes = g(
            "shellac_fabric_park_bytes",
            "Bytes currently resident in this replica's KV park spool "
            "(size-capped; LRU-trimmed on write)",
        )
        # Per-tenant QoS series. Unlike the widened request/reject
        # counters above, these key the RESOLVED tenant ("anonymous"
        # when no id rode the request), so a tenants dashboard always
        # accounts for every token served.
        self.tenant_tokens = c(
            "shellac_tenant_tokens_admitted_total",
            "Tokens admitted past per-tenant quota (prompt + budgeted "
            "max_new, the same cost the token bucket charges), by "
            "resolved tenant",
            labels=("tenant",),
        )
        self.tenant_throttles = c(
            "shellac_tenant_throttles_total",
            "Per-tenant quota rejections (HTTP 429 + Retry-After), by "
            "tenant and exhausted budget (rate|concurrency)",
            labels=("tenant", "reason"),
        )
        self.tenant_preemptions = c(
            "shellac_tenant_preemptions_total",
            "Requests frozen mid-decode and parked so a higher-"
            "priority class could take the slot, by victim tenant",
            labels=("tenant",),
        )
        self.tenant_parked_bytes = g(
            "shellac_tenant_parked_bytes",
            "Bytes of preempted KV currently parked awaiting resume, "
            "by victim tenant (measured blob size, the preemption "
            "cost model's input)",
            labels=("tenant",),
        )
        self.tenant_sheds = c(
            "shellac_tenant_sheds_total",
            "Deadline sheds by resolved tenant (the unlabeled "
            "shellac_requests_shed_total keeps the fleet total)",
            labels=("tenant",),
        )
        self._engine_stats: Dict[str, object] = {}

    def trace(self, trace_id: Optional[str] = None,
              recorder=None, tenant: Optional[str] = None
              ) -> "RequestTrace":
        """A span for one request. `trace_id` links the span to the
        distributed trace (the tier/header id); `recorder` is the
        server's FlightRecorder — when both are set the span's event
        methods also deposit timeline events, and the latency
        histograms retain the id as a per-bucket exemplar. `tenant`
        (None for untenanted traffic) labels the settlement counters."""
        return RequestTrace(self, trace_id=trace_id, recorder=recorder,
                            tenant=tenant)

    def engine_stat(self, key: str):
        """Scrape-time gauge mirroring one engine `stats` counter as
        `shellac_engine_<key>` (keys are code-side identifiers, so the
        name is exposition-safe by construction)."""
        gauge = self._engine_stats.get(key)
        if gauge is None:
            gauge = self.registry.gauge(
                f"shellac_engine_{key}", f"Engine stats counter {key!r}"
            )
            self._engine_stats[key] = gauge
        return gauge


class RequestTrace:
    """Span recorder for ONE request. Event methods are idempotent (the
    first call wins) and `finish`/`shed`/`abort` settle the trace
    exactly once — late duplicate settlement from racing sweeps (close
    vs a final delivery) is ignored, mirroring the server's own
    pop-arbitrated settlement."""

    __slots__ = ("_m", "t_submit", "t_prefill", "t_first", "t_done",
                 "n_tokens", "outcome", "trace_id", "recorder", "tenant")

    def __init__(self, metrics: ServeMetrics,
                 trace_id: Optional[str] = None, recorder=None,
                 tenant: Optional[str] = None):
        self._m = metrics
        # Distributed-trace identity (obs.events.new_trace_id shape) and
        # the flight recorder the span's events feed. Both optional:
        # a bare trace()/RequestTrace() records spans only, exactly the
        # pre-tracing behavior.
        self.trace_id = trace_id
        self.recorder = recorder
        # Tenant id the request carried (None when untenanted): labels
        # the settlement counters and surfaces in /debug/requests.
        self.tenant = tenant
        self.t_submit = time.monotonic()
        self.t_prefill: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.n_tokens = 0
        self.outcome: Optional[str] = None

    def record(self, event: str, **fields) -> None:
        """Deposit one flight-recorder event under this span's trace
        id. A no-op without a recorder, so engine/server call sites
        need no branching."""
        if self.recorder is not None:
            self.recorder.record(self.trace_id, event, **fields)

    # ---- pipeline events (called by the engine-owning thread) --------

    def prefill_start(self) -> None:
        """Queue wait ends: the scheduler popped this request into a
        slot and is about to prefill it."""
        if self.t_prefill is not None:
            return
        self.t_prefill = time.monotonic()
        wait = self.t_prefill - self.t_submit
        self._m.queue_wait.observe(wait, exemplar=self.trace_id)
        self.record("prefill", src="engine", queue_wait_s=round(wait, 6))

    def first_token(self) -> None:
        """The first generated token exists host-side (prefill sampled
        it): the TTFT point."""
        if self.t_first is not None:
            return
        self.t_first = time.monotonic()
        ttft = self.t_first - self.t_submit
        self._m.ttft.observe(ttft, exemplar=self.trace_id)
        self.record("first-token", src="engine", ttft_s=round(ttft, 6))

    # ---- settlement --------------------------------------------------

    def _settle(self, outcome: str) -> bool:
        if self.outcome is not None:
            return False
        self.outcome = outcome
        self.t_done = time.monotonic()
        self._m.requests.labels(outcome=outcome,
                                tenant=self.tenant or "").inc()
        return True

    def finish(self, n_tokens: int) -> None:
        """Completed normally with `n_tokens` generated tokens."""
        if not self._settle("ok"):
            return
        self.n_tokens = int(n_tokens)
        e2e = self.t_done - self.t_submit
        self._m.e2e.observe(e2e, exemplar=self.trace_id)
        if self.t_first is not None and self.n_tokens > 1:
            self._m.tpot.observe(
                (self.t_done - self.t_first) / (self.n_tokens - 1),
                exemplar=self.trace_id,
            )
        self.record("finish", src="server", n_tokens=self.n_tokens,
                    e2e_s=round(e2e, 6))

    def shed(self) -> None:
        """Deadline expired before prefill; the scheduler dropped it."""
        if self._settle("shed"):
            self._m.sheds.inc()
            if self.tenant:
                self._m.tenant_sheds.labels(tenant=self.tenant).inc()
            self.record("shed", src="server")

    def abort(self, outcome: str = "cancelled") -> None:
        """Any non-ok, non-shed settlement: cancelled | error | fault."""
        if self._settle(outcome):
            self.record(outcome, src="server")


class TierMetrics:
    """The router-tier instruments over one registry.

    Per-replica series are labeled by the replica's base URL so a
    scrape shows exactly where traffic went, what was retried away
    from whom, and who is ejected — the counters the tier chaos tests
    assert against. Written only from router threads (health poller +
    request handlers); replicas keep their own ServeMetrics."""

    def __init__(self, registry: Registry):
        self.registry = registry
        h, c, g = registry.histogram, registry.counter, registry.gauge
        self.routed = c(
            "shellac_tier_routed_total",
            "Request attempts forwarded, by replica and routing reason "
            "(affinity|least_loaded|directory|retry|disagg_prefill|"
            "disagg_decode)",
            labels=("replica", "reason"),
        )
        self.outcomes = c(
            "shellac_tier_requests_total",
            "Tier-level request settlements, by outcome "
            "(ok|failed|rejected|deadline)",
            labels=("outcome",),
        )
        self.retries = c(
            "shellac_tier_retries_total",
            "Retryable attempt failures, by replica the attempt hit "
            "and the failure class (connect|timeout|status_503|"
            "status_429|status_500|stream_pre_byte)",
            labels=("replica", "kind"),
        )
        self.ejections = c(
            "shellac_tier_ejections_total",
            "Circuit-breaker ejections, by replica",
            labels=("replica",),
        )
        self.readmissions = c(
            "shellac_tier_readmissions_total",
            "Half-open probes that readmitted a replica",
            labels=("replica",),
        )
        self.drains = c(
            "shellac_tier_drains_observed_total",
            "Health polls that found a replica newly draining",
            labels=("replica",),
        )
        self.respawns = c(
            "shellac_tier_respawns_total",
            "Dead replicas replaced through the replica factory",
        )
        self.stream_severed = c(
            "shellac_tier_stream_severed_total",
            "Streams lost mid-relay AFTER bytes reached the client "
            "(non-retryable by contract; reported in-band), by replica",
            labels=("replica",),
        )
        self.healthy = g(
            "shellac_tier_replicas_healthy",
            "Replicas currently routable (healthy, not ejected or "
            "draining)",
        )
        self.replica_state = g(
            "shellac_tier_replica_state",
            "Per-replica routability: 1 routable, 0 not (ejected, "
            "draining, or dead)",
            labels=("replica",),
        )
        self.attempt_latency = h(
            "shellac_tier_attempt_seconds",
            "Wall time of one forwarded attempt (connect to full "
            "response, successful or not)",
            buckets=LATENCY_BUCKETS,
        )
        self.e2e = h(
            "shellac_tier_e2e_seconds",
            "End-to-end tier latency (admission to final byte, "
            "retries included)",
            buckets=LATENCY_BUCKETS,
        )
        self.backoff = h(
            "shellac_tier_backoff_seconds",
            "Backoff slept between retry attempts (after jitter and "
            "deadline capping)",
            buckets=LATENCY_BUCKETS,
        )
        # Same family the replicas register (idempotent): tier-side
        # outcomes (ok / fallback_*) and replica-side leg outcomes
        # (export / import / *_failed) share one catalog entry.
        self.migrations = c(
            "shellac_migrations_total",
            "KV-migration legs by outcome. Replica-side: export / "
            "export_failed (serialize+push from a prefill replica), "
            "import / import_failed (adoption on a decode replica). "
            "Tier-side: ok (full disaggregated path served), "
            "fallback_* (served monolithically: no_pair | cost | "
            "feature | failed)",
            labels=("outcome",),
        )
        self.fabric_directory_chains = g(
            "shellac_fabric_directory_chains",
            "Distinct prefix-cache blocks the tier's directory "
            "currently knows across all routable replicas",
        )
        self.fabric_directory_hits = c(
            "shellac_fabric_directory_hits_total",
            "Routing decisions won by directory-measured chain "
            "overlap (the replica was chosen because the directory "
            "says it already holds the prompt's prefix KV)",
        )
        self.fabric_pushes = c(
            "shellac_fabric_pushes_total",
            "Hot-prefix replication pushes planned by the tier, by "
            "outcome (ok|failed|skipped_cost)",
            labels=("outcome",),
        )
        # Tier-side tenant admission shares the replica family name
        # (registration is idempotent) so one catalog entry covers
        # both enforcement points.
        self.tenant_throttles = c(
            "shellac_tenant_throttles_total",
            "Per-tenant quota rejections (HTTP 429 + Retry-After), by "
            "tenant and exhausted budget (rate|concurrency)",
            labels=("tenant", "reason"),
        )
        self.autoscale_actions = c(
            "shellac_autoscale_actions_total",
            "Autoscaler decisions actually executed, by action "
            "(scale_out: replica spawned via the factory; scale_down: "
            "/drain posted to the least-loaded replica)",
            labels=("action",),
        )
        self.autoscale_replicas = g(
            "shellac_autoscale_replicas",
            "Replica count the autoscaler last observed (its min/max "
            "envelope input; present only when autoscaling is on)",
        )


class EngineMetrics:
    """The engine-layer instruments: batch occupancy, prefill vs decode
    section durations, and cache-utilization gauges. All writes happen
    from the engine-owning thread, once per engine STEP (host code,
    after the step's own host sync) — never per token and never inside
    a jitted program."""

    def __init__(self, registry: Registry):
        self.registry = registry
        h, g = registry.histogram, registry.gauge
        self.prefill_seconds = h(
            "shellac_prefill_seconds",
            "Wall time of one engine step's prefill section (all "
            "prefill/chunk programs it ran)",
            buckets=LATENCY_BUCKETS,
        )
        self.decode_window_seconds = h(
            "shellac_decode_window_seconds",
            "Wall time of one decode window, dispatch to results-on-"
            "host (under overlapped dispatch this spans the host work "
            "interleaved with the window — the overlapped reality)",
            buckets=LATENCY_BUCKETS,
        )
        self.host_overhead = h(
            "shellac_decode_host_overhead_seconds",
            "Per engine step that synced a decode window: step wall "
            "time minus time blocked awaiting window results — the "
            "host-side share of the tick (scheduling, settlement, "
            "prefill dispatch). A replica whose overhead rivals its "
            "window time is host-bound, not device-bound",
            buckets=LATENCY_BUCKETS,
        )
        self.step_phase = h(
            "shellac_step_phase_seconds",
            "Per engine step: wall time attributed to one phase of "
            "the tick (admission | prefill_dispatch | prefill_settle "
            "| decode_sync | settle | host_bookkeeping — see "
            "obs.STEP_PHASES). "
            "Observed once per phase per non-idle step, so the "
            "per-phase _sum series divide the step loop's wall time "
            "exactly and 'prefill stalls decode windows' is a "
            "measurement, not a claim",
            labels=("phase",),
            buckets=TPOT_BUCKETS,
        )
        self.occupancy = h(
            "shellac_batch_occupancy",
            "Active slots / n_slots at each decode window",
            buckets=OCCUPANCY_BUCKETS,
        )
        self.slots_busy = g(
            "shellac_slots_busy", "Slots currently holding a request"
        )
        self.queue_depth = g(
            "shellac_engine_queue_depth",
            "Requests admitted but not yet in a slot",
        )
        self.kv_util = g(
            "shellac_kv_utilization",
            "Live KV tokens / capacity (dense) or pool blocks in use / "
            "pool size (paged)",
        )
        self.prefix_blocks = g(
            "shellac_prefix_cache_blocks",
            "Blocks currently registered in the prefix cache (paged "
            "engines with prefix_cache=True)",
        )
