"""Declarative SLOs evaluated by multi-window burn rate.

An SLO here is one line of operator intent — `ttft_p99<500ms@99.9` —
turned into the Google SRE-workbook alerting shape: the SLI is a
good-event fraction (requests whose TTFT beat 500ms), the objective
is the target fraction (99.9%), and alerting is on the BURN RATE —
how fast the error budget (the allowed 0.1% of bad events) is being
spent — measured over paired windows so that neither a 30-second
blip (fast window alone) nor a slow leak (long window alone) pages
spuriously:

  page    — fast pair:  burn(5m) and burn(1h) both >= 14.4
            (a rate that exhausts a 30-day budget in ~2 days)
  warning — slow pair:  burn(6h) and burn(3d) both >= 1.0
            (budget being consumed faster than it accrues)

Spec grammar (`SLOSpec.parse`):

    <sli>[_p<NN>] <op> <value>[ms|us|s] @ <objective-percent>
    availability @ <objective-percent>

`sli` ∈ {ttft, tpot, e2e, queue_wait, availability}. The optional
`_pNN` tag is operator-facing display — "the p99 target is 500ms" and
"at most (100-objective)% of requests exceed 500ms" are the same
statement, and the burn-rate math is event-based either way (the
workbook's form). `availability` counts request outcomes instead of
latencies, so it takes no threshold.

`SLOEngine` is source-agnostic: each `tick()` hands it cumulative
`(good, total)` event counts per SLO (the tier derives them from the
federated fleet histograms and its own outcome counters) and it keeps
the time-windowed snapshots needed to answer "what was the count at
now-W" — a fine ring (per-tick, bounded to the 1h window) plus a
coarse ring (one point a minute, bounded to 3d), so memory stays a
few thousand tuples however long the tier runs. Windows the process
has not lived through yet fall back to the oldest snapshot (partial
window, reported as such) — a young tier alerts on what it has seen,
not never.

Alert transitions land in the flight recorder (`slo-transition`
events) with a trace-id exemplar of a violating request when the
caller can supply one — the PR 10 path from "the pager fired" to one
concrete request timeline.
"""

from __future__ import annotations

import re
import threading
import time
from bisect import bisect_right
from collections import deque
from dataclasses import dataclass
from typing import (
    Callable,
    Deque,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

#: Evaluation windows, seconds: the workbook's fast pair + slow pair.
FAST_WINDOWS: Tuple[Tuple[str, float], ...] = (("5m", 300.0),
                                               ("1h", 3600.0))
SLOW_WINDOWS: Tuple[Tuple[str, float], ...] = (("6h", 21600.0),
                                               ("3d", 259200.0))
ALL_WINDOWS: Tuple[Tuple[str, float], ...] = FAST_WINDOWS + SLOW_WINDOWS

#: Default burn thresholds (SRE workbook: 14.4 = a 30-day budget gone
#: in 2 days; 1.0 = spending exactly as fast as the budget accrues).
PAGE_BURN = 14.4
WARN_BURN = 1.0

STATES = ("ok", "warning", "page")

_SPEC_RE = re.compile(
    r"^\s*([a-z][a-z0-9_]*?)(?:_p(\d+(?:\.\d+)?))?"
    r"(?:\s*(<=|<)\s*(\d+(?:\.\d+)?)\s*(ms|us|s)?)?"
    r"\s*@\s*(\d+(?:\.\d+)?)\s*$"
)

_UNIT_S = {"s": 1.0, "ms": 1e-3, "us": 1e-6, None: 1.0}

#: SLIs with a latency threshold (histogram-backed good counts).
LATENCY_SLIS = ("ttft", "tpot", "e2e", "queue_wait")


@dataclass(frozen=True)
class SLOSpec:
    """One parsed objective. `name` is the verbatim spec string — the
    stable label value every shellac_slo_* series carries."""

    name: str
    sli: str                       # ttft|tpot|e2e|queue_wait|availability
    threshold_s: Optional[float]   # None for availability
    objective: float               # fraction in (0, 1), e.g. 0.999
    percentile_tag: Optional[str]  # display-only "_pNN" tag, if given

    @property
    def budget(self) -> float:
        """Allowed bad-event fraction (1 - objective)."""
        return 1.0 - self.objective

    @classmethod
    def parse(cls, spec: str) -> "SLOSpec":
        m = _SPEC_RE.match(spec)
        if not m:
            raise ValueError(
                f"bad SLO spec {spec!r}: expected "
                "'<sli>[_pNN]<threshold><ms|s>@<objective>' "
                "(e.g. 'ttft_p99<500ms@99.9') or 'availability@99.9'"
            )
        sli, ptag, _op, value, unit, obj = m.groups()
        if sli == "availability":
            if value is not None or ptag is not None:
                raise ValueError(
                    f"bad SLO spec {spec!r}: availability takes no "
                    "threshold or percentile tag"
                )
            threshold = None
        elif sli in LATENCY_SLIS:
            if value is None:
                raise ValueError(
                    f"bad SLO spec {spec!r}: latency SLI {sli!r} "
                    "needs a threshold (e.g. <500ms)"
                )
            threshold = float(value) * _UNIT_S[unit]
        else:
            raise ValueError(
                f"bad SLO spec {spec!r}: unknown SLI {sli!r} "
                f"(known: {', '.join(LATENCY_SLIS)}, availability)"
            )
        objective = float(obj) / 100.0
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"bad SLO spec {spec!r}: objective must be in (0, 100) "
                "percent, exclusive"
            )
        return cls(name=spec.strip(), sli=sli, threshold_s=threshold,
                   objective=objective,
                   percentile_tag=f"p{ptag}" if ptag else None)


def parse_slo_specs(specs: Sequence[str]) -> List[SLOSpec]:
    out = [SLOSpec.parse(s) for s in specs]
    names = [s.name for s in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate SLO specs: {names}")
    return out


class _Ring:
    """Timestamped (t, good, total) snapshots with bounded memory:
    drop-from-the-left once the oldest point is older than `horizon`
    AND a second point also covers the horizon (the newest point at
    or before now-W must survive)."""

    __slots__ = ("horizon", "min_gap", "_pts", "_last_t")

    def __init__(self, horizon: float, min_gap: float = 0.0):
        self.horizon = horizon
        self.min_gap = min_gap
        self._pts: Deque[Tuple[float, float, float]] = deque()
        self._last_t: Optional[float] = None

    def append(self, t: float, good: float, total: float) -> None:
        if self._last_t is not None and t - self._last_t < self.min_gap:
            return
        self._last_t = t
        self._pts.append((t, good, total))
        cutoff = t - self.horizon
        while len(self._pts) >= 2 and self._pts[1][0] <= cutoff:
            self._pts.popleft()

    def at_or_before(self, t: float) -> Optional[Tuple[float, float, float]]:
        """Newest snapshot with timestamp <= t, else None."""
        pts = self._pts
        if not pts or pts[0][0] > t:
            return None
        idx = bisect_right(pts, (t, float("inf"), float("inf"))) - 1
        return pts[idx]

    def oldest(self) -> Optional[Tuple[float, float, float]]:
        return self._pts[0] if self._pts else None


class _Track:
    """Per-SLO mutable state: snapshot rings + alert state."""

    __slots__ = ("spec", "fine", "coarse", "state", "last_transition",
                 "last_counts")

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        # Fine ring answers the fast windows; coarse (1/min) answers
        # the slow ones without holding 3 days of per-tick points.
        self.fine = _Ring(horizon=FAST_WINDOWS[-1][1] + 60.0)
        self.coarse = _Ring(horizon=SLOW_WINDOWS[-1][1] + 3600.0,
                            min_gap=60.0)
        self.state = "ok"
        self.last_transition: Optional[Dict[str, object]] = None
        self.last_counts: Tuple[float, float] = (0.0, 0.0)

    def lookup(self, t: float) -> Optional[Tuple[float, float, float]]:
        hit = self.fine.at_or_before(t)
        if hit is not None:
            return hit
        return self.coarse.at_or_before(t)

    def oldest(self) -> Optional[Tuple[float, float, float]]:
        old_c = self.coarse.oldest()
        old_f = self.fine.oldest()
        if old_c is None:
            return old_f
        if old_f is None or old_c[0] <= old_f[0]:
            return old_c
        return old_f


class SLOEngine:
    """Evaluate a set of `SLOSpec`s from cumulative good/total counts.

    `tick(counts)` is called on the tier's poll cadence with
    `{spec.name: (good, total)}`; the engine snapshots, computes the
    four window burn rates, runs the ok→warning→page state machine,
    updates the shellac_slo_* gauges, and records transitions in the
    flight recorder (with a violating-request exemplar from
    `exemplar_fn` when one exists). `status()` is the `/slo` JSON.

    Counter resets (a replica restart shrinking the federated
    cumulative counts) clamp window deltas at zero — a reset must
    read as "no data", never as negative errors.
    """

    def __init__(self, specs: Sequence[SLOSpec], *,
                 registry=None, recorder=None,
                 exemplar_fn: Optional[
                     Callable[[SLOSpec], Optional[str]]] = None,
                 on_transition: Optional[
                     Callable[[SLOSpec, str, str,
                               Dict[str, object]], None]] = None,
                 page_burn: float = PAGE_BURN,
                 warn_burn: float = WARN_BURN):
        self.specs = list(specs)
        self.page_burn = float(page_burn)
        self.warn_burn = float(warn_burn)
        self._recorder = recorder
        self._exemplar_fn = exemplar_fn
        # Transition hook: called AFTER the state/gauges/recorder are
        # updated, with (spec, old, new, last_transition). The tier
        # hangs the incident manager off this seam — a `page` landing
        # auto-captures an evidence bundle. Exceptions are swallowed:
        # a broken hook must never break alerting.
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._tracks = {s.name: _Track(s) for s in self.specs}
        self._g_burn = self._g_state = self._g_good = None
        self._g_objective = self._c_transitions = None
        if registry is not None and self.specs:
            self._g_burn = registry.gauge(
                "shellac_slo_burn_rate",
                "Error-budget burn rate per SLO and window (1.0 = "
                "spending exactly as fast as the budget accrues; the "
                "page pair trips at 14.4)",
                labels=("slo", "window"),
            )
            self._g_state = registry.gauge(
                "shellac_slo_state",
                "Alert state per SLO: 0 ok, 1 warning, 2 page",
                labels=("slo",),
            )
            self._g_good = registry.gauge(
                "shellac_slo_good_fraction",
                "Good-event fraction over the fast (5m) window",
                labels=("slo",),
            )
            self._g_objective = registry.gauge(
                "shellac_slo_objective",
                "The SLO's objective as a fraction (info gauge)",
                labels=("slo",),
            )
            self._c_transitions = registry.counter(
                "shellac_slo_transitions_total",
                "Alert state transitions per SLO, by destination state",
                labels=("slo", "to"),
            )
            for s in self.specs:
                self._g_objective.labels(slo=s.name).set(s.objective)
                self._g_state.labels(slo=s.name).set(0)

    # ---- evaluation --------------------------------------------------

    def _window_burn(self, track: _Track, now: float, window_s: float,
                     good: float, total: float
                     ) -> Tuple[float, float, float]:
        """(burn rate, bad fraction, actual window seconds) for one
        window ending now. Falls back to the oldest snapshot when the
        engine has not lived `window_s` yet."""
        anchor = track.lookup(now - window_s)
        if anchor is None:
            anchor = track.oldest()
        if anchor is None:
            return 0.0, 0.0, 0.0
        t0, g0, n0 = anchor
        d_total = total - n0
        d_good = good - g0
        if d_total <= 0 or d_good < 0:
            # No traffic in the window, or a counter reset mid-window.
            return 0.0, 0.0, now - t0
        d_bad = max(0.0, d_total - d_good)
        bad_frac = min(1.0, d_bad / d_total)
        burn = bad_frac / track.spec.budget
        return burn, bad_frac, now - t0

    def tick(self, counts: Dict[str, Tuple[float, float]],
             now: Optional[float] = None) -> None:
        """One evaluation pass. `counts[name] = (good, total)`,
        cumulative since replica/tier start (the engine differences
        them per window)."""
        now = time.monotonic() if now is None else now
        fired: List[Tuple[_Track, str, str, Dict[str, float]]] = []
        with self._lock:
            for name, track in self._tracks.items():
                good, total = counts.get(name, track.last_counts)
                track.last_counts = (float(good), float(total))
                track.fine.append(now, float(good), float(total))
                track.coarse.append(now, float(good), float(total))
                burns: Dict[str, float] = {}
                fracs: Dict[str, float] = {}
                for label, w in ALL_WINDOWS:
                    b, f, _ = self._window_burn(track, now, w,
                                                float(good), float(total))
                    burns[label] = b
                    fracs[label] = f
                    if self._g_burn is not None:
                        self._g_burn.labels(slo=name, window=label).set(b)
                if self._g_good is not None:
                    self._g_good.labels(slo=name).set(
                        1.0 - fracs[FAST_WINDOWS[0][0]]
                    )
                new_state = self._classify(burns)
                if new_state != track.state:
                    old = track.state
                    self._transition(track, new_state, burns)
                    fired.append((track, old, new_state, burns))
        # Everything that leaves the engine fires AFTER the lock
        # drops: the exemplar callback walks histogram and recorder
        # internals (their own locks), and a transition hook that
        # reads back through status()/state() (the tier's incident
        # trigger does, via its bundle sections) must not deadlock
        # the tick.
        for track, old, new_state, burns in fired:
            exemplar = None
            if new_state != "ok" and self._exemplar_fn is not None:
                try:
                    exemplar = self._exemplar_fn(track.spec)
                except Exception:  # noqa: BLE001 — an exemplar lookup
                    exemplar = None  # must never break alerting
            with self._lock:
                track.last_transition["exemplar"] = exemplar
                transition = dict(track.last_transition)
            if self._recorder is not None:
                # The transition event is system-scoped (trace=None):
                # the EXEMPLAR field carries the violating request's
                # trace id, which /debug/request/<id> resolves to its
                # timeline.
                self._recorder.record(
                    None, "slo-transition", src="tier",
                    slo=track.spec.name, **{"from": old}, to=new_state,
                    burn={k: round(v, 3) for k, v in burns.items()},
                    exemplar=exemplar,
                )
            if self._on_transition is not None:
                try:
                    self._on_transition(track.spec, old, new_state,
                                        transition)
                except Exception:  # noqa: BLE001 — hooks must never
                    pass           # break alerting

    def _classify(self, burns: Dict[str, float]) -> str:
        fast = [burns[label] for label, _ in FAST_WINDOWS]
        slow = [burns[label] for label, _ in SLOW_WINDOWS]
        if all(b >= self.page_burn for b in fast):
            return "page"
        if all(b >= self.warn_burn for b in slow):
            return "warning"
        return "ok"

    def _transition(self, track: _Track, new_state: str,
                    burns: Dict[str, float]) -> None:
        """Commit a state change (caller holds the engine lock).

        Only lock-safe work happens here: the exemplar lookup, the
        recorder event, and the user hook are all deferred to `tick`'s
        post-lock loop, because each re-enters code with locks of its
        own. `tick` patches the exemplar into `last_transition` once
        it resolves.
        """
        old = track.state
        track.state = new_state
        track.last_transition = {
            "at": time.time(),
            "from": old,
            "to": new_state,
            "burn": {k: round(v, 3) for k, v in burns.items()},
            "exemplar": None,
        }
        if self._g_state is not None:
            self._g_state.labels(slo=track.spec.name).set(
                STATES.index(new_state)
            )
        if self._c_transitions is not None:
            self._c_transitions.labels(slo=track.spec.name,
                                       to=new_state).inc()

    # ---- reads -------------------------------------------------------

    def state(self, name: str) -> str:
        with self._lock:
            return self._tracks[name].state

    def status(self, now: Optional[float] = None) -> List[Dict[str, object]]:
        """The `/slo` JSON payload: one entry per SLO."""
        now = time.monotonic() if now is None else now
        out: List[Dict[str, object]] = []
        with self._lock:
            for name, track in self._tracks.items():
                good, total = track.last_counts
                windows: Dict[str, Dict[str, float]] = {}
                for label, w in ALL_WINDOWS:
                    b, f, actual = self._window_burn(track, now, w,
                                                     good, total)
                    windows[label] = {
                        "burn_rate": round(b, 3),
                        "bad_fraction": round(f, 6),
                        "window_s": w,
                        "covered_s": round(actual, 1),
                    }
                spec = track.spec
                out.append({
                    "slo": name,
                    "sli": spec.sli,
                    "threshold_s": spec.threshold_s,
                    "objective": spec.objective,
                    "state": track.state,
                    "good_events": good,
                    "total_events": total,
                    "good_fraction": (
                        round(good / total, 6) if total else None
                    ),
                    "windows": windows,
                    "page_burn_threshold": self.page_burn,
                    "warn_burn_threshold": self.warn_burn,
                    "last_transition": track.last_transition,
                })
        return out
