"""Device-trace analysis: `python -m shellac_tpu trace-report`.

`POST /debug/profile` (PR 10) captures a `jax.profiler` trace of the
live engine, but nothing in the repo could READ one — fusion and
step-time questions were still answered by guessing. This module
parses the profiler's Chrome-trace event stream (the
`*.trace.json.gz` every capture contains, host and TPU alike) into:

  op-level time attribution — every complete ('X') event on a device
    process (a `process_name` containing "/device:", or — the CPU
    backend's shape — any event whose args carry an `hlo_op`/
    `hlo_module`) aggregated per op name: count, total time, share.

  phase alignment — each device op is classified against the
    `shellac_step_phase_seconds` phases by the HLO module / op name
    it belongs to (the engine's jitted programs have recognizable
    names: prefill/chunk programs -> `prefill_dispatch`, decode
    window/beam programs -> `decode_sync`). `admission`,
    `prefill_settle`, `settle`, and `host_bookkeeping` are host-side
    phases with no device ops of their own (the prefill COMPUTE the
    settle waits on is attributed to `prefill_dispatch`, where its
    programs run); their device share is structurally zero and the
    live histogram stays the authority for them — the report says
    where the DEVICE half of each phase goes, which is exactly the
    half the histogram cannot see.

  fusion counts — events and distinct ops named `fusion*` (XLA's
    fused computations): how much of the device time runs fused, and
    how many distinct fusions the compiler emitted. A layout change
    that breaks a fusion apart shows up here as more distinct ops and
    less fused time — the regression class "Operator Fusion in XLA"
    (PAPERS.md) describes.

`diff(before, after)` compares two reports and FLAGS regressions —
per-op slowdowns past a threshold, expensive new ops, total device
time growth, fusion breakup — so two committed captures answer "did
this change regress the step" mechanically (the trace-reading half
ROADMAP item 3's TPU re-measure campaign needs). The CLI exits
non-zero when the diff flags anything, so the comparison gates.

Dependency-free (stdlib only): reading a capture must work on any
box, not just an accelerator host.
"""

from __future__ import annotations

import gzip
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from shellac_tpu.obs.trace import STEP_PHASES

#: Module/op name -> step phase (first match wins; matched against
#: the HLO module name first, then the op/event name). The catalog
#: mirrors the engine's jitted-program names in
#: inference/batching.py: `_prefill_impl` and the chunked-prefill
#: programs carry "prefill"/"chunk", the decode window programs carry
#: "decode", beam search carries "beam".
PHASE_RULES: Tuple[Tuple[str, str], ...] = (
    (r"prefill|chunk", "prefill_dispatch"),
    # NOT "window": XLA's reduce-window pooling ops would
    # false-positive into the decode phase.
    (r"decode|beam", "decode_sync"),
)
_PHASE_RES = tuple((re.compile(p, re.I), phase) for p, phase in PHASE_RULES)

#: XLA fusion op names: `fusion`, `fusion.123`, `%fusion.4`, plus the
#:  kind-tagged `loop_fusion`/`input_fusion` variants.
_FUSION_RE = re.compile(r"^%?(?:[a-z]+_)?fusion(?:[._]\d+)?$", re.I)

#: Op-name normalization: strip the leading '%' and any SSA suffix so
#: `%add.12` and `add.7` aggregate as one op family.
_OP_NORM_RE = re.compile(r"^%?(.*?)(?:\.\d+)?$")


def _norm_op(name: str) -> str:
    m = _OP_NORM_RE.match(name)
    return m.group(1) if m and m.group(1) else name


def classify_phase(module: Optional[str], name: str) -> Optional[str]:
    """Phase for one device op, or None (unattributed) when neither
    the module nor the op name matches the catalog."""
    for rx, phase in _PHASE_RES:
        if module and rx.search(module):
            return phase
        if rx.search(name):
            return phase
    return None


# ---- loading ---------------------------------------------------------


def find_trace_file(path: str) -> str:
    """Resolve a capture argument to one trace file. Accepts the
    `.trace.json.gz` (or plain .json) file itself, or a capture
    directory — the `trace_dir` a /debug/profile response names —
    searched recursively for the newest `*.trace.json(.gz)`."""
    if os.path.isfile(path):
        return path
    if os.path.isdir(path):
        hits: List[str] = []
        for root, _, files in os.walk(path):
            for f in files:
                if f.endswith((".trace.json.gz", ".trace.json")):
                    hits.append(os.path.join(root, f))
        if not hits:
            raise FileNotFoundError(
                f"no *.trace.json(.gz) under {path!r} — is this a "
                "jax.profiler capture directory?"
            )
        return max(hits, key=os.path.getmtime)
    raise FileNotFoundError(f"no such capture: {path!r}")


def load_trace(path: str) -> Dict[str, Any]:
    """The parsed Chrome-trace JSON object of one capture."""
    f = find_trace_file(path)
    opener = gzip.open if f.endswith(".gz") else open
    with opener(f, "rb") as fh:
        data = json.loads(fh.read().decode("utf-8", errors="replace"))
    if not isinstance(data, dict) or "traceEvents" not in data:
        raise ValueError(
            f"{f!r} is not a Chrome-trace capture (no traceEvents)"
        )
    data["_trace_file"] = f
    return data


# ---- analysis --------------------------------------------------------


def _process_names(events: Iterable[Dict[str, Any]]) -> Dict[Any, str]:
    out: Dict[Any, str] = {}
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and isinstance(e.get("args"), dict)):
            out[e.get("pid")] = str(e["args"].get("name", ""))
    return out


def _is_op_event(e: Dict[str, Any], device_pids) -> bool:
    if e.get("ph") != "X" or not e.get("name"):
        return False
    if e.get("pid") in device_pids:
        return True
    args = e.get("args")
    # CPU-backend captures put the op stream on the host process but
    # tag each op event with its HLO identity.
    return isinstance(args, dict) and (
        "hlo_op" in args or "hlo_module" in args
    )


def analyze(path: str, *, top: int = 20) -> Dict[str, Any]:
    """One capture -> the trace-report dict (the `--json` payload, the
    bundle's trace_report.json, and diff()'s input)."""
    data = load_trace(path)
    events = data.get("traceEvents") or []
    procs = _process_names(events)
    device_pids = {pid for pid, name in procs.items()
                   if "/device:" in name}
    ops: Dict[str, Dict[str, Any]] = {}
    modules: Dict[str, float] = {}
    # Phase attribution over the device ops (host-only phases report
    # zero device time by construction — see module docstring).
    # Accumulated PER EVENT: the same op name may run under a prefill
    # module in one event and a decode module in the next, and
    # distinct fusions (fusion.1, fusion.2) normalize to one op row
    # but must count as distinct fusions.
    phases: Dict[str, Dict[str, float]] = {
        p: {"device_us": 0.0, "ops": 0} for p in STEP_PHASES
    }
    unattributed: Dict[str, float] = {"device_us": 0.0, "ops": 0}
    fus_raw: set = set()
    fus_events = 0
    fus_us = 0.0
    total_us = 0.0
    n_events = 0
    for e in events:
        if not _is_op_event(e, device_pids):
            continue
        dur = float(e.get("dur") or 0.0)
        args = e.get("args") if isinstance(e.get("args"), dict) else {}
        raw = str(e["name"])
        module = str(args["hlo_module"]) if args.get("hlo_module") \
            else None
        op = _norm_op(str(args.get("hlo_op") or raw))
        n_events += 1
        total_us += dur
        if module:
            modules[module] = modules.get(module, 0.0) + dur
        ph = classify_phase(module, op)
        tgt = phases[ph] if ph else unattributed
        tgt["device_us"] += dur
        tgt["ops"] += 1
        if _FUSION_RE.match(raw) or _FUSION_RE.match(op):
            fus_raw.add(raw)
            fus_events += 1
            fus_us += dur
        row = ops.get(op)
        if row is None:
            row = ops[op] = {"name": op, "count": 0, "total_us": 0.0,
                             "phase": ph}
        row["count"] += 1
        row["total_us"] += dur
    fus_distinct = len(fus_raw)
    for p in phases.values():
        p["share"] = round(p["device_us"] / total_us, 4) if total_us else 0.0
        p["device_us"] = round(p["device_us"], 3)
    unattributed["share"] = (round(unattributed["device_us"] / total_us, 4)
                             if total_us else 0.0)
    unattributed["device_us"] = round(unattributed["device_us"], 3)
    ranked = sorted(ops.values(), key=lambda r: -r["total_us"])
    top_ops = [
        {
            "name": r["name"], "count": r["count"],
            "total_us": round(r["total_us"], 3),
            "avg_us": round(r["total_us"] / r["count"], 3),
            "share": (round(r["total_us"] / total_us, 4)
                      if total_us else 0.0),
            "phase": r["phase"],
        }
        for r in ranked[: max(0, int(top))]
    ]
    return {
        "capture": data.get("_trace_file"),
        "op_events": n_events,
        "distinct_ops": len(ops),
        "device_time_us": round(total_us, 3),
        "top_ops": top_ops,
        # The full per-op table rides along for diff(): same row shape
        # as top_ops, unranked callers can rank themselves.
        "ops": {r["name"]: {"count": r["count"],
                            "total_us": round(r["total_us"], 3),
                            "phase": r["phase"]}
                for r in ranked},
        "modules": {k: round(v, 3) for k, v in sorted(
            modules.items(), key=lambda kv: -kv[1])},
        "fusion": {
            "distinct": fus_distinct,
            "events": int(fus_events),
            "total_us": round(fus_us, 3),
            "share": round(fus_us / total_us, 4) if total_us else 0.0,
        },
        "phases": phases,
        "unattributed": unattributed,
    }


# ---- diff ------------------------------------------------------------


def diff(before: Dict[str, Any], after: Dict[str, Any], *,
         threshold: float = 0.15, min_us: float = 50.0,
         phase_shift_points: float = 0.15) -> Dict[str, Any]:
    """Compare two reports; flag regressions in `after` relative to
    `before`. A regression is flagged when it is BOTH relatively
    (`threshold`, default +15%) and absolutely (`min_us`) significant
    — a 3µs op doubling is noise, not a finding. `phase_shift_points`
    is a separate, ABSOLUTE knob (share points a phase's device share
    may grow): shares live on a 0..1 scale, so reusing the relative
    `threshold` would silently retune this check whenever the op
    knob moved. Identical captures produce zero flags by
    construction."""
    regressions: List[Dict[str, Any]] = []
    b_ops = before.get("ops") or {}
    a_ops = after.get("ops") or {}
    for name, a in a_ops.items():
        b = b_ops.get(name)
        if b is None:
            if a["total_us"] >= min_us:
                regressions.append({
                    "kind": "new_op", "name": name,
                    "after_us": a["total_us"],
                    "note": "op absent from the baseline capture",
                })
            continue
        if (a["total_us"] > b["total_us"] * (1.0 + threshold)
                and a["total_us"] - b["total_us"] >= min_us):
            regressions.append({
                "kind": "op_regression", "name": name,
                "before_us": b["total_us"], "after_us": a["total_us"],
                "ratio": round(a["total_us"] / max(b["total_us"], 1e-9),
                               3),
            })
    b_tot = float(before.get("device_time_us") or 0.0)
    a_tot = float(after.get("device_time_us") or 0.0)
    if a_tot > b_tot * (1.0 + threshold) and a_tot - b_tot >= min_us:
        regressions.append({
            "kind": "device_time_regression",
            "before_us": b_tot, "after_us": a_tot,
            "ratio": round(a_tot / max(b_tot, 1e-9), 3),
        })
    b_fus = before.get("fusion") or {}
    a_fus = after.get("fusion") or {}
    # Fusion breakup: the same workload executing MORE distinct ops
    # while the fused share of device time fell — the compiler split
    # work fusions used to cover.
    if (int(after.get("distinct_ops") or 0)
            > int(before.get("distinct_ops") or 0) * (1.0 + threshold)
            and float(a_fus.get("share") or 0.0)
            < float(b_fus.get("share") or 0.0)):
        regressions.append({
            "kind": "fusion_breakup",
            "before_distinct_ops": before.get("distinct_ops"),
            "after_distinct_ops": after.get("distinct_ops"),
            "before_fused_share": b_fus.get("share"),
            "after_fused_share": a_fus.get("share"),
        })
    # Phase shift: a phase's device share growing past the absolute
    # share-point knob — e.g. prefill programs eating into the decode
    # window's device time.
    for phase in STEP_PHASES:
        b_share = float(((before.get("phases") or {}).get(phase)
                         or {}).get("share") or 0.0)
        a_share = float(((after.get("phases") or {}).get(phase)
                         or {}).get("share") or 0.0)
        if a_share - b_share > phase_shift_points:
            regressions.append({
                "kind": "phase_shift", "phase": phase,
                "before_share": b_share, "after_share": a_share,
            })
    return {
        "ok": not regressions,
        "threshold": threshold,
        "min_us": min_us,
        "phase_shift_points": phase_shift_points,
        "before": before.get("capture"),
        "after": after.get("capture"),
        "regressions": regressions,
    }


# ---- rendering -------------------------------------------------------


def render_report(report: Dict[str, Any]) -> str:
    """Human text for the CLI (the --json flag prints the dict)."""
    out: List[str] = []
    out.append(f"capture: {report.get('capture')}")
    out.append(
        f"device time: {report.get('device_time_us', 0) / 1e3:.3f} ms "
        f"over {report.get('op_events')} op events "
        f"({report.get('distinct_ops')} distinct ops)"
    )
    fus = report.get("fusion") or {}
    out.append(
        f"fusion: {fus.get('distinct', 0)} distinct / "
        f"{fus.get('events', 0)} events / "
        f"{100 * (fus.get('share') or 0):.1f}% of device time"
    )
    out.append("")
    out.append("phase alignment (device half of shellac_step_phase_seconds)")
    for phase in STEP_PHASES:
        p = (report.get("phases") or {}).get(phase) or {}
        out.append(
            f"  {phase:<18} {p.get('device_us', 0) / 1e3:10.3f} ms"
            f"  {100 * (p.get('share') or 0):5.1f}%"
            f"  ({p.get('ops', 0)} ops)"
        )
    un = report.get("unattributed") or {}
    out.append(
        f"  {'(unattributed)':<18} {un.get('device_us', 0) / 1e3:10.3f} ms"
        f"  {100 * (un.get('share') or 0):5.1f}%"
        f"  ({un.get('ops', 0)} ops)"
    )
    out.append("")
    out.append(f"{'top ops':<28}{'count':>7}{'total ms':>11}"
               f"{'share':>8}  phase")
    for r in report.get("top_ops") or []:
        out.append(
            f"{r['name'][:27]:<28}{r['count']:>7}"
            f"{r['total_us'] / 1e3:>11.3f}"
            f"{100 * r['share']:>7.1f}%  {r['phase'] or '-'}"
        )
    return "\n".join(out) + "\n"


def render_diff(result: Dict[str, Any]) -> str:
    out = [
        f"before: {result.get('before')}",
        f"after:  {result.get('after')}",
    ]
    regs = result.get("regressions") or []
    if not regs:
        out.append("no regressions flagged "
                   f"(threshold {100 * result['threshold']:.0f}%, "
                   f"min {result['min_us']:g}us)")
        return "\n".join(out) + "\n"
    out.append(f"{len(regs)} regression(s) flagged:")
    for r in regs:
        kind = r.get("kind")
        if kind == "op_regression":
            out.append(
                f"  op {r['name']}: {r['before_us'] / 1e3:.3f} -> "
                f"{r['after_us'] / 1e3:.3f} ms ({r['ratio']:.2f}x)"
            )
        elif kind == "new_op":
            out.append(
                f"  new op {r['name']}: {r['after_us'] / 1e3:.3f} ms "
                "(absent from baseline)"
            )
        elif kind == "device_time_regression":
            out.append(
                f"  device time: {r['before_us'] / 1e3:.3f} -> "
                f"{r['after_us'] / 1e3:.3f} ms ({r['ratio']:.2f}x)"
            )
        elif kind == "fusion_breakup":
            out.append(
                f"  fusion breakup: {r['before_distinct_ops']} -> "
                f"{r['after_distinct_ops']} distinct ops, fused share "
                f"{r['before_fused_share']} -> {r['after_fused_share']}"
            )
        elif kind == "phase_shift":
            out.append(
                f"  phase {r['phase']}: device share "
                f"{r['before_share']} -> {r['after_share']}"
            )
        else:
            out.append(f"  {r}")
    return "\n".join(out) + "\n"
