"""Incident black box: trigger-driven, auto-captured evidence bundles.

The observability stack can SEE a problem live — SLO burn rates page,
the flight recorder holds per-request timelines — but until now every
piece of evidence was volatile: the recorder ring dies with the
process, /metrics is whatever the last scrape kept, and by the time a
human opens `top` the interesting state is gone. The incident manager
closes that gap: when a trigger fires (an SLO `page` transition, a
supervisor wedge→rebuild, restart-budget exhaustion, a severed or
exhausted tier request, a failed migration, or a manual
`POST /debug/incident`), it snapshots the whole evidence surface into
one atomic on-disk BUNDLE:

    <incident-dir>/<bundle-id>/
        manifest.json        id, trigger, time, trace-id exemplar,
                             detail, section index, capture state
        flight_recorder.json the full recorder ring at trigger time
        metrics.json         registry snapshot (every series + buckets)
        requests.json        the /debug/requests in-flight table
        step_phases.json     per-phase step-time digest (sums/shares)
        config.json          config + engine/mesh fingerprint
        ...                  whatever sections the host layer wired
        capture.json         (later) profiler-capture result, if armed
        trace_report.json    (later) trace-report analysis of it

Bundles are written to a temp dir and `os.rename`d into place, so a
reader never sees a half-written bundle. Retention caps the bundle
count (oldest deleted); triggering is rate-limited with the
sliding-window RestartBudget semantics (at most `rate` bundles per
`rate_window` seconds — a flapping SLO or a severed-stream storm
yields a handful of bundles, not a full disk). Dropped triggers are
counted (`shellac_incidents_dropped_total`), never silent.

A trigger may also ARM a bounded `jax.profiler` capture: the host
layer passes its own capture callable (the server's `profile()`,
which already serializes captures through the one-at-a-time profile
lock), the capture runs on a background thread so triggering never
blocks the serving path, and when it completes the capture result —
plus a `tracereport` analysis when an analyzer was wired — is written
INTO the already-published bundle.

This module is dependency-free (stdlib only) like the rest of
`shellac_tpu.obs`: the server/tier wire their own section callables
in, so the manager never imports the serving stack (or jax).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: The trigger catalog (docs/observability.md#incidents). Triggers are
#: open-ended strings, but these are the ones the stack fires.
TRIGGERS = (
    "slo-page",                  # tier: fast-pair burn rate paged
    "wedge-rebuild",             # server: watchdog wedge -> rebuild
    "wedge-fatal",               # server: wedge, in-place factory ->
    #                              terminal ("restart the pod")
    "scheduler-death",           # server: scheduler died -> rebuild
    "restart-budget-exhausted",  # server: supervisor went fatal
    "stream-severed",            # tier: bytes lost after the client 200
    "attempts-exhausted",        # tier: request ran out of road
    "migration-failed",          # tier: disagg path gave up mid-flight
    "manual",                    # POST /debug/incident
)

_MANIFEST = "manifest.json"


class _SlidingWindow:
    """At most `limit` events inside the trailing `window` seconds —
    utils.failure.RestartBudget's semantics, restated here so the obs
    package stays dependency-free (importing utils.failure would pull
    jax into every obs consumer, including the deliberately jax-free
    `top`)."""

    def __init__(self, limit: int, window: float):
        if limit < 1:
            raise ValueError("rate limit must be >= 1")
        if window <= 0:
            raise ValueError("rate window must be > 0 seconds")
        self.limit = int(limit)
        self.window = float(window)
        self._events: List[float] = []

    def allow(self, now: Optional[float] = None) -> bool:
        t = time.monotonic() if now is None else now
        cutoff = t - self.window
        self._events = [e for e in self._events if e > cutoff]
        if len(self._events) >= self.limit:
            return False
        self._events.append(t)
        return True

    def would_allow(self, now: Optional[float] = None) -> bool:
        """Peek without consuming a slot (cheap pre-check for callers
        that would otherwise spawn a thread per trigger)."""
        t = time.monotonic() if now is None else now
        cutoff = t - self.window
        return sum(1 for e in self._events if e > cutoff) < self.limit

    def refund(self, now: Optional[float] = None) -> None:
        """Give back the most recent slot: a trigger whose bundle
        write FAILED must not throttle later (possibly succeeding)
        triggers — a full disk would otherwise convert every
        subsequent incident into a misleading 'rate-limited' drop."""
        del now
        if self._events:
            self._events.pop()


def _bundle_id(trigger: str, at: float, seq: int) -> str:
    """Sortable id: UTC timestamp first so lexicographic order IS
    chronological order (retention and listing both lean on that),
    then a per-process sequence for same-second triggers."""
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(at))
    safe = "".join(c if c.isalnum() or c == "-" else "-"
                   for c in trigger)[:32]
    return f"inc-{stamp}-{seq:04d}-{safe}"


class IncidentManager:
    """Writes evidence bundles under `incident_dir`.

    `sections` maps a section name to a zero-arg callable returning
    JSON-serializable evidence; each is evaluated AT TRIGGER TIME and
    failures are isolated per section (a broken collector yields an
    `{"error": ...}` section, never a lost bundle). The host layer
    (server or tier) owns the catalog; the manager owns atomicity,
    rate limiting, retention, and the capture arm.
    """

    def __init__(
        self,
        incident_dir: str,
        *,
        source: str = "server",
        sections: Optional[Dict[str, Callable[[], Any]]] = None,
        registry=None,
        recorder=None,
        rate: int = 6,
        rate_window: float = 600.0,
        retention: int = 24,
        capture_fn: Optional[Callable[[float], Dict[str, Any]]] = None,
        capture_seconds: float = 0.0,
        analyze_fn: Optional[Callable[[str], Dict[str, Any]]] = None,
    ):
        if retention < 1:
            raise ValueError("incident retention must be >= 1")
        if capture_seconds < 0:
            raise ValueError("capture_seconds must be >= 0")
        self.incident_dir = incident_dir
        self.source = source
        self.sections: Dict[str, Callable[[], Any]] = dict(sections or {})
        self.retention = int(retention)
        self._recorder = recorder
        self._limiter = _SlidingWindow(rate, rate_window)
        self._capture_fn = capture_fn
        self.capture_seconds = float(capture_seconds)
        self._analyze_fn = analyze_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._last: Optional[Dict[str, Any]] = None
        #: Tmp dirs with a bundle write IN FLIGHT (triggers may run
        #: concurrently on tier daemon threads): the retention sweep
        #: must not mistake a live write for crash debris.
        self._active_tmp: set = set()
        #: Bundle writes that FAILED (disk full, permissions). Kept
        #: distinct from rate-limiter drops so callers (the HTTP
        #: handlers) can answer 500 instead of a misleading 429.
        self.write_errors = 0
        self._c_incidents = self._c_dropped = self._h_bundle = None
        self._c_write_errors = None
        if registry is not None:
            self._c_incidents = registry.counter(
                "shellac_incidents_total",
                "Incident bundles written, by trigger",
                labels=("trigger",),
            )
            self._c_dropped = registry.counter(
                "shellac_incidents_dropped_total",
                "Incident triggers dropped by the rate limiter "
                "(a flapping trigger must not fill the disk)",
                labels=("trigger",),
            )
            self._h_bundle = registry.histogram(
                "shellac_incident_bundle_seconds",
                "Wall time to collect + atomically write one bundle "
                "(the cost an incident trigger adds to its code path)",
            )
            self._c_write_errors = registry.counter(
                "shellac_incident_write_errors_total",
                "Bundle writes that failed (disk full, permissions "
                "on the incident dir) — evidence was LOST, by trigger",
                labels=("trigger",),
            )
        os.makedirs(incident_dir, exist_ok=True)

    # ---- trigger -----------------------------------------------------

    def would_allow(self) -> bool:
        """Cheap peek: would a trigger right now pass the rate
        limiter? Advisory only (the authoritative check is inside
        trigger()); callers that spawn a thread per trigger use it to
        skip the spawn during a storm."""
        with self._lock:
            return self._limiter.would_allow()

    def record_drop(self, trigger: str,
                    trace_id: Optional[str] = None) -> None:
        """Count one dropped trigger WITHOUT consulting the limiter
        or attempting a write — the storm path's guaranteed-cheap
        arm (the would_allow() peek is advisory, and re-running
        trigger() after a False peek could race a freed slot into a
        synchronous bundle write on a serving thread)."""
        if self._c_dropped is not None:
            self._c_dropped.labels(trigger=trigger).inc()
        if self._recorder is not None:
            self._recorder.record(trace_id, "incident-dropped",
                                  src=self.source, trigger=trigger)

    def trigger(self, trigger: str, *, trace_id: Optional[str] = None,
                detail: Optional[Dict[str, Any]] = None,
                capture_seconds: Optional[float] = None,
                ) -> Optional[str]:
        """Fire one trigger: collect every section, write the bundle
        atomically, enforce retention, optionally arm a background
        profiler capture. Returns the bundle id, or None when the
        rate limiter dropped the trigger. Never raises — an incident
        path must not add failures to the failure it is recording."""
        with self._lock:
            if not self._limiter.allow():
                if self._c_dropped is not None:
                    self._c_dropped.labels(trigger=trigger).inc()
                if self._recorder is not None:
                    self._recorder.record(trace_id, "incident-dropped",
                                          src=self.source,
                                          trigger=trigger)
                return None
            self._seq += 1
            seq = self._seq
        t0 = time.monotonic()
        at = time.time()
        bid = _bundle_id(trigger, at, seq)
        want_capture = (capture_seconds
                        if capture_seconds is not None
                        else self.capture_seconds)
        armed = bool(want_capture and self._capture_fn is not None)
        manifest: Dict[str, Any] = {
            "id": bid,
            "trigger": trigger,
            "at": at,
            "at_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime(at)),
            "source": self.source,
            "trace_id": trace_id,
            "detail": detail or {},
            "sections": sorted(self.sections),
            "capture": ({"state": "armed", "seconds": want_capture}
                        if armed else None),
        }
        try:
            final = self._write_bundle(bid, manifest)
        except Exception as e:  # noqa: BLE001 — see docstring
            # A lost bundle is never silent: counted separately from
            # rate-limiter drops (so an unwritable incident dir reads
            # as a 500-class failure, not backpressure) and noted in
            # the recorder, which at least survives in the spool.
            with self._lock:
                self.write_errors += 1
                self._limiter.refund()
            if self._c_write_errors is not None:
                self._c_write_errors.labels(trigger=trigger).inc()
            if self._recorder is not None:
                self._recorder.record(
                    trace_id, "incident-write-failed",
                    src=self.source, trigger=trigger,
                    error=f"{type(e).__name__}: {e}")
            return None
        if self._c_incidents is not None:
            self._c_incidents.labels(trigger=trigger).inc()
        if self._h_bundle is not None:
            self._h_bundle.observe(time.monotonic() - t0)
        with self._lock:
            self._last = {"id": bid, "trigger": trigger, "at": at,
                          "trace_id": trace_id}
        if self._recorder is not None:
            self._recorder.record(trace_id, "incident", src=self.source,
                                  trigger=trigger, bundle=bid)
        if armed:
            threading.Thread(
                target=self._run_capture,
                args=(final, float(want_capture)),
                daemon=True, name=f"shellac-incident-capture-{bid}",
            ).start()
        self._enforce_retention()
        return bid

    def _write_bundle(self, bid: str, manifest: Dict[str, Any]) -> str:
        """Collect sections and publish the bundle directory with one
        rename: a crash mid-write leaves only a .tmp- dir (swept on
        the next trigger), never a half bundle."""
        tmp = os.path.join(self.incident_dir, f".tmp-{bid}")
        final = os.path.join(self.incident_dir, bid)
        with self._lock:
            self._active_tmp.add(tmp)
        try:
            os.makedirs(tmp, exist_ok=True)
            for name, fn in sorted(self.sections.items()):
                try:
                    data = fn()
                except Exception as e:  # noqa: BLE001 — per-section
                    data = {"error": f"{type(e).__name__}: {e}"}
                with open(os.path.join(tmp, f"{name}.json"),
                          "w") as f:
                    json.dump(data, f, default=str)
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f, indent=1, default=str)
            os.rename(tmp, final)
        finally:
            with self._lock:
                self._active_tmp.discard(tmp)
        return final

    def _run_capture(self, bundle_dir: str, seconds: float) -> None:
        """Background capture arm: run the host's profiler capture,
        then (when an analyzer is wired) the trace-report analysis,
        writing both into the published bundle. Additive writes into
        a final directory — readers treat these files as optional."""
        result: Dict[str, Any]
        try:
            result = dict(self._capture_fn(seconds) or {})
            result["state"] = "done"
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            result = {"state": "failed",
                      "error": f"{type(e).__name__}: {e}"}
        try:
            with open(os.path.join(bundle_dir, "capture.json"),
                      "w") as f:
                json.dump(result, f, default=str)
        except OSError:
            return
        # Reflect the settled state in the manifest too (atomically):
        # GET /debug/incidents summarizes manifests only, and "armed"
        # forever would hide a capture that silently died.
        self._update_manifest_capture(bundle_dir, {
            "state": result["state"],
            "seconds": seconds,
            "trace_dir": result.get("trace_dir"),
            "error": result.get("error"),
        })
        trace_dir = result.get("trace_dir")
        if result.get("state") != "done" or not trace_dir \
                or self._analyze_fn is None:
            return
        try:
            report = self._analyze_fn(str(trace_dir))
        except Exception as e:  # noqa: BLE001
            report = {"error": f"{type(e).__name__}: {e}"}
        try:
            with open(os.path.join(bundle_dir, "trace_report.json"),
                      "w") as f:
                json.dump(report, f, default=str)
        except OSError:
            pass

    def _update_manifest_capture(self, bundle_dir: str,
                                 capture: Dict[str, Any]) -> None:
        path = os.path.join(bundle_dir, _MANIFEST)
        manifest = self._read_json(path)
        if not isinstance(manifest, dict):
            return  # bundle evicted by retention meanwhile
        manifest["capture"] = {k: v for k, v in capture.items()
                               if v is not None}
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError:
            pass

    def _enforce_retention(self) -> None:
        """Delete the oldest bundles past `retention`, plus any
        orphaned .tmp- debris from a crash mid-write."""
        try:
            entries = sorted(os.listdir(self.incident_dir))
        except OSError:
            return
        with self._lock:
            active = set(self._active_tmp)
        for name in entries:
            if name.startswith(".tmp-"):
                path = os.path.join(self.incident_dir, name)
                # A concurrent trigger (tier daemon threads) may still
                # be writing its bundle here — only orphans (a crash's
                # debris) are swept.
                if path not in active:
                    shutil.rmtree(path, ignore_errors=True)
        bundles = [n for n in entries if n.startswith("inc-")]
        for name in bundles[: max(0, len(bundles) - self.retention)]:
            shutil.rmtree(os.path.join(self.incident_dir, name),
                          ignore_errors=True)

    # ---- reads -------------------------------------------------------

    @property
    def last(self) -> Optional[Dict[str, Any]]:
        """The most recent bundle's {id, trigger, at, trace_id} (the
        `top` dashboard's last-incident line), or None."""
        with self._lock:
            return dict(self._last) if self._last else None

    def list(self) -> List[Dict[str, Any]]:
        """Manifest summaries of every retained bundle, oldest first
        (the GET /debug/incidents payload). Retention bounds the scan
        to a couple dozen small files."""
        out: List[Dict[str, Any]] = []
        try:
            names = sorted(os.listdir(self.incident_dir))
        except OSError:
            return out
        for name in names:
            if not name.startswith("inc-"):
                continue
            m = self._read_json(os.path.join(self.incident_dir, name,
                                             _MANIFEST))
            if m is None:
                continue
            out.append({k: m.get(k)
                        for k in ("id", "trigger", "at", "at_iso",
                                  "trace_id", "source", "capture")})
        return out

    def load(self, bundle_id: str) -> Optional[Dict[str, Any]]:
        """One full bundle — manifest plus every section file — or
        None for an unknown/evicted id (GET /debug/incident/<id>)."""
        if os.sep in bundle_id or not bundle_id.startswith("inc-"):
            return None  # ids never contain path structure
        bdir = os.path.join(self.incident_dir, bundle_id)
        manifest = self._read_json(os.path.join(bdir, _MANIFEST))
        if manifest is None:
            return None
        out: Dict[str, Any] = {"manifest": manifest}
        try:
            files = os.listdir(bdir)
        except OSError:
            return None
        for name in sorted(files):
            if name == _MANIFEST or not name.endswith(".json"):
                continue
            out[name[: -len(".json")]] = self._read_json(
                os.path.join(bdir, name))
        return out

    @staticmethod
    def _read_json(path: str) -> Optional[Any]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None
