"""Fleet metrics federation: one scrape answers for N replicas.

The serving tier already pulls every replica's `/metrics` on the
health-poll interval — and, before this module, threw away everything
but four load-score gauges. `FleetCollector` keeps the whole parsed
exposition instead and re-exposes it on the TIER's `/metrics`:

  federated series — every replica sample re-emitted with a
    `replica="<url>"` label, so one Prometheus target (the tier)
    yields the full per-replica picture without N scrape configs that
    chase respawned replicas around.

  last-known-good through outages — a replica that stops answering
    keeps serving its LAST successful scrape (a dying replica's final
    counters are exactly the numbers an incident review needs), with
    staleness stamped next to it: `shellac_fleet_scrape_age_seconds`
    (seconds since the last good scrape) and
    `shellac_fleet_scrape_stale` (1 once the replica is unreachable
    or the age exceeds the staleness bound). `forget()` drops a
    replaced replica's series for good (tier respawn), and a scrape
    from a restarted process simply overwrites the LKG with the fresh
    (reset) series.

  fleet aggregates — tier-computed `shellac_fleet_*` series: the
    routable count, pending summed across live replicas, mean KV
    utilization, and CROSS-REPLICA MERGED latency histograms
    (`shellac_fleet_ttft_seconds`, `shellac_fleet_tpot_seconds`):
    cumulative bucket counts summed edge-wise, which is exact
    aggregation because every replica uses the same fixed bucket
    layout (obs/trace.py). Merges include stale replicas — their
    cumulative history is real traffic the fleet served.

Everything here is host-side text processing on the tier's poll and
scrape paths; replicas are untouched.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, FrozenSet, List, Optional, Tuple

from shellac_tpu.obs.metrics import _escape, _fmt
from shellac_tpu.obs.promtext import (
    ParsedMetrics,
    merge_buckets,
    parse_prometheus_text,
)

#: Replica histograms merged into shellac_fleet_* counterparts.
MERGED_HISTOGRAMS = ("shellac_ttft_seconds", "shellac_tpot_seconds")


class _Scrape:
    __slots__ = ("parsed", "t_ok", "ok")

    def __init__(self, parsed: ParsedMetrics, t_ok: float):
        self.parsed = parsed
        self.t_ok = t_ok
        self.ok = True


class FleetCollector:
    """Per-replica last-known-good scrape store + federated renderer.

    Writers: the tier's health poller (`observe` on a successful
    /metrics pull, `mark_unreachable` on a failed one, `forget` on
    respawn). Readers: the tier's `/metrics` handler (`render`), the
    SLO engine (`merged_histogram` / `sum_gauge`), and `top`.
    """

    def __init__(self, stale_after: float = 5.0):
        if stale_after <= 0:
            raise ValueError("stale_after must be > 0")
        self.stale_after = float(stale_after)
        self._lock = threading.Lock()
        self._scrapes: Dict[str, _Scrape] = {}

    # ---- writes (health poller) -------------------------------------

    def observe(self, replica_url: str, text: str) -> ParsedMetrics:
        """Store one successful scrape; returns the parse so the load
        scorer reads the same object instead of re-parsing."""
        parsed = parse_prometheus_text(text)
        with self._lock:
            self._scrapes[replica_url] = _Scrape(parsed, time.monotonic())
        return parsed

    def mark_unreachable(self, replica_url: str) -> None:
        """A scrape failed: keep the last-known-good series, flip the
        staleness flag. Unknown replicas (never scraped) stay absent —
        there is nothing to serve for them."""
        with self._lock:
            sc = self._scrapes.get(replica_url)
            if sc is not None:
                sc.ok = False

    def forget(self, replica_url: str) -> None:
        """Drop a replica's series entirely (it was REPLACED, not
        merely down: tier respawn under a new URL)."""
        with self._lock:
            self._scrapes.pop(replica_url, None)

    # ---- reads -------------------------------------------------------

    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._scrapes)

    def parsed(self, replica_url: str) -> Optional[ParsedMetrics]:
        with self._lock:
            sc = self._scrapes.get(replica_url)
            return sc.parsed if sc is not None else None

    def staleness(self) -> Dict[str, Tuple[float, bool]]:
        """{replica: (age of last good scrape, stale?)}."""
        now = time.monotonic()
        out: Dict[str, Tuple[float, bool]] = {}
        with self._lock:
            for url, sc in self._scrapes.items():
                age = now - sc.t_ok
                out[url] = (age, (not sc.ok) or age > self.stale_after)
        return out

    def merged_histogram(self, family: str
                         ) -> Tuple[List[Tuple[float, float]], float, float]:
        """Cross-replica merged cumulative buckets + (_sum, _count)
        for one histogram family, LKG included."""
        with self._lock:
            scrapes = list(self._scrapes.values())
        series = []
        total_sum = total_count = 0.0
        for sc in scrapes:
            b = sc.parsed.buckets(family)
            if b:
                series.append(b)
            s, c = sc.parsed.histogram_sum_count(family)
            total_sum += s
            total_count += c
        return merge_buckets(series), total_sum, total_count

    def sum_gauge(self, name: str, fresh_only: bool = True) -> float:
        """Sum one gauge across replicas (every labeling of it), by
        default over FRESH scrapes only — a dead replica holds no
        pending work, whatever its last exposition said."""
        stale = self.staleness()
        with self._lock:
            items = list(self._scrapes.items())
        total = 0.0
        for url, sc in items:
            if fresh_only and stale.get(url, (0, True))[1]:
                continue
            for _, v in sc.parsed.series(name):
                total += v
        return total

    def mean_gauge(self, name: str, fresh_only: bool = True
                   ) -> Optional[float]:
        stale = self.staleness()
        with self._lock:
            items = list(self._scrapes.items())
        vals: List[float] = []
        for url, sc in items:
            if fresh_only and stale.get(url, (0, True))[1]:
                continue
            v = sc.parsed.value(name)
            if v is not None:
                vals.append(v)
        if not vals:
            return None
        return sum(vals) / len(vals)

    # ---- exposition --------------------------------------------------

    @staticmethod
    def _labelstr(labels: Dict[str, str]) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels.items())
        return "{" + inner + "}"

    def render(self, *, routable_count: Optional[int] = None,
               skip_families: FrozenSet[str] = frozenset()) -> str:
        """The federated exposition block, appended after the tier's
        own `Registry.render()` output.

        `skip_families` carries the family names the tier's registry
        already emitted `# TYPE` headers for (e.g. the tier's own
        flight-recorder counters, which replicas also expose): their
        federated samples are still emitted — same family, disjoint
        `replica`-labeled series — but the duplicate header is not,
        keeping the combined exposition format-valid.
        """
        now = time.monotonic()
        with self._lock:
            scrapes = sorted(self._scrapes.items())
        lines: List[str] = []

        # -- staleness stamps + aggregates -----------------------------
        lines.append(
            "# HELP shellac_fleet_scrape_age_seconds Seconds since the "
            "last successful /metrics scrape of this replica (its "
            "series below are last-known-good once this grows)"
        )
        lines.append("# TYPE shellac_fleet_scrape_age_seconds gauge")
        for url, sc in scrapes:
            ls = self._labelstr({"replica": url})
            lines.append(
                f"shellac_fleet_scrape_age_seconds{ls} "
                f"{_fmt(round(now - sc.t_ok, 3))}"
            )
        lines.append(
            "# HELP shellac_fleet_scrape_stale 1 when the replica's "
            "series are last-known-good (scrape failing or older than "
            "the staleness bound), else 0"
        )
        lines.append("# TYPE shellac_fleet_scrape_stale gauge")
        for url, sc in scrapes:
            ls = self._labelstr({"replica": url})
            stale = (not sc.ok) or (now - sc.t_ok) > self.stale_after
            lines.append(f"shellac_fleet_scrape_stale{ls} "
                         f"{1 if stale else 0}")

        if routable_count is not None:
            lines.append(
                "# HELP shellac_fleet_replicas_routable Replicas the "
                "tier will currently route to"
            )
            lines.append("# TYPE shellac_fleet_replicas_routable gauge")
            lines.append(
                f"shellac_fleet_replicas_routable {routable_count}"
            )
        pending = self.sum_gauge("shellac_pending_requests")
        lines.append(
            "# HELP shellac_fleet_pending_requests Pending requests "
            "summed across live (non-stale) replicas"
        )
        lines.append("# TYPE shellac_fleet_pending_requests gauge")
        lines.append(f"shellac_fleet_pending_requests {_fmt(pending)}")
        kv = self.mean_gauge("shellac_kv_utilization")
        if kv is not None:
            lines.append(
                "# HELP shellac_fleet_kv_utilization Mean KV-cache "
                "utilization across live (non-stale) replicas"
            )
            lines.append("# TYPE shellac_fleet_kv_utilization gauge")
            lines.append(f"shellac_fleet_kv_utilization {_fmt(kv)}")

        for family in MERGED_HISTOGRAMS:
            buckets, h_sum, h_count = self.merged_histogram(family)
            if not buckets:
                continue
            fleet = family.replace("shellac_", "shellac_fleet_", 1)
            lines.append(
                f"# HELP {fleet} Cross-replica merge of {family} "
                "(cumulative buckets summed edge-wise; stale replicas' "
                "history included)"
            )
            lines.append(f"# TYPE {fleet} histogram")
            for le, cum in buckets:
                lines.append(
                    f'{fleet}_bucket{{le="{_fmt(le)}"}} {_fmt(cum)}'
                )
            lines.append(f"{fleet}_sum {_fmt(h_sum)}")
            lines.append(f"{fleet}_count {_fmt(h_count)}")

        # -- federated per-replica series ------------------------------
        # Family-major order (the exposition format requires all of a
        # family's samples in ONE group): for each family, one header,
        # then every replica's samples of it with the replica label.
        grouped: Dict[str, List[Tuple[str, str, Dict[str, str], float]]] = {}
        order: List[str] = []
        kinds: Dict[str, str] = {}
        helps: Dict[str, str] = {}
        for url, sc in scrapes:
            for name, labels, value in sc.parsed.samples:
                family = name
                for suffix in ("_bucket", "_sum", "_count"):
                    if name.endswith(suffix) and (
                        name[: -len(suffix)] in sc.parsed.types
                    ):
                        family = name[: -len(suffix)]
                        break
                if family not in grouped:
                    grouped[family] = []
                    order.append(family)
                kinds.setdefault(family, sc.parsed.types.get(family, ""))
                helps.setdefault(family, sc.parsed.helps.get(family, ""))
                grouped[family].append((url, name, labels, value))
        for family in order:
            if family not in skip_families:
                if helps[family]:
                    lines.append(f"# HELP {family} "
                                 f"{_escape(helps[family])}")
                if kinds[family]:
                    lines.append(f"# TYPE {family} {kinds[family]}")
            for url, name, labels, value in grouped[family]:
                merged = dict(labels)
                merged["replica"] = url  # flat federation: ours wins
                lines.append(
                    f"{name}{self._labelstr(merged)} {_fmt(value)}"
                )
        return "\n".join(lines) + ("\n" if lines else "")
