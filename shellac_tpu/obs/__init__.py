"""shellac_tpu.obs — unified metrics & request tracing.

A dependency-free metrics core (`Counter`, `Gauge`, `Histogram`,
`Registry` with labeled series and Prometheus text exposition) plus the
`RequestTrace` span recorder that rides each serving request from
submit to settlement. Engines, the HTTP server, and the training loop
all deposit into one process-global registry by default
(`get_registry()`), so `GET /metrics` — or a bench snapshot — sees
training throughput and serving latency through one exposition path.

See docs/observability.md for the metric catalog and scrape examples.
"""

from shellac_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    linear_buckets,
    log_buckets,
    set_default_registry,
)
from shellac_tpu.obs.trace import (
    EngineMetrics,
    RequestTrace,
    ServeMetrics,
    TierMetrics,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "set_default_registry",
    "log_buckets",
    "linear_buckets",
    "EngineMetrics",
    "RequestTrace",
    "ServeMetrics",
    "TierMetrics",
]
