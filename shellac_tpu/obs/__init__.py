"""shellac_tpu.obs — unified metrics, request tracing & introspection.

A dependency-free metrics core (`Counter`, `Gauge`, `Histogram` with
per-bucket trace-id exemplars, `Registry` with labeled series and
Prometheus text exposition), the `RequestTrace` span recorder that
rides each serving request from submit to settlement, and the
distributed-tracing layer (`events.py`): W3C-shaped trace ids with the
x-shellac-trace / x-request-id header contract, plus the
`FlightRecorder` ring of lifecycle events behind the /debug endpoints.
Engines, the HTTP server, and the training loop all deposit into one
process-global registry by default (`get_registry()`), so
`GET /metrics` — or a bench snapshot — sees training throughput and
serving latency through one exposition path.

The fleet layer builds on those: `promtext.py` (the one scrape-side
Prometheus text parser every consumer shares), `fleet.py` (the tier's
federated collector — replica series re-exposed with a `replica`
label, last-known-good through outages, `shellac_fleet_*` merged
aggregates), and `slo.py` (declarative objectives evaluated by
multi-window burn rate, with an ok→warning→page alert state machine
that lands transitions in the flight recorder).

The incident layer makes the evidence durable: `spool.py` (a
rotating, size-capped JSONL spill sink the recorder writes through,
so a SIGKILL'd replica's timelines survive to disk), `incident.py`
(trigger-driven evidence bundles — SLO pages, supervisor rebuilds,
severed/exhausted tier requests, manual POST /debug/incident — each
an atomic on-disk snapshot of the recorder, metrics, in-flight table,
SLO state, and config fingerprint), and `tracereport.py` (the
trace-reading half of /debug/profile: op-level attribution, fusion
counts, and phase alignment from a captured device trace, with a
regression-flagging diff).

See docs/observability.md for the metric catalog, the tracing/header
contract, the recorder event catalog, and §Fleet.
"""

from shellac_tpu.obs.events import (
    REQUEST_ID_HEADER,
    TRACE_HEADER,
    FlightRecorder,
    adopt_trace,
    format_trace_header,
    new_trace_id,
    parse_trace_header,
)
from shellac_tpu.obs.fleet import (
    MERGED_HISTOGRAMS,
    FleetCollector,
)
from shellac_tpu.obs.incident import (
    TRIGGERS,
    IncidentManager,
)
from shellac_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    linear_buckets,
    log_buckets,
    set_default_registry,
)
from shellac_tpu.obs.promtext import (
    ParsedMetrics,
    cumulative_at,
    histogram_quantile,
    merge_buckets,
    parse_prometheus_text,
)
from shellac_tpu.obs.scenario import (
    ScenarioMetrics,
)
from shellac_tpu.obs.slo import (
    SLOEngine,
    SLOSpec,
    parse_slo_specs,
)
from shellac_tpu.obs.spool import (
    EventSpool,
    read_spool,
    spool_events_for,
    spool_path,
)
from shellac_tpu.obs.trace import (
    STEP_PHASES,
    EngineMetrics,
    RequestTrace,
    ServeMetrics,
    TierMetrics,
)
from shellac_tpu.obs.train import (
    ResilienceMetrics,
    train_interval_histogram,
)

__all__ = [
    "FlightRecorder",
    "TRACE_HEADER",
    "REQUEST_ID_HEADER",
    "new_trace_id",
    "parse_trace_header",
    "format_trace_header",
    "adopt_trace",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "set_default_registry",
    "log_buckets",
    "linear_buckets",
    "EngineMetrics",
    "RequestTrace",
    "ServeMetrics",
    "TierMetrics",
    "ResilienceMetrics",
    "train_interval_histogram",
    "STEP_PHASES",
    "ParsedMetrics",
    "parse_prometheus_text",
    "histogram_quantile",
    "cumulative_at",
    "merge_buckets",
    "FleetCollector",
    "MERGED_HISTOGRAMS",
    "SLOEngine",
    "SLOSpec",
    "parse_slo_specs",
    "ScenarioMetrics",
    "IncidentManager",
    "TRIGGERS",
    "EventSpool",
    "read_spool",
    "spool_events_for",
    "spool_path",
]
