"""The `shellac_train_*` metric bundles, owned by the obs layer.

The bundle layer owns the `shellac_*` namespace (SH015 enforces this):
every metric family the training stack emits is declared here, next to
the serving bundles in `trace.py`, so `docs/observability.md` and the
code share one source of truth. `training.resilience` re-exports
`ResilienceMetrics` for its existing callers; both register idempotently
against the shared registry.
"""

from __future__ import annotations

from shellac_tpu.obs.metrics import get_registry, log_buckets


class ResilienceMetrics:
    """The `shellac_train_*` resilience series, registered once
    (idempotently) against the shared registry so the fit loop, the
    checkpointer, and tests all deposit into the same instruments."""

    def __init__(self, registry=None):
        reg = registry if registry is not None else get_registry()
        self.anomalies = reg.counter(
            "shellac_train_anomalies_total",
            "Training anomalies by kind and resolved action",
            labels=("kind", "action"),
        )
        self.rollbacks = reg.counter(
            "shellac_train_rollbacks_total",
            "Checkpoint rollbacks performed by the training loop",
        )
        self.quarantined = reg.counter(
            "shellac_train_ckpt_quarantined_total",
            "Checkpoint steps renamed *.corrupt after failing "
            "verification or restore",
        )
        self.fallback_restores = reg.counter(
            "shellac_train_ckpt_fallback_restores_total",
            "Restores that had to walk past the newest step to an "
            "older intact one",
        )
        self.last_good_step = reg.gauge(
            "shellac_train_last_good_step",
            "Newest checkpoint step believed intact (set on save and "
            "on every restore)",
        )


def train_interval_histogram(registry=None):
    """Step-interval wall-time distribution in the shared registry, so
    training pace is scrapable alongside serving latency (one series
    per process; registration is idempotent)."""
    reg = registry if registry is not None else get_registry()
    return reg.histogram(
        "shellac_train_log_interval_seconds",
        "Wall time between metric log boundaries (log_every steps)",
        buckets=log_buckets(0.001, 600.0),
    )
