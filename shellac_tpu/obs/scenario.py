"""The `shellac_scenario_*` metric bundle, owned by the obs layer.

The scenario gate (`inference/scenarios.py`, `python -m shellac_tpu
scenarios`) runs workload-model traffic against a replica and turns
per-scenario SLO assertions into verdicts. Its metric families are
declared here — next to the serving bundles in `trace.py` and the
training bundle in `train.py` — so the `shellac_*` namespace stays
owned by obs (SH015) and `docs/observability.md` and the code share
one source of truth. Registration is idempotent against the shared
registry, so the CLI runner, tests, and any embedding caller deposit
into the same instruments.
"""

from __future__ import annotations

from shellac_tpu.obs.metrics import get_registry, log_buckets


class ScenarioMetrics:
    """The scenario-gate series: one bundle per runner process."""

    def __init__(self, registry=None):
        reg = registry if registry is not None else get_registry()
        self.runs = reg.counter(
            "shellac_scenario_runs_total",
            "Scenario executions by final verdict (pass|fail|skip)",
            labels=("scenario", "verdict"),
        )
        self.requests = reg.counter(
            "shellac_scenario_requests_total",
            "Workload requests issued by the scenario runner, by "
            "client-side outcome (ok, cancelled, http_NNN, "
            "connect_error, stream_severed, client_saturated, ...)",
            labels=("scenario", "outcome"),
        )
        self.good_fraction = reg.gauge(
            "shellac_scenario_slo_good_fraction",
            "Final good-event fraction per scenario and SLO assertion "
            "(compare against the SLO's objective)",
            labels=("scenario", "slo"),
        )
        self.breaches = reg.counter(
            "shellac_scenario_slo_breaches_total",
            "SLO assertions that finished below objective — each one "
            "fails the scenario and fires an incident bundle naming a "
            "violating trace id",
            labels=("scenario", "slo"),
        )
        self.duration = reg.histogram(
            "shellac_scenario_duration_seconds",
            "Wall time to run one scenario (workload playback plus "
            "verdict evaluation; skips observe ~0)",
            buckets=log_buckets(0.1, 600.0),
        )
