"""`python -m shellac_tpu top` — a live fleet view over one tier URL.

The terminal counterpart of the federation work: everything rendered
here comes from the tier's public observability surface — `/metrics`
(tier series + the federated per-replica block), `/slo`, `/stats`,
and `/debug/requests` — so what the dashboard shows is exactly what a
Prometheus + alerting stack would see, just without the stack:

    $ python -m shellac_tpu top --tier http://tier:8100
    $ python -m shellac_tpu top --tier http://tier:8100 --once   # CI
    $ python -m shellac_tpu top --tier http://tier:8100 \
          --trace 00-abc...-01        # one request's timeline

Layout: a fleet header (routable count, outcomes, fleet p99s), the
SLO block (state + the four window burn rates per objective), a
per-replica table (routability, pending, KV utilization, p99 TTFT,
staleness), the step-phase attribution bars (where each replica's
engine tick actually goes — the measurement the prefill/decode
disaggregation decision reads), and the recorder's recent events.

Refresh is plain-text: ANSI clear + redraw on an interval (degrading
to `--once` single-shot for scripts and CI assertions, and to
best-effort partial renders when an endpoint 404s — a tier without
SLOs configured still tops fine). Endpoint failures mark the section
absent rather than crashing the loop: a dashboard must outlive the
thing it watches.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from shellac_tpu.obs.promtext import (
    ParsedMetrics,
    histogram_quantile,
    parse_prometheus_text,
)
from shellac_tpu.obs.trace import STEP_PHASES

#: Compact per-phase tags for the attribution bars.
_PHASE_TAGS = {
    "admission": "adm",
    "prefill_dispatch": "pf",
    "prefill_settle": "pfst",
    "decode_sync": "sync",
    "settle": "settle",
    "host_bookkeeping": "host",
}

_STATE_ICON = {"ok": "·", "warning": "!", "page": "!!"}


def _get_json(base: str, path: str, timeout: float) -> Optional[Any]:
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return json.loads(r.read())
    except (OSError, ValueError, urllib.error.HTTPError):
        return None


def _get_text(base: str, path: str, timeout: float) -> Optional[str]:
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.read().decode(errors="replace")
    except (OSError, urllib.error.HTTPError):
        return None


def collect(tier_url: str, timeout: float = 5.0) -> Dict[str, Any]:
    """One snapshot of the tier's observability surface. Sections that
    fail to fetch are None — render() degrades per section."""
    base = tier_url.rstrip("/")
    metrics_text = _get_text(base, "/metrics", timeout)
    return {
        "tier": base,
        "stats": _get_json(base, "/stats", timeout),
        "slo": _get_json(base, "/slo", timeout),
        "debug": _get_json(base, "/debug/requests", timeout),
        "incidents": _get_json(base, "/debug/incidents", timeout),
        "metrics": (parse_prometheus_text(metrics_text)
                    if metrics_text is not None else None),
    }


def _fmt_ms(seconds: Optional[float]) -> str:
    if seconds is None:
        return "-"
    if seconds >= 10:
        return f"{seconds:.1f}s"
    return f"{seconds * 1e3:.0f}ms"


def _short(url: str, width: int = 30) -> str:
    u = url.replace("http://", "")
    return u if len(u) <= width else "…" + u[-(width - 1):]


def _replica_rows(parsed: Optional[ParsedMetrics],
                  stats: Optional[dict]) -> List[Dict[str, Any]]:
    """Join the /stats replica snapshots with the federated series."""
    rows: List[Dict[str, Any]] = []
    by_url: Dict[str, dict] = {}
    if stats:
        for rep in stats.get("replicas", []):
            by_url[rep["url"]] = rep
    urls = list(by_url)
    if parsed is not None:
        for u in parsed.label_values("shellac_fleet_scrape_age_seconds",
                                     "replica"):
            if u not in by_url:
                urls.append(u)
    for url in urls:
        rep = by_url.get(url, {})
        row: Dict[str, Any] = {
            "url": url,
            "state": rep.get("state", "?"),
            "role": rep.get("role", "?"),
            "breaker": rep.get("breaker", "?"),
            "pending": rep.get("pending"),
            "kv": None,
            "ttft_p99": None,
            "stale_age": None,
            "stale": None,
            "overlap": None,
            "prefill_chunk": None,
            "phases": {},
        }
        if parsed is not None:
            v = parsed.value("shellac_pending_requests", replica=url)
            if v is not None:
                row["pending"] = int(v)
            row["kv"] = parsed.value("shellac_kv_utilization",
                                     replica=url)
            # Pipeline mode flags from the engine-stat mirrors: "d" =
            # overlapped decode (depth 2), "p" = overlapped prefill.
            depth = parsed.value("shellac_engine_overlap_depth",
                                 replica=url)
            opf = parsed.value("shellac_engine_overlap_prefill",
                               replica=url)
            if depth is not None or opf is not None:
                row["overlap"] = (
                    ("d" if (depth or 0) >= 2 else "")
                    + ("p" if opf else "")
                ) or "-"
            pfc = parsed.value("shellac_engine_prefill_chunk",
                               replica=url)
            if pfc is not None:
                row["prefill_chunk"] = int(pfc)
            row["ttft_p99"] = histogram_quantile(
                parsed.buckets("shellac_ttft_seconds", replica=url),
                0.99,
            )
            row["stale_age"] = parsed.value(
                "shellac_fleet_scrape_age_seconds", replica=url)
            st = parsed.value("shellac_fleet_scrape_stale", replica=url)
            row["stale"] = bool(st) if st is not None else None
            for phase in STEP_PHASES:
                s = parsed.value("shellac_step_phase_seconds_sum",
                                 replica=url, phase=phase)
                if s is not None:
                    row["phases"][phase] = s
        rows.append(row)
    return rows


def render(snapshot: Dict[str, Any], width: int = 100) -> str:
    """Pure snapshot -> text (tested without a terminal)."""
    out: List[str] = []
    stats = snapshot.get("stats")
    parsed: Optional[ParsedMetrics] = snapshot.get("metrics")
    slo = snapshot.get("slo")
    debug = snapshot.get("debug")

    # -- fleet header --------------------------------------------------
    head = f"shellac top · {snapshot.get('tier', '?')}"
    out.append(head)
    out.append("=" * min(width, max(len(head), 40)))
    if stats is not None:
        fleet_ttft = fleet_tpot = None
        if parsed is not None:
            fleet_ttft = histogram_quantile(
                parsed.buckets("shellac_fleet_ttft_seconds"), 0.99)
            fleet_tpot = histogram_quantile(
                parsed.buckets("shellac_fleet_tpot_seconds"), 0.99)
        out.append(
            f"replicas {stats.get('replicas_healthy', '?')}/"
            f"{stats.get('replicas_total', '?')} routable · "
            f"routed {stats.get('routed', '?')} · "
            f"retried {stats.get('retried', '?')} · "
            f"ejected {stats.get('ejected', '?')} · "
            f"uptime {stats.get('uptime_s', 0):.0f}s"
        )
        out.append(
            f"fleet p99: ttft {_fmt_ms(fleet_ttft)} · "
            f"tpot {_fmt_ms(fleet_tpot)}"
        )
    else:
        out.append("tier /stats unreachable")

    # -- SLO block -----------------------------------------------------
    if slo and slo.get("slos"):
        out.append("")
        out.append("SLOs" + " " * 28 + "state    5m      1h      6h      3d")
        for s in slo["slos"]:
            burns = s.get("windows", {})

            def b(label):
                w = burns.get(label)
                return f"{w['burn_rate']:7.2f}" if w else "      -"

            icon = _STATE_ICON.get(s.get("state"), "?")
            out.append(
                f"  {s['slo']:<28.28} {icon:>2} {s.get('state', '?'):<7}"
                f"{b('5m')} {b('1h')} {b('6h')} {b('3d')}"
            )
    elif slo is not None:
        out.append("")
        out.append("SLOs: none configured (serve-tier --slo ...)")

    # -- replica table -------------------------------------------------
    rows = _replica_rows(parsed, stats)
    if rows:
        out.append("")
        out.append(
            f"{'replica':<32}{'state':<10}{'role':<9}{'pend':>5}"
            f"{'kv%':>6}{'p99 ttft':>10}{'ovl':>5}{'pfc':>6}"
            f"{'stale':>8}"
        )
        for r in rows:
            kv = f"{100 * r['kv']:.0f}" if r["kv"] is not None else "-"
            stale = ("-" if r["stale_age"] is None else
                     (f"{r['stale_age']:.0f}s!" if r["stale"]
                      else f"{r['stale_age']:.0f}s"))
            pend = r["pending"] if r["pending"] is not None else "-"
            ovl = r["overlap"] or "-"
            pfc = ("-" if not r["prefill_chunk"]
                   else str(r["prefill_chunk"]))
            out.append(
                f"{_short(r['url'], 30):<32}{r['state']:<10}"
                f"{r['role']:<9}{pend:>5}{kv:>6}"
                f"{_fmt_ms(r['ttft_p99']):>10}{ovl:>5}{pfc:>6}"
                f"{stale:>8}"
            )
        # -- step-phase attribution bars -------------------------------
        phased = [r for r in rows if r["phases"]]
        if phased:
            out.append("")
            out.append("step-time attribution (share of engine tick)")
            for r in phased:
                total = sum(r["phases"].values())
                if total <= 0:
                    continue
                parts = []
                for phase in STEP_PHASES:
                    v = r["phases"].get(phase)
                    if v is None:
                        continue
                    parts.append(
                        f"{_PHASE_TAGS[phase]} {100 * v / total:4.1f}%"
                    )
                out.append(f"  {_short(r['url'], 30):<32}"
                           + "  ".join(parts))

    # -- tenants panel -------------------------------------------------
    # Per-tenant QoS view (serve-tier --tenant-config): the tier's
    # admission snapshot joined with the federated per-tenant series
    # (preemptions and parked bytes live on the replicas).
    tenants = stats.get("tenants") if isinstance(stats, dict) else None
    if tenants:
        preempts: Dict[str, float] = {}
        parked: Dict[str, float] = {}
        if parsed is not None:
            for fam, acc in (
                    ("shellac_tenant_preemptions_total", preempts),
                    ("shellac_tenant_parked_bytes", parked)):
                for ls, v in parsed.series(fam):
                    t = ls.get("tenant")
                    if t:
                        acc[t] = acc.get(t, 0.0) + v
        total_adm = sum(row.get("admitted", 0)
                        for row in tenants.values()) or 1
        out.append("")
        out.append(
            f"{'tenant':<22}{'class':<13}{'wt':>5}{'infl':>6}"
            f"{'share':>7}{'thr%':>7}{'preempt':>9}{'parked':>9}"
        )
        for name in sorted(tenants):
            row = tenants[name]
            adm = row.get("admitted", 0)
            thr = row.get("throttled", 0)
            rate = 100.0 * thr / max(adm + thr, 1)
            pk = parked.get(name)
            out.append(
                f"{name:<22.22}{str(row.get('priority', '-')):<13}"
                f"{row.get('weight', 0):>5.1f}"
                f"{row.get('inflight', 0):>6}"
                f"{100.0 * adm / total_adm:>6.1f}%"
                f"{rate:>6.1f}%"
                f"{int(preempts.get(name, 0)):>9}"
                f"{(f'{pk / 1024:.0f}K' if pk else '-'):>9}"
            )

    # -- autoscaler status ---------------------------------------------
    scale = stats.get("autoscale") if isinstance(stats, dict) else None
    if scale:
        last = scale.get("last_action")
        out.append("")
        out.append(
            f"autoscaler: replicas "
            f"{stats.get('replicas_healthy', '?')} routable "
            f"(min {scale.get('min_replicas')} / "
            f"max {scale.get('max_replicas')}) · "
            f"last {last or 'none'}"
            + (f" → {_short(str(scale.get('last_action_replica')), 24)}"
               if last and scale.get("last_action_replica") else "")
            + f" · cooldown {scale.get('cooldown_remaining_s', 0):.0f}s"
            + (f" · page pending: {scale['page_pending']}"
               if scale.get("page_pending") else "")
        )

    # -- last incident -------------------------------------------------
    # One line, always near the bottom: the most recent evidence
    # bundle (tier-side --incident-dir), so "did the black box fire"
    # is answered without leaving the dashboard.
    incidents = snapshot.get("incidents")
    last = incidents.get("last") if isinstance(incidents, dict) else None
    if last:
        age = time.time() - float(last.get("at") or time.time())
        out.append("")
        out.append(
            f"last incident: {last.get('id')} "
            f"[{last.get('trigger')}] {age:.0f}s ago"
            + (f" trace {str(last.get('trace_id'))[:18]}…"
               if last.get("trace_id") else "")
        )

    # -- recent events -------------------------------------------------
    if debug and debug.get("recent_events"):
        out.append("")
        out.append("recent events")
        for ev in debug["recent_events"][-8:]:
            trace = ev.get("trace")
            tid = f" {trace[:18]}…" if trace else ""
            extra = {k: v for k, v in ev.items()
                     if k not in ("seq", "ts", "trace", "event", "src")}
            brief = ", ".join(f"{k}={v}" for k, v in list(extra.items())[:4])
            out.append(f"  {ev.get('event', '?'):<16}{tid:<22} {brief}")
    return "\n".join(out) + "\n"


def render_trace(timeline: Dict[str, Any]) -> str:
    """One request's flight-recorder timeline, relative-timestamped."""
    events = timeline.get("events", [])
    out = [f"trace {timeline.get('trace_id', '?')}"]
    t0 = events[0]["ts"] if events else 0.0
    for ev in events:
        dt = ev.get("ts", t0) - t0
        extra = {k: v for k, v in ev.items()
                 if k not in ("seq", "ts", "trace", "event", "src")}
        brief = ", ".join(f"{k}={v}" for k, v in extra.items())
        out.append(f"  +{dt * 1e3:9.1f}ms  {ev.get('src', '?'):<7}"
                   f"{ev.get('event', '?'):<16}{brief}")
    return "\n".join(out) + "\n"


def run_top(tier: Optional[str], *, once: bool = False,
            interval: float = 2.0, trace: Optional[str] = None,
            timeout: float = 5.0, spool: Optional[str] = None,
            out=None) -> int:
    out = sys.stdout if out is None else out
    if trace is not None:
        timeline = (_get_json(tier.rstrip("/"),
                              f"/debug/request/{trace}", timeout)
                    if tier else None)
        if timeline is None and spool:
            # Dead-replica path: the tier (or the replica) is gone,
            # but the durable spool on disk still holds the timeline.
            from shellac_tpu.obs.spool import spool_events_for

            events = spool_events_for(spool, trace)
            if events:
                timeline = {"trace_id": trace, "events": events,
                            "source": "spool"}
        if timeline is None:
            out.write(f"no recorded timeline for {trace!r} "
                      "(evicted, never seen, --no-debug — or pass "
                      "--spool <dir> to read a dead replica's "
                      "on-disk spool)\n")
            return 1
        out.write(render_trace(timeline))
        return 0
    if once:
        out.write(render(collect(tier, timeout)))
        return 0
    try:
        while True:
            text = render(collect(tier, timeout))
            # ANSI clear + home: plain-text auto-refresh without a
            # curses dependency (works in any VT-ish terminal; pipe
            # consumers should use --once).
            out.write("\x1b[2J\x1b[H" + text)
            out.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


# The CLI entry point is `python -m shellac_tpu top` (cli.py owns the
# single argparse surface); this module stays a jax-free library.
