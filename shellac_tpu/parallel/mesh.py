"""Device-mesh construction.

TPU-first: parallelism is expressed as a `jax.sharding.Mesh` with named
axes; XLA's GSPMD partitioner inserts the collectives (all-reduce,
all-gather, reduce-scatter, collective-permute) that ride ICI. Nothing in
this module moves data itself.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from shellac_tpu.config import ParallelConfig

# Canonical mesh-axis names, outermost first. dp/fsdp tolerate the slower
# (DCN) links; sp/tp want the fastest (ICI) links, so they are innermost.
# ep sits between pp and sp: the MoE all-to-all moves one activation's
# worth of tokens per layer — more traffic than a pipeline bubble, less
# than tp's per-matmul collectives.
AXIS_DATA = "dp"
AXIS_FSDP = "fsdp"
AXIS_SEQ = "sp"
AXIS_TENSOR = "tp"
AXIS_PIPE = "pp"
AXIS_EXPERT = "ep"

MESH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_EXPERT, AXIS_SEQ,
             AXIS_TENSOR)


def make_mesh(
    parallel: Optional[ParallelConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global device mesh for a ParallelConfig.

    If `parallel` is None, all devices are assigned to the fsdp axis (a
    sensible single-slice default: ZeRO-3 with no extra communication
    tuning needed).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if parallel is None:
        parallel = ParallelConfig(fsdp=n)
    if parallel.num_devices != n:
        raise ValueError(
            f"ParallelConfig asks for {parallel.num_devices} devices "
            f"(dp={parallel.dp} fsdp={parallel.fsdp} pp={parallel.pp} "
            f"ep={parallel.ep} sp={parallel.sp} tp={parallel.tp}) but "
            f"{n} are available"
        )
    shape = (parallel.dp, parallel.fsdp, parallel.pp, parallel.ep,
             parallel.sp, parallel.tp)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except Exception:
        # mesh_utils optimizes for physical topology; fall back to a plain
        # reshape when it cannot (e.g. virtual CPU devices).
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def factor_devices(n: int, *, moe: bool = False) -> ParallelConfig:
    """Pick a reasonable multi-axis factorization of `n` devices.

    Used by dry-run tooling to exercise real shardings on a virtual
    mesh: spread powers of two across tp, sp, then (for n >= 8, so the
    graded dryrun covers the pipeline path too) pp, then fsdp; any odd
    remainder lands on dp. Note 8 devices fit only three size-2 axes,
    so fsdp stays 1 there — dryrun_multichip covers ZeRO-3 with a
    second, fsdp=2 mesh instead.

    With `moe=True` (expert-routed models) the order becomes
    tp → ep → fsdp → sp → pp: the expert all-to-all deserves an axis
    before sequence/pipeline splits, and experts shard over (ep, fsdp)
    so fsdp follows ep. At n=8 this yields fsdp2/ep2/tp2 — the DeepSeek
    ep mesh the graded dryrun exercises.
    """
    sizes = {"tp": 1, "ep": 1, "sp": 1, "pp": 1, "fsdp": 1, "dp": 1}
    remaining = n
    order = (("tp", "ep", "fsdp", "sp", "pp") if moe
             else ("tp", "sp", "pp", "fsdp"))
    for axis in order:
        if axis == "pp" and n < 8:
            continue
        if remaining % 2 == 0 and remaining > 1:
            sizes[axis] = 2
            remaining //= 2
    sizes["dp"] = remaining
    return ParallelConfig(
        dp=sizes["dp"], fsdp=sizes["fsdp"], pp=sizes["pp"],
        ep=sizes["ep"], sp=sizes["sp"], tp=sizes["tp"],
    )
