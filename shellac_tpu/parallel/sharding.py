"""Logical-axis sharding rules.

Every parameter and activation carries *logical* axis names (e.g.
("layers", "embed", "mlp")); a rule table maps logical names to mesh axes.
This is the GSPMD idiom: annotate shardings, let XLA insert collectives.
Changing the parallelism strategy is a rule-table edit, not a model edit.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shellac_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_TENSOR,
)

LogicalAxes = Tuple[Optional[str], ...]

# logical axis name -> mesh axis (or tuple of mesh axes, or None=replicated)
DEFAULT_RULES: Tuple[Tuple[str, Union[None, str, Tuple[str, ...]]], ...] = (
    # activations
    ("batch", (AXIS_DATA, AXIS_FSDP)),
    ("seq", AXIS_SEQ),
    ("kv_seq", AXIS_SEQ),
    # parameters
    ("vocab", AXIS_TENSOR),
    ("embed", AXIS_FSDP),
    ("heads", AXIS_TENSOR),
    ("kv_heads", AXIS_TENSOR),
    ("head_dim", None),
    ("mlp", AXIS_TENSOR),
    # Expert weights AND the dispatched capacity buckets shard the E
    # dim over (ep, fsdp): with ep=1 this is round-3's ZeRO-style
    # memory sharding; with ep>1 each ep group owns E/ep experts and
    # the expert FFN einsums are fully local — XLA inserts the token
    # all-to-all at the scatter (dispatch) / gather (combine)
    # resharding boundaries in ops/moe.py.
    ("experts", (AXIS_EXPERT, AXIS_FSDP)),
    # Stacked layers shard over the pipeline axis: with pp=1 this is a
    # no-op; with pp>1 each device holds its own pipeline stage's layers.
    ("layers", AXIS_PIPE),
)


def rules_dict(rules=DEFAULT_RULES):
    return dict(rules)


def logical_to_spec(axes: Sequence[Optional[str]], rules=DEFAULT_RULES) -> P:
    """Translate logical axis names into a PartitionSpec via the rule table."""
    table = dict(rules)
    spec = []
    used = set()
    for name in axes:
        if name is None:
            spec.append(None)
            continue
        mesh_axes = table.get(name)
        if mesh_axes is None:
            spec.append(None)
            continue
        if isinstance(mesh_axes, str):
            mesh_axes = (mesh_axes,)
        # A mesh axis may appear at most once in a PartitionSpec; drop
        # repeats (e.g. both "embed" and "mlp" map to axes already used).
        fresh = tuple(a for a in mesh_axes if a not in used)
        used.update(fresh)
        if not fresh:
            spec.append(None)
        elif len(fresh) == 1:
            spec.append(fresh[0])
        else:
            spec.append(fresh)
    return P(*spec)


def make_shardings(mesh: Mesh, logical_tree, rules=DEFAULT_RULES):
    """Map a pytree of logical-axes tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )


def constrain(x, mesh: Optional[Mesh], axes: Sequence[Optional[str]], rules=DEFAULT_RULES):
    """`with_sharding_constraint` by logical axis names; no-op without a mesh.

    Keeping this a no-op when mesh is None lets the same model code run
    un-sharded (unit tests, single chip) and sharded (pjit over a mesh).
    """
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_to_spec(axes, rules))
    )


def shard_pytree(tree, mesh: Mesh, logical_tree, rules=DEFAULT_RULES):
    """Device-put a pytree according to its logical axes."""
    shardings = make_shardings(mesh, logical_tree, rules)
    return jax.device_put(tree, shardings)
