"""GPipe-style pipeline parallelism, the GSPMD way.

No per-stage programs: the layer stack is sharded over the `pp` mesh
axis (rule "layers" -> "pp"), activations for all stages live in one
(pp, micro_batch, ...) array sharded the same way, and one `lax.scan`
over pipeline ticks does, per tick:

    shift   — jnp.roll along the stage axis (XLA: collective-permute
              over ICI) + insert the next microbatch at stage 0
    compute — vmap(stage_fn) over the stage axis; since both weights
              and activations are sharded on that axis, each device
              computes exactly its own stage
    collect — the last stage's output lands in the results buffer

Bubble fraction is (pp-1)/(n_micro+pp-1); raise n_micro to amortize.
Everything composes with dp/fsdp/sp/tp sharding inside stage_fn because
it is all still one GSPMD program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from shellac_tpu.parallel.sharding import constrain

# Logical axes for the pipeline buffers, resolved through the shared
# rule table ("batch" -> (dp, fsdp), "seq" -> sp, "layers" -> pp), so a
# rule-table edit re-lays-out the pipeline with the rest of the model.
_MICRO_AXES = (None, "batch", "seq", None)
_STAGE_AXES = ("layers", "batch", "seq", None)


def _micro_extra_axes(r, leaf_axes=None):
    """Logical axes for a microbatched extras leaf (n_micro, bm, ...).

    Default: batch/seq shard like the activations, trailing dims
    unsharded. `leaf_axes` overrides the per-row axes (everything after
    the microbatch dim) — e.g. packed segment ids want ("batch", None)
    so the sp replication the model set up survives microbatching."""
    if leaf_axes is not None:
        return (None, *leaf_axes)
    return (None, "batch", "seq") + (None,) * (r.ndim - 3)


def pipeline_apply(
    stage_fn: Callable,  # (stage_params, x (B_m, S, D)[, extras]) -> ...
    stage_params,  # pytree, leaves (pp, ...) sharded over "pp"
    x: jax.Array,  # (B, S, D)
    *,
    n_stages: int,
    n_micro: int,
    mesh: Mesh,
    aux_init=None,  # pytree of scalar zeros; stage_fn then returns (y, aux)
    extras=None,  # pytree of per-token arrays (B, S, ...) riding with x
    extras_axes=None,  # optional pytree of logical axes per extras leaf
):
    """Run the stage pipeline; returns outputs, or (outputs, aux_sum).

    With `aux_init`, stage_fn must return (y, aux) where aux matches
    aux_init's structure (fp32 scalars). Contributions from bubble
    ticks — stages holding no live microbatch during warmup/drain —
    are masked out; the result sums every (stage, microbatch) pair's
    aux exactly once.

    With `extras`, each leaf (B, S, ...) is microbatched alongside x
    and shifted through the same stage register, so stage_fn(sp, x, ex)
    sees exactly the rows it is processing — this is how packed
    segment ids and per-row RoPE tables ride the pipeline.
    """
    b, s, d = x.shape
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
    bm = b // n_micro

    micro = constrain(x.reshape(n_micro, bm, s, d), mesh, _MICRO_AXES)
    stage_ids = jnp.arange(n_stages)

    def micro_extras_leaf(a, la=None):
        r = a.reshape(n_micro, bm, *a.shape[1:])
        return constrain(r, mesh, _micro_extra_axes(r, la))

    if extras is None:
        micro_ex = None
    elif extras_axes is not None:
        micro_ex = jax.tree.map(
            micro_extras_leaf, extras, extras_axes,
            is_leaf=lambda x: isinstance(x, tuple),
        )
    else:
        micro_ex = jax.tree.map(micro_extras_leaf, extras)

    def tick(carry, t):
        stages_x, stages_ex, outputs, aux_acc = carry
        ti = jnp.clip(t, 0, n_micro - 1)
        inp0 = jax.lax.dynamic_index_in_dim(micro, ti, 0, keepdims=False)
        shifted = jnp.roll(stages_x, 1, axis=0).at[0].set(inp0)
        shifted = constrain(shifted, mesh, _STAGE_AXES)
        if stages_ex is not None:
            shifted_ex = jax.tree.map(
                lambda buf, m: jnp.roll(buf, 1, axis=0).at[0].set(
                    jax.lax.dynamic_index_in_dim(m, ti, 0, keepdims=False)
                ),
                stages_ex, micro_ex,
            )
            call = lambda sp, xx, ex: stage_fn(sp, xx, ex)
            res = jax.vmap(call)(stage_params, shifted, shifted_ex)
        else:
            shifted_ex = None
            res = jax.vmap(stage_fn)(stage_params, shifted)
        if aux_init is None:
            y = res
        else:
            y, aux = res  # aux: (pp,)
            # Stage s processes microbatch t - s; outside [0, n_micro)
            # it is chewing on bubble zeros and its aux is garbage.
            m = t - stage_ids
            live = (m >= 0) & (m < n_micro)
            aux_acc = jax.tree.map(
                lambda acc, v: acc + jnp.sum(jnp.where(live, v, 0.0)),
                aux_acc, aux,
            )
        y = constrain(y, mesh, _STAGE_AXES)

        out_idx = t - (n_stages - 1)
        safe = jnp.clip(out_idx, 0, n_micro - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, safe, 0, keepdims=False)
        val = jnp.where(out_idx >= 0, y[-1], prev)
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, val, safe, 0)
        return (y, shifted_ex, outputs, aux_acc), None

    stages0 = constrain(
        jnp.zeros((n_stages, bm, s, d), x.dtype), mesh, _STAGE_AXES
    )
    stages_ex0 = (
        jax.tree.map(
            lambda m: jnp.zeros((n_stages, *m.shape[1:]), m.dtype), micro_ex
        )
        if micro_ex is not None
        else None
    )
    out0 = constrain(jnp.zeros((n_micro, bm, s, d), x.dtype), mesh, _MICRO_AXES)
    aux0 = jax.tree.map(jnp.asarray, aux_init) if aux_init is not None else 0.0
    ticks = jnp.arange(n_micro + n_stages - 1)
    (_, _, outputs, aux_sum), _ = jax.lax.scan(
        tick, (stages0, stages_ex0, out0, aux0), ticks
    )
    outputs = outputs.reshape(b, s, d)
    if aux_init is None:
        return outputs
    return outputs, aux_sum
