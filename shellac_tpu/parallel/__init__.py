from shellac_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_TENSOR,
    MESH_AXES,
    factor_devices,
    make_mesh,
)
from shellac_tpu.parallel.ulysses import ulysses_attention, ulysses_supported
from shellac_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    logical_to_spec,
    make_shardings,
    shard_pytree,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_FSDP",
    "AXIS_PIPE",
    "AXIS_SEQ",
    "AXIS_TENSOR",
    "MESH_AXES",
    "make_mesh",
    "factor_devices",
    "DEFAULT_RULES",
    "logical_to_spec",
    "make_shardings",
    "shard_pytree",
    "constrain",
    "ulysses_attention",
    "ulysses_supported",
]
