"""Ulysses-style (all-to-all) sequence parallelism over the 'sp' mesh axis.

The complement to ring attention (parallel/ring_attention.py): instead of
rotating kv chunks around a ring, two `all_to_all` collectives reshard
the activations so attention itself is embarrassingly parallel.

Each sp rank enters holding a contiguous sequence chunk of q/k/v
(B, S/n, H, D). The first all-to-all trades the sequence sharding for a
head sharding: every rank ends up with the FULL sequence for H/n heads.
Local attention then needs no communication at all — so it supports
sliding windows and arbitrary masks, and it can use the Pallas flash
kernel as-is (both things the ring cannot do without extra machinery).
A second all-to-all restores the sequence sharding for the residual
stream.

Cost model: 2 all-to-alls moving O(B·S·H·D / n) per device over ICI,
independent of sequence length per hop, vs the ring's n ppermutes of kv.
Ulysses wins when H is large relative to n and masks are irregular; ring
wins on kv memory (O(S/n) holds throughout) and when H/n would round
badly. Both are exposed; `auto` in the model picks ring for plain causal
and ulysses for windowed attention on an sp mesh.

GQA: kv heads are split over sp like q heads when divisible; otherwise
kv is broadcast to full multi-head (a memory cost, never a correctness
change). Backward is jax autodiff through the collectives (all_to_all is
its own transpose up to permutation).

No reference citation is possible: the reference mount is empty
(SURVEY.md §0). The design follows the public DeepSpeed-Ulysses idea,
re-expressed as shard_map + lax.all_to_all so GSPMD sees static shapes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
try:
    from jax import shard_map

    _NO_CHECK = {"check_vma": False}
except ImportError:  # pre-promotion jax: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map

    _NO_CHECK = {"check_rep": False}
from jax.sharding import Mesh, PartitionSpec as P

from shellac_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQ, AXIS_TENSOR

if hasattr(jax.lax, "axis_size"):
    _axis_size = jax.lax.axis_size
else:  # pre-0.5 jax: psum of a Python 1 folds to the static axis size
    def _axis_size(axis_name):
        return jax.lax.psum(1, axis_name)


def ulysses_supported(
    n_heads: int, n_kv_heads: int, mesh: Mesh, *, axis_name: str = AXIS_SEQ
) -> bool:
    """Can ulysses run for these head counts on this mesh?

    Heads are already sharded over tp before the sp all-to-all, so the
    per-device head count (H / tp) must split evenly over sp.
    """
    n = mesh.shape.get(axis_name, 1)
    tp = mesh.shape.get(AXIS_TENSOR, 1)
    if n_heads % tp or n_kv_heads % tp:
        return False
    return (n_heads // tp) % n == 0


def _ulysses_local(
    q, k, v, seg, sinks, *, axis_name: str, causal: bool,
    window: Optional[int], scale: float, impl: str, has_segments: bool,
    softcap=None, has_sinks=False,
):
    """Runs on one device inside shard_map.

    q: (B, S_loc, H_loc, D); k, v: (B, S_loc, Hkv_loc, D) — local
    shapes. seg: (B, S) packed document ids, FULL row (replicated over
    sp by the in_spec; dummy when has_segments=False).
    """
    from shellac_tpu.ops.attention import attention

    n = _axis_size(axis_name)
    b, s_loc, h_loc, dh = q.shape
    hkv_loc = k.shape[2]
    if h_loc % n:
        raise ValueError(
            f"ulysses: local head count {h_loc} not divisible by sp={n}"
        )
    if hkv_loc % n:
        # Repeat kv heads to the smallest count that splits evenly over
        # sp: lcm(hkv_loc, n). It divides h_loc (hkv_loc and n both do),
        # so GQA grouping downstream stays valid, and it beats
        # broadcasting to the full q head count on kv memory/bandwidth.
        import math

        hkv_new = math.lcm(hkv_loc, n)
        rep = hkv_new // hkv_loc
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    # seq-sharded -> head-sharded: (B, S_loc, H_loc, D) -> (B, S, H_loc/n, D)
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name,
        split_axis=2, concat_axis=1, tiled=True,
    )
    qh, kh, vh = a2a(q), a2a(k), a2a(v)

    seg_full = None
    if has_segments:
        # After the a2a every rank holds the FULL sequence for its
        # heads, so the block-diagonal mask needs the full segment row.
        # The in_spec replicates seg over sp (it arrives as the full
        # (B, S) row), so no per-layer collective runs here: the ids are
        # constant across layers and one resharding outside the layer
        # scan covers every block.
        seg_full = seg  # (B, S)

    sinks_h = None
    if has_sinks:
        # After the a2a this rank computes heads
        # [my * h_loc/n, (my+1) * h_loc/n) of the LOCAL (tp-sharded)
        # head axis; slice the matching sink logits.
        my = jax.lax.axis_index(axis_name)
        per = h_loc // n
        sinks_h = jax.lax.dynamic_slice_in_dim(sinks, my * per, per)
    o = attention(
        qh, kh, vh, causal=causal, window=window, scale=scale, impl=impl,
        softcap=softcap, sinks=sinks_h,
        q_segments=seg_full, kv_segments=seg_full,
    )

    # head-sharded -> seq-sharded
    return jax.lax.all_to_all(
        o, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    window: Optional[int] = None,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
    sinks: Optional[jax.Array] = None,
    segments: Optional[jax.Array] = None,  # (B, S) packed document ids
    axis_name: str = AXIS_SEQ,
    impl: str = "auto",
) -> jax.Array:
    """All-to-all sequence-parallel attention. q (B,S,H,D); k,v (B,S,Hkv,D).

    S is globally sharded over `axis_name`; batch over dp/fsdp; heads over
    tp. Returns (B,S,H,D) with the same sharding as q. `impl` is forwarded
    to the local attention dispatch ("auto" uses the flash kernel on TPU).
    With `segments`, attention is block-diagonal over packed documents.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    q_spec = P((AXIS_DATA, AXIS_FSDP), axis_name, AXIS_TENSOR, None)
    kv_spec = P((AXIS_DATA, AXIS_FSDP), axis_name, AXIS_TENSOR, None)
    # seg replicated over sp: every rank needs the full row after the
    # head a2a anyway, and ids are layer-invariant, so resharding once
    # outside beats an all_gather inside every layer's body.
    seg_spec = P((AXIS_DATA, AXIS_FSDP), None)
    sink_spec = P(AXIS_TENSOR)
    has_segments = segments is not None
    if not has_segments:
        segments = jnp.zeros(q.shape[:2], jnp.int32)
    has_sinks = sinks is not None
    if not has_sinks:
        sinks = jnp.zeros((q.shape[2],), jnp.float32)
    fn = shard_map(
        functools.partial(
            _ulysses_local, axis_name=axis_name, causal=causal,
            window=window, scale=float(scale), impl=impl,
            has_segments=has_segments,
            softcap=None if softcap is None else float(softcap),
            has_sinks=has_sinks,
        ),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, seg_spec, sink_spec),
        out_specs=q_spec,
        **_NO_CHECK,
    )
    return fn(q, k, v, segments, sinks)
