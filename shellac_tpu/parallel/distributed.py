"""Multi-host initialization and cross-host mesh construction.

On multi-host TPU pods every host runs the same program; JAX needs a
coordinator rendezvous before any collective compiles. This wraps
`jax.distributed.initialize` with the standard environment conventions
so launchers (GKE, ray, mpirun, manual ssh) all funnel through one
entry point, and builds meshes over the *global* device set with the
DCN-crossing axes outermost.

Typical use, identical on every host:

    from shellac_tpu.parallel.distributed import initialize, global_mesh
    initialize()                       # no-op on single host
    mesh = global_mesh(ParallelConfig(dp=n_hosts, fsdp=8))
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from shellac_tpu.config import ParallelConfig
from shellac_tpu.parallel.mesh import make_mesh

_ENV_ALIASES = {
    "coordinator_address": ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS"),
    "num_processes": ("JAX_NUM_PROCESSES", "NUM_PROCESSES", "WORLD_SIZE"),
    "process_id": ("JAX_PROCESS_ID", "PROCESS_ID", "RANK"),
}


def _from_env(name: str) -> Optional[str]:
    for var in _ENV_ALIASES[name]:
        v = os.environ.get(var)
        if v:
            return v
    return None


def env_config() -> Optional[dict]:
    """Distributed settings from the environment, or None if single-host."""
    addr = _from_env("coordinator_address")
    nproc = _from_env("num_processes")
    pid = _from_env("process_id")
    if addr is None and nproc is None and pid is None:
        return None
    if addr is None or nproc is None or pid is None:
        missing = [
            k for k, v in (
                ("coordinator_address", addr),
                ("num_processes", nproc),
                ("process_id", pid),
            ) if v is None
        ]
        raise ValueError(
            f"partial distributed environment: missing {missing} "
            f"(aliases: {[_ENV_ALIASES[m] for m in missing]})"
        )
    return {
        "coordinator_address": addr,
        "num_processes": int(nproc),
        "process_id": int(pid),
    }


def initialize(**overrides) -> bool:
    """Join the distributed runtime if the environment asks for it.

    Returns True when multi-host init ran, False for single-host. Safe
    to call unconditionally at program start (before first jax use).
    Explicit kwargs override the environment.
    """
    cfg = env_config() or {}
    cfg.update(overrides)
    if not cfg:
        return False
    if int(cfg.get("num_processes", 1)) <= 1:
        return False
    jax.distributed.initialize(**cfg)
    return True


def global_mesh(parallel: ParallelConfig):
    """Mesh over every device in the job (all hosts).

    The ParallelConfig must multiply out to the global device count;
    axis order already puts dp/fsdp outermost, which is where the
    DCN boundary belongs (see docs/parallelism.md).
    """
    devices = jax.devices()
    if parallel.num_devices != len(devices):
        raise ValueError(
            f"ParallelConfig wants {parallel.num_devices} devices but the "
            f"job has {len(devices)} "
            f"({jax.process_count()} processes x "
            f"{jax.local_device_count()} local)"
        )
    return make_mesh(parallel, devices=devices)
