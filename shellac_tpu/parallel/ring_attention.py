"""Ring attention: sequence-parallel attention over the 'sp' mesh axis.

Each sp rank holds a contiguous sequence chunk of q/k/v. The kv chunks
rotate around the ring (`ppermute`) while each rank folds every visiting
chunk into a running online-softmax state — the same math as the flash
kernel, lifted one level up: blocks are whole per-device chunks and the
"grid" is the ring. KV memory per device stays O(S / sp), so context
length scales linearly with the sp axis, and the permutes ride ICI
neighbor links.

Causality is enforced at two granularities: whole visiting chunks from
the future are masked out, and the diagonal (own) chunk gets the usual
triangular mask. Sliding windows add a global-position band mask per
visiting chunk (chunks wholly outside the window contribute nothing via
the mask; the rotation itself stays uniform, which is what lax.scan
wants). Backward is jax autodiff through the scan; wrap the caller in
jax.checkpoint (the model's remat does) to keep residuals per layer
instead of per ring step.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
try:
    from jax import shard_map

    _NO_CHECK = {"check_vma": False}
except ImportError:  # pre-promotion jax: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map

    _NO_CHECK = {"check_rep": False}
from jax.sharding import Mesh, PartitionSpec as P

from shellac_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQ, AXIS_TENSOR

if hasattr(jax.lax, "axis_size"):
    _axis_size = jax.lax.axis_size
else:  # pre-0.5 jax: psum of a Python 1 folds to the static axis size
    def _axis_size(axis_name):
        return jax.lax.psum(1, axis_name)

NEG_INF = -2.0e38


def _block_stats(q, k, v, scale, mask, softcap=None):
    """Unnormalized block attention: returns (acc, m, l).

    q (B,Sq,Hkv,G,D); k,v (B,Sk,Hkv,D); mask (Sq,Sk) or (B,Sq,Sk) or
    None, True=attend. acc (B,Sq,Hkv,G,D) fp32; m,l (B,Sq,Hkv,G,1) fp32.
    """
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if softcap is not None:
        s = softcap * jnp.tanh(s / softcap)
    if mask is not None:
        if mask.ndim == 2:
            mask = mask[None]
        mask = mask[:, None, None]  # (B,1,1,Sq,Sk)
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # (B,Hkv,G,Sq,1)
    # Guard all-masked blocks: exp(NEG_INF - NEG_INF) would be exp(0)=1.
    m_safe = jnp.maximum(m, -1e37)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum(
        "bhgqk,bkhd->bhgqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    # -> (B,Sq,Hkv,G,·)
    perm = (0, 3, 1, 2, 4)
    return acc.transpose(perm), m_safe.transpose(perm), l.transpose(perm)


def _ring_local(
    q, k, v, seg, sinks, *, axis_name: str, causal: bool, scale: float,
    has_segments: bool, window=None, softcap=None, has_sinks=False,
):
    """Runs on one device inside shard_map. q (B,S_loc,H,D); k,v
    (B,S_loc,Hkv,D); seg (B,S_loc) int32 (packed document ids; a dummy
    when has_segments=False — shard_map needs a uniform signature)."""
    b, s_loc, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    my = jax.lax.axis_index(axis_name)
    n = _axis_size(axis_name)

    # Keep q in its input dtype: preferred_element_type on the einsums
    # already gives fp32 accumulation, and bf16 inputs run the MXU at
    # full rate with half the live-range footprint.
    qg = q.reshape(b, s_loc, hkv, g, d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    tri = jnp.tril(jnp.ones((s_loc, s_loc), bool)) if causal else None

    def step(carry, i):
        acc, m, l, kv = carry
        k_cur, v_cur, seg_cur = kv
        src = (my - i) % n  # which chunk of the sequence we hold now
        if causal:
            # src < my: fully visible. src == my: triangular. src > my: hidden.
            block_mask = jnp.where(
                src < my,
                jnp.ones((s_loc, s_loc), bool),
                jnp.where(src == my, tri, jnp.zeros((s_loc, s_loc), bool)),
            )
        else:
            block_mask = None
        if window is not None:
            # Global positions: rank r's rows sit at r*s_loc + i.
            qpos = my * s_loc + jnp.arange(s_loc)
            kpos = src * s_loc + jnp.arange(s_loc)
            wmask = qpos[:, None] - kpos[None, :] < window  # (Sq, Sk)
            block_mask = wmask if block_mask is None else block_mask & wmask
        if has_segments:
            # Packed documents: attend only within the same segment. The
            # segment ids rotate with their kv chunk, so the pairing is
            # always (my q chunk) x (visiting kv chunk) — global-order
            # causality plus segment equality is exactly within-document
            # causal attention for contiguous packing.
            seg_mask = seg[:, :, None] == seg_cur[:, None, :]  # (B,Sq,Sk)
            block_mask = (
                seg_mask if block_mask is None
                else block_mask[None] & seg_mask
            )
        acc_c, m_c, l_c = _block_stats(qg, k_cur, v_cur, scale,
                                       block_mask, softcap)
        m_new = jnp.maximum(m, m_c)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(m_c - m_new)
        acc = acc * a1 + acc_c * a2
        l = l * a1 + l_c * a2
        # Rotate kv to the next rank; the last iteration's rotate returns
        # chunks home (kept for a uniform loop; XLA overlaps it).
        kv = jax.lax.ppermute((k_cur, v_cur, seg_cur), axis_name, perm)
        return (acc, m_new, l, kv), None

    acc0 = jnp.zeros((b, s_loc, hkv, g, d), jnp.float32)
    m0 = jnp.full((b, s_loc, hkv, g, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s_loc, hkv, g, 1), jnp.float32)
    (acc, m, l, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, (k, v, seg)), jnp.arange(n)
    )
    if has_sinks:
        # Sink denominator: per-head exp(sink) joins l. sinks is
        # (H_loc,) ordered (kv_head, group) like qg.
        from shellac_tpu.ops.flash_attention import sink_rebase

        sk = sinks.astype(jnp.float32).reshape(1, 1, hkv, g, 1)
        r, l, _ = sink_rebase(m, l, sk)
        acc = acc * r
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l).reshape(b, s_loc, h, d)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    segments: Optional[jax.Array] = None,  # (B, S) packed document ids
    window: Optional[int] = None,
    softcap: Optional[float] = None,
    sinks: Optional[jax.Array] = None,
    axis_name: str = AXIS_SEQ,
) -> jax.Array:
    """Sequence-parallel attention. q (B,S,H,D); k,v (B,S,Hkv,D).

    S is globally sharded over `axis_name`; batch over dp/fsdp; heads
    over tp. Returns (B,S,H,D) with the same sharding as q. With
    `segments`, attention is block-diagonal over packed documents (the
    ids rotate around the ring with their kv chunk). With `window`,
    attention is banded on global positions (qpos - kpos < window).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    q_spec = P((AXIS_DATA, AXIS_FSDP), axis_name, AXIS_TENSOR, None)
    kv_spec = P((AXIS_DATA, AXIS_FSDP), axis_name, AXIS_TENSOR, None)
    seg_spec = P((AXIS_DATA, AXIS_FSDP), axis_name)
    # Sink logits shard with the heads (tp axis).
    sink_spec = P(AXIS_TENSOR)
    has_segments = segments is not None
    if not has_segments:
        segments = jnp.zeros(q.shape[:2], jnp.int32)
    has_sinks = sinks is not None
    if not has_sinks:
        sinks = jnp.zeros((q.shape[2],), jnp.float32)
    fn = shard_map(
        functools.partial(
            _ring_local, axis_name=axis_name, causal=causal,
            scale=float(scale), has_segments=has_segments, window=window,
            softcap=None if softcap is None else float(softcap),
            has_sinks=has_sinks,
        ),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, seg_spec, sink_spec),
        out_specs=q_spec,
        **_NO_CHECK,
    )
    return fn(q, k, v, segments, sinks)
