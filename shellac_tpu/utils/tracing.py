"""Profiling / tracing helpers around jax.profiler.

Traces are viewable in TensorBoard or Perfetto; `annotate` scopes show
up on the TPU timeline so step phases (data, step, checkpoint) are
attributable.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a profiler trace (TPU timeline + host) into log_dir."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Context manager labelling a region on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock step timing with warmup discard and EMA throughput.

    Synchronization is the caller's job (fetch a scalar from the step
    output before calling tick(); on some platforms block_until_ready
    does not synchronize).
    """

    def __init__(self, tokens_per_step: Optional[int] = None, warmup: int = 2,
                 histogram=None):
        self.tokens_per_step = tokens_per_step
        self.warmup = warmup
        self._count = 0
        self._last: Optional[float] = None
        self._ema: Optional[float] = None
        # Optional obs.Histogram: post-warmup step times are observed
        # into it, so the step-time DISTRIBUTION (not just the EMA)
        # reaches the shared registry / Prometheus exposition.
        self._hist = histogram

    def tick(self) -> Optional[float]:
        """Mark a step boundary; returns the step time (or None in warmup)."""
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return None
        dt = now - self._last
        self._last = now
        self._count += 1
        if self._count <= self.warmup:
            return None
        self._ema = dt if self._ema is None else 0.9 * self._ema + 0.1 * dt
        if self._hist is not None:
            self._hist.observe(dt)
        return dt

    @property
    def step_time(self) -> Optional[float]:
        return self._ema

    @property
    def tokens_per_sec(self) -> Optional[float]:
        if self._ema is None or not self.tokens_per_step:
            return None
        return self.tokens_per_step / self._ema
