"""Scalar metrics logging (JSONL file + stdout) and perf accounting.

`MetricsLogger` sits on top of the shellac_tpu.obs core: every scalar
it logs is also routed into the shared registry as a
`shellac_train_<name>` gauge (latest value), so train throughput/MFU
and serving latency share one Prometheus exposition path. The JSONL
file remains the durable per-step record; the registry is the live
scrape surface.
"""

from __future__ import annotations

import json
import re
import sys
import time
from typing import IO, Optional

import jax
import numpy as np

from shellac_tpu.obs import get_registry

# v5e bf16 peak; single source of truth for MFU across bench scripts.
TPU_V5E_BF16_PEAK_FLOPS = 197e12


def train_flops_per_token(n_params: int, n_layers: int, d_model: int, seq: int) -> int:
    """Rough model FLOPs per trained token: 6*params (fwd+bwd matmuls)
    plus the causal-attention term."""
    return 6 * n_params + 12 * n_layers * d_model * seq


def _to_python(tree):
    return jax.tree.map(
        lambda x: float(np.asarray(x)) if hasattr(x, "dtype") else x, tree
    )


def _metric_name(key: str) -> str:
    """A logged dict key as a Prometheus-safe metric name suffix."""
    return re.sub(r"[^a-zA-Z0-9_]", "_", key)


class MetricsLogger:
    """JSONL + stdout scalar logger, usable as a context manager so the
    file is closed (and flushed) even when the training loop raises:

        with MetricsLogger(path) as logger:
            logger.log(step, metrics)

    The legacy call pattern (construct, log, close) keeps working.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        stdout: bool = True,
        every: int = 1,
        registry=None,
        prefix: str = "shellac_train_",
    ):
        self._file: Optional[IO] = open(path, "a") if path else None
        self._stdout = stdout
        self._every = max(every, 1)
        self._registry = registry if registry is not None else get_registry()
        self._prefix = prefix
        self._gauges: dict = {}
        self._steps = self._registry.counter(
            f"{prefix}log_steps_total",
            "Training steps that reached the metrics logger",
        )

    def _route(self, record: dict) -> None:
        """Mirror the record's scalars into the shared registry as
        latest-value gauges (one exposition path with serving)."""
        if not self._registry.enabled:
            return
        self._steps.inc()
        for k, v in record.items():
            if k == "time" or not isinstance(v, (int, float)):
                continue
            gauge = self._gauges.get(k)
            if gauge is None:
                gauge = self._registry.gauge(
                    f"{self._prefix}{_metric_name(k)}",
                    f"Latest logged training scalar {k!r}",
                )
                self._gauges[k] = gauge
            gauge.set(float(v))

    def log(self, step: int, metrics: dict) -> None:
        if step % self._every:
            return
        record = {"step": int(step), "time": time.time(), **_to_python(metrics)}
        self._route(record)
        line = json.dumps(record)
        if self._file:
            self._file.write(line + "\n")
            self._file.flush()
        if self._stdout:
            shown = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
                if k != "time"
            )
            print(shown, file=sys.stderr)

    def close(self) -> None:
        if self._file:
            self._file.flush()
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
