"""Scalar metrics logging (JSONL file + stdout) and perf accounting."""

from __future__ import annotations

import json
import sys
import time
from typing import IO, Optional

import jax
import numpy as np

# v5e bf16 peak; single source of truth for MFU across bench scripts.
TPU_V5E_BF16_PEAK_FLOPS = 197e12


def train_flops_per_token(n_params: int, n_layers: int, d_model: int, seq: int) -> int:
    """Rough model FLOPs per trained token: 6*params (fwd+bwd matmuls)
    plus the causal-attention term."""
    return 6 * n_params + 12 * n_layers * d_model * seq


def _to_python(tree):
    return jax.tree.map(
        lambda x: float(np.asarray(x)) if hasattr(x, "dtype") else x, tree
    )


class MetricsLogger:
    def __init__(
        self,
        path: Optional[str] = None,
        *,
        stdout: bool = True,
        every: int = 1,
    ):
        self._file: Optional[IO] = open(path, "a") if path else None
        self._stdout = stdout
        self._every = max(every, 1)

    def log(self, step: int, metrics: dict) -> None:
        if step % self._every:
            return
        record = {"step": int(step), "time": time.time(), **_to_python(metrics)}
        line = json.dumps(record)
        if self._file:
            self._file.write(line + "\n")
            self._file.flush()
        if self._stdout:
            shown = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
                if k != "time"
            )
            print(shown, file=sys.stderr)

    def close(self) -> None:
        if self._file:
            self._file.close()
