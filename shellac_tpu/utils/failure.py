"""Failure detection and recovery hooks.

Four layers of defense, cheapest first:
  1. `guard_update` (inside the jitted step): if any gradient is
     non-finite, the parameter/optimizer update is skipped wholesale —
     one bad batch cannot poison the state. Costs one fused all-reduce
     of isfinite flags.
  2. Host-side loss-stream monitoring: the fit loop runs
     `training.resilience.AnomalySentinel` (non-finite + EMA-spike
     detection with warn/skip/rollback/fatal actions); the simpler
     `FailureDetector` here remains for custom loops that just want a
     tripwire over a scalar stream.
  3. `RestartBudget` (supervisor level): a sliding-window circuit
     breaker over in-process restarts — recover from isolated faults,
     but a component that keeps dying is declared fatal instead of
     crash-looping. Gates both the serving supervisor's engine
     rebuilds and the training sentinel's skip/rollback escalation.
     `CircuitBreaker` is the same sliding-window idea pointed OUTWARD:
     failures observed against a remote peer (a serving replica) trip
     it open, and a half-open probe readmits the peer once it proves
     healthy again — the serving tier keeps one per replica.
  4. `Heartbeat` (process level): a file touched every step; an
     external watchdog (or another host) treats a stale heartbeat as a
     hung/dead worker and can restart it. This is the single-host
     analogue of a multi-host liveness protocol over DCN. Both the
     training loop and the serving scheduler beat one.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp


def all_finite(tree) -> jax.Array:
    """Scalar bool: every element of every leaf is finite."""
    leaves = [jnp.all(jnp.isfinite(x)) for x in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        return jnp.array(True)
    return jnp.stack(leaves).all()


def guard_update(old_tree, new_tree, ok: jax.Array):
    """Select new_tree where ok else old_tree (jit-friendly)."""
    return jax.tree.map(
        lambda o, n: jnp.where(ok, n, o), old_tree, new_tree
    )


class FailureDetector:
    """Host-side monitor over scalar training metrics.

    A plain tripwire: feed it a loss stream, get a reason string when
    it looks broken. The fit loop itself uses the richer
    `training.resilience.AnomalySentinel` (configurable actions,
    budgeted escalation, multi-host verdict agreement); this stays for
    custom loops and external monitors that only need detection.
    """

    def __init__(
        self,
        *,
        patience: int = 3,
        loss_explosion_factor: float = 10.0,
        window: int = 50,
    ):
        self.patience = patience
        self.factor = loss_explosion_factor
        self.window = window
        self._bad_streak = 0
        self._history: list[float] = []

    def check(self, loss: float) -> Optional[str]:
        """Feed one loss value; returns a failure reason or None."""
        bad = None
        if not (loss == loss) or loss in (float("inf"), float("-inf")):
            bad = f"non-finite loss {loss}"
        elif self._history:
            ref = sum(self._history) / len(self._history)
            if loss > self.factor * max(ref, 1e-6):
                bad = f"loss explosion {loss:.4g} vs recent mean {ref:.4g}"
        if bad is None:
            self._bad_streak = 0
            self._history.append(loss)
            if len(self._history) > self.window:
                self._history.pop(0)
            return None
        self._bad_streak += 1
        if self._bad_streak >= self.patience:
            return bad
        return None

    def reset(self) -> None:
        self._bad_streak = 0
        self._history.clear()


class RestartBudget:
    """Sliding-window circuit breaker over restart attempts.

    `allow()` records one restart attempt and returns whether it is
    within budget: at most `max_restarts` attempts inside the trailing
    `window` seconds. A crash-looping component exhausts the budget and
    stays down (the caller declares it fatal) instead of burning the
    machine rebuilding state it will immediately wedge again; isolated
    faults spread further apart than the window recover forever.
    """

    def __init__(self, max_restarts: int, window: float = 300.0):
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        if window <= 0:
            raise ValueError("window must be > 0 seconds")
        self.max_restarts = max_restarts
        self.window = window
        self._attempts: list[float] = []

    def allow(self, now: Optional[float] = None) -> bool:
        """Record a restart attempt; True iff it fits the budget."""
        t = time.monotonic() if now is None else now
        cutoff = t - self.window
        self._attempts = [a for a in self._attempts if a > cutoff]
        if len(self._attempts) >= self.max_restarts:
            return False
        self._attempts.append(t)
        return True

    @property
    def used(self) -> int:
        """Attempts currently inside the window (stale ones age out at
        the next allow(); this is a monitoring read, not a gate)."""
        cutoff = time.monotonic() - self.window
        return sum(1 for a in self._attempts if a > cutoff)


class CircuitBreaker:
    """Per-peer circuit breaker: sliding-window trip, half-open probe.

    `RestartBudget` semantics turned outward. Record failures observed
    against one remote peer (health-check 503s, connect errors,
    timeouts); once `max_failures` land inside the trailing `window`
    seconds the breaker OPENS and the caller stops sending the peer
    work. After `cooldown` seconds open, exactly one caller is granted
    a HALF-OPEN probe (`allow_probe()`); the probe's outcome decides —
    `record_success()` closes the breaker (failure history cleared),
    another `record_failure()` re-opens it for a fresh cooldown.

    While CLOSED, successes do NOT clear the failure window — only
    window expiry forgives. The tier's health poller reports a
    success every sweep, and if that wiped the window, a replica
    whose /health answers 200 while its data path times out (handler
    exhaustion, a wedged accept loop) could never accumulate enough
    request-path failures to eject. A slow trickle of isolated blips
    still never trips: they age out of the window first.
    """

    def __init__(self, max_failures: int = 3, window: float = 30.0,
                 cooldown: float = 5.0):
        if max_failures < 1:
            raise ValueError("max_failures must be >= 1")
        if window <= 0 or cooldown <= 0:
            raise ValueError("window and cooldown must be > 0 seconds")
        self.max_failures = max_failures
        self.window = window
        self.cooldown = cooldown
        self._failures: list[float] = []
        self._opened_at: Optional[float] = None
        self._probing = False

    @property
    def state(self) -> str:
        """"closed" | "open" | "half_open" (probe in flight)."""
        if self._opened_at is None:
            return "closed"
        return "half_open" if self._probing else "open"

    def record_failure(self, now: Optional[float] = None) -> bool:
        """One observed failure; True iff the breaker is now open
        (including a failed half-open probe re-opening it)."""
        t = time.monotonic() if now is None else now
        if self._opened_at is not None:
            # Open or probing: any failure (re-)starts the cooldown.
            self._opened_at = t
            self._probing = False
            return True
        cutoff = t - self.window
        self._failures = [f for f in self._failures if f > cutoff]
        self._failures.append(t)
        if len(self._failures) >= self.max_failures:
            self._opened_at = t
            self._probing = False
            return True
        return False

    def record_success(self, now: Optional[float] = None) -> None:
        """A success while open/half-open (the probe passed) closes
        the breaker and clears the failure window — the readmitted
        peer starts fresh. A success while CLOSED is a no-op: routine
        health-poll passes must not erase data-path failures
        accumulating inside the window (see class docstring)."""
        del now
        if self._opened_at is not None:
            self._failures.clear()
            self._opened_at = None
            self._probing = False

    def allow_probe(self, now: Optional[float] = None) -> bool:
        """True once per cooldown: the breaker is open, the cooldown
        has elapsed, and no other probe is in flight — the caller may
        send ONE trial request and report its outcome."""
        if self._opened_at is None or self._probing:
            return False
        t = time.monotonic() if now is None else now
        if t - self._opened_at < self.cooldown:
            return False
        self._probing = True
        return True


def heartbeat_age(path: str) -> Optional[float]:
    """Seconds since the heartbeat file at `path` was last beaten, or
    None when the file is missing/corrupt (callers treat None as
    stale — a worker that never wrote its heartbeat is not live)."""
    try:
        with open(path) as f:
            return time.time() - json.load(f)["time"]
    except (FileNotFoundError, json.JSONDecodeError, KeyError):
        return None


class Heartbeat:
    """Liveness file for external watchdogs."""

    def __init__(self, path: str, *, process_index: Optional[int] = None):
        self.path = path
        self.process_index = (
            process_index if process_index is not None else jax.process_index()
        )
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def beat(self, step: int) -> None:
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"step": int(step), "time": time.time(),
                 "process": self.process_index}, f
            )
        os.replace(tmp, self.path)

    def age(self) -> Optional[float]:
        """Seconds since the last beat, or None if never beaten."""
        return heartbeat_age(self.path)

    @staticmethod
    def is_stale(path: str, timeout: float) -> bool:
        age = heartbeat_age(path)
        return age is None or age > timeout
