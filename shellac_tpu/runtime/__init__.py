"""Native (C++) runtime components: prefetching shard loader."""
