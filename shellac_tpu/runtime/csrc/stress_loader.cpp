// ThreadSanitizer stress driver for the native data loader.
//
// Compiled together with dataloader.cpp under -fsanitize=thread by
// tests/test_native_tsan.py. Exercises the racy surfaces on purpose:
//   - many producer threads against a shallow queue (condvar contention)
//   - teardown while producers are mid-batch (stop/join path)
//   - rapid open/start/consume/close cycles (lifetime races)
//
// Exits 0 on success; TSan reports (if any) land on stderr and fail
// the calling test.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <vector>

extern "C" {
void* stsh_open(uint64_t seed);
int stsh_add_shard(void* h, const char* path);
int stsh_start(void* h, int batch_size, int seq_len, int queue_depth,
               int n_threads);
int stsh_next(void* h, int32_t* inputs, int32_t* targets);
uint64_t stsh_total_tokens(void* h);
const char* stsh_last_error();
void stsh_close(void* h);
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s shard.bin [shard2.bin ...]\n", argv[0]);
    return 2;
  }
  const int batch = 4, seq = 64;
  std::vector<int32_t> inputs((size_t)batch * seq), targets((size_t)batch * seq);

  for (int cycle = 0; cycle < 30; ++cycle) {
    void* h = stsh_open(cycle);
    for (int i = 1; i < argc; ++i) {
      if (stsh_add_shard(h, argv[i])) {
        std::fprintf(stderr, "add_shard: %s\n", stsh_last_error());
        return 1;
      }
    }
    // Shallow queue + more threads than depth maximizes blocking on the
    // not_full condvar; odd cycles tear down while producers are stuck
    // there (the historic double-free / missed-wakeup spot).
    if (stsh_start(h, batch, seq, /*queue_depth=*/2, /*n_threads=*/4)) {
      std::fprintf(stderr, "start: %s\n", stsh_last_error());
      return 1;
    }
    const int consume = (cycle % 2 == 0) ? 8 : 1;
    for (int b = 0; b < consume; ++b) {
      if (stsh_next(h, inputs.data(), targets.data())) {
        std::fprintf(stderr, "next failed\n");
        return 1;
      }
      // Shifted-window invariant per row: targets advance inputs by one.
      for (int row = 0; row < batch; ++row) {
        const int32_t* in = &inputs[(size_t)row * seq];
        const int32_t* tg = &targets[(size_t)row * seq];
        for (int i = 0; i < seq - 1; ++i) {
          if (in[i + 1] != tg[i]) {
            std::fprintf(stderr, "window invariant broken row %d pos %d\n",
                         row, i);
            return 1;
          }
        }
      }
    }
    stsh_close(h);  // producers may be mid-batch or blocked right now
  }
  std::puts("stress ok");
  return 0;
}
