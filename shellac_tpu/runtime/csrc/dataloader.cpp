// Native data loader: mmap'd token shards -> prefetched training batches.
//
// The hot path of host-side data work is (1) page-cache reads of token
// windows and (2) the int32 copies into batch buffers. Python threads
// serialize on the GIL; this loader runs N worker threads that sample
// random windows from mmap'd shards and push ready batches into a
// bounded ring buffer, so the training loop's next() is a single
// condvar pop + memcpy, independent of Python.
//
// Shard format (matches shellac_tpu/training/data.py):
//   header: magic "STSH" (4 bytes) | u32 version (=1) | u64 num_tokens
//   payload: num_tokens little-endian int32
//
// C ABI for ctypes; no exceptions cross the boundary.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[4] = {'S', 'T', 'S', 'H'};
constexpr uint32_t kVersion = 1;
constexpr size_t kHeaderSize = 16;  // 4 magic + 4 version + 8 count

struct Shard {
  const int32_t* tokens = nullptr;  // into the mmap, past the header
  uint64_t num_tokens = 0;
  void* map_base = nullptr;
  size_t map_len = 0;
};

struct Batch {
  std::vector<int32_t> inputs;
  std::vector<int32_t> targets;
};

// xorshift128+ — fast, per-thread, deterministic from seed.
struct Rng {
  uint64_t s0, s1;
  explicit Rng(uint64_t seed) {
    s0 = seed ^ 0x9E3779B97F4A7C15ULL;
    s1 = (seed << 1) | 1;
    for (int i = 0; i < 8; ++i) next();
  }
  uint64_t next() {
    uint64_t x = s0, y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  uint64_t below(uint64_t n) { return next() % n; }
};

class Loader {
 public:
  Loader(uint64_t seed) : seed_(seed) {}

  ~Loader() { stop_and_join(); unmap_all(); }

  // Returns empty string on success, else an error message.
  std::string open_shard(const std::string& path) {
    int fd = ::open(path.c_str(), O_RDONLY);
    if (fd < 0) return "cannot open " + path;
    struct stat st;
    if (fstat(fd, &st) != 0) { ::close(fd); return "cannot stat " + path; }
    if ((size_t)st.st_size < kHeaderSize) {
      ::close(fd);
      return path + ": too small for header";
    }
    void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
    ::close(fd);
    if (base == MAP_FAILED) return "mmap failed for " + path;

    const unsigned char* p = static_cast<const unsigned char*>(base);
    if (memcmp(p, kMagic, 4) != 0) {
      munmap(base, st.st_size);
      return path + ": bad magic";
    }
    uint32_t version;
    uint64_t count;
    memcpy(&version, p + 4, 4);
    memcpy(&count, p + 8, 8);
    if (version != kVersion) {
      munmap(base, st.st_size);
      return path + ": unsupported version";
    }
    // Divide instead of multiplying: count * 4 can wrap uint64.
    if (count > ((uint64_t)st.st_size - kHeaderSize) / sizeof(int32_t)) {
      munmap(base, st.st_size);
      return path + ": truncated payload";
    }
    Shard sh;
    sh.tokens = reinterpret_cast<const int32_t*>(p + kHeaderSize);
    sh.num_tokens = count;
    sh.map_base = base;
    sh.map_len = st.st_size;
    shards_.push_back(sh);
    total_tokens_ += count;
    return "";
  }

  std::string start(int batch_size, int seq_len, int queue_depth,
                    int n_threads) {
    if (shards_.empty()) return "no shards opened";
    for (const Shard& s : shards_) {
      if (s.num_tokens < (uint64_t)seq_len + 1) {
        return "a shard is smaller than seq_len+1";
      }
    }
    batch_size_ = batch_size;
    seq_len_ = seq_len;
    depth_ = queue_depth > 0 ? queue_depth : 4;
    stop_.store(false);
    for (int i = 0; i < n_threads; ++i) {
      workers_.emplace_back([this, i] { worker(i); });
    }
    return "";
  }

  // Blocking; fills caller buffers of batch_size*seq_len each.
  bool next(int32_t* inputs, int32_t* targets) {
    Batch b;
    {
      std::unique_lock<std::mutex> lk(mu_);
      not_empty_.wait(lk, [this] { return !queue_.empty() || stop_.load(); });
      if (queue_.empty()) return false;
      b = std::move(queue_.front());
      queue_.pop_front();
    }
    not_full_.notify_one();
    memcpy(inputs, b.inputs.data(), b.inputs.size() * sizeof(int32_t));
    memcpy(targets, b.targets.data(), b.targets.size() * sizeof(int32_t));
    return true;
  }

  uint64_t total_tokens() const { return total_tokens_; }

 private:
  void worker(int tid) {
    Rng rng(seed_ * 0x5DEECE66DULL + tid + 1);
    const size_t n = (size_t)batch_size_ * seq_len_;
    while (!stop_.load()) {
      Batch b;
      b.inputs.resize(n);
      b.targets.resize(n);
      for (int row = 0; row < batch_size_; ++row) {
        // Sample a shard proportionally to its token count, then a
        // window within it.
        uint64_t pick = rng.below(total_tokens_);
        size_t si = 0;
        while (si + 1 < shards_.size() && pick >= shards_[si].num_tokens) {
          pick -= shards_[si].num_tokens;
          ++si;
        }
        const Shard& sh = shards_[si];
        // Valid starts: [0, num_tokens - seq_len - 1], i.e. num_tokens -
        // seq_len choices (start() guarantees num_tokens >= seq_len + 1,
        // so the bound is >= 1 and below() never sees 0).
        uint64_t start = rng.below(sh.num_tokens - seq_len_);
        const int32_t* w = sh.tokens + start;
        memcpy(&b.inputs[(size_t)row * seq_len_], w,
               seq_len_ * sizeof(int32_t));
        memcpy(&b.targets[(size_t)row * seq_len_], w + 1,
               seq_len_ * sizeof(int32_t));
      }
      {
        std::unique_lock<std::mutex> lk(mu_);
        not_full_.wait(lk, [this] {
          return queue_.size() < (size_t)depth_ || stop_.load();
        });
        if (stop_.load()) return;
        queue_.push_back(std::move(b));
      }
      not_empty_.notify_one();
    }
  }

  void stop_and_join() {
    {
      // The store must happen under mu_: a worker that has evaluated
      // the not_full_ predicate (stop_ false, queue full) but not yet
      // entered the wait queue would otherwise miss the notify and
      // sleep forever, hanging the join below.
      std::lock_guard<std::mutex> lk(mu_);
      stop_.store(true);
    }
    not_empty_.notify_all();
    not_full_.notify_all();
    for (auto& t : workers_) {
      if (t.joinable()) t.join();
    }
    workers_.clear();
  }

  void unmap_all() {
    for (Shard& s : shards_) munmap(s.map_base, s.map_len);
    shards_.clear();
  }

  uint64_t seed_;
  std::vector<Shard> shards_;
  uint64_t total_tokens_ = 0;
  int batch_size_ = 0, seq_len_ = 0, depth_ = 4;
  std::deque<Batch> queue_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::atomic<bool> stop_{false};
  std::vector<std::thread> workers_;
};

thread_local std::string g_error;

}  // namespace

extern "C" {

void* stsh_open(uint64_t seed) { return new Loader(seed); }

// Returns 0 on success; on failure sets the error retrievable below.
int stsh_add_shard(void* h, const char* path) {
  std::string err = static_cast<Loader*>(h)->open_shard(path);
  if (!err.empty()) { g_error = err; return 1; }
  return 0;
}

int stsh_start(void* h, int batch_size, int seq_len, int queue_depth,
               int n_threads) {
  std::string err = static_cast<Loader*>(h)->start(batch_size, seq_len,
                                                   queue_depth, n_threads);
  if (!err.empty()) { g_error = err; return 1; }
  return 0;
}

int stsh_next(void* h, int32_t* inputs, int32_t* targets) {
  return static_cast<Loader*>(h)->next(inputs, targets) ? 0 : 1;
}

uint64_t stsh_total_tokens(void* h) {
  return static_cast<Loader*>(h)->total_tokens();
}

const char* stsh_last_error() { return g_error.c_str(); }

void stsh_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
