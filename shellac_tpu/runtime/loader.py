"""ctypes bindings for the native (C++) data loader.

The shared library builds lazily on first use (one g++ invocation,
cached next to the sources); if the toolchain is unavailable the caller
(shellac_tpu/training/data.py) falls back to the pure-Python reader with
identical semantics.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libshellac_runtime.so")
_SRC = os.path.join(_DIR, "csrc", "dataloader.cpp")
_build_lock = threading.Lock()


def ensure_built() -> str:
    """Build the shared library if missing; returns its path."""
    with _build_lock:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
        cmd = [
            os.environ.get("CXX", "g++"), "-O2", "-std=c++17", "-fPIC",
            "-Wall", "-shared", "-pthread", "-o", _SO, _SRC,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = getattr(e, "stderr", str(e))
            raise OSError(f"native loader build failed: {detail}") from e
        return _SO


def _load_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(ensure_built())
    lib.stsh_open.restype = ctypes.c_void_p
    lib.stsh_open.argtypes = [ctypes.c_uint64]
    lib.stsh_add_shard.restype = ctypes.c_int
    lib.stsh_add_shard.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.stsh_start.restype = ctypes.c_int
    lib.stsh_start.argtypes = [ctypes.c_void_p] + [ctypes.c_int] * 4
    lib.stsh_next.restype = ctypes.c_int
    lib.stsh_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.stsh_total_tokens.restype = ctypes.c_uint64
    lib.stsh_total_tokens.argtypes = [ctypes.c_void_p]
    lib.stsh_last_error.restype = ctypes.c_char_p
    lib.stsh_close.argtypes = [ctypes.c_void_p]
    return lib


class NativeShardReader:
    """Prefetching reader over binary token shards (C++ backend)."""

    def __init__(self, paths: Sequence[str], *, seed: int = 0):
        if not paths:
            raise ValueError("no shard paths given")
        self._lib = _load_lib()
        self._h = self._lib.stsh_open(ctypes.c_uint64(seed))
        self._started = False
        try:
            for p in paths:
                if self._lib.stsh_add_shard(self._h, os.fsencode(p)):
                    raise ValueError(
                        self._lib.stsh_last_error().decode(errors="replace")
                    )
        except Exception:
            self.close()
            raise

    @property
    def total_tokens(self) -> int:
        return int(self._lib.stsh_total_tokens(self._h))

    def batches(
        self,
        *,
        batch_size: int,
        seq_len: int,
        num_batches: Optional[int] = None,
        queue_depth: int = 4,
        num_threads: int = 2,
    ) -> Iterator[dict]:
        if self._h is None:
            raise RuntimeError("reader is closed")
        if self._started:
            raise RuntimeError("batches() may only be called once per reader")
        if self._lib.stsh_start(
            self._h, batch_size, seq_len, queue_depth, num_threads
        ):
            raise ValueError(
                self._lib.stsh_last_error().decode(errors="replace")
            )
        self._started = True
        produced = 0
        try:
            while num_batches is None or produced < num_batches:
                inputs = np.empty((batch_size, seq_len), np.int32)
                targets = np.empty((batch_size, seq_len), np.int32)
                rc = self._lib.stsh_next(
                    self._h,
                    inputs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    targets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                )
                if rc:
                    return
                yield {"inputs": inputs, "targets": targets}
                produced += 1
        finally:
            self.close()

    def close(self) -> None:
        if getattr(self, "_h", None) is not None:
            self._lib.stsh_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
