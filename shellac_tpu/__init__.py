"""shellac_tpu — a TPU-native training & inference framework.

Compute path: JAX/XLA with Pallas TPU kernels for the hot ops.
Parallelism: GSPMD over a named device mesh (dp/fsdp/pp/sp/tp) — XLA
inserts the collectives; ring attention rides ICI for long context.

The reference project this repo was allocated against (kmacrow/Shellac,
mounted at /root/reference) is empty — see SURVEY.md §0 — so this is an
original design with no upstream file:line citations.
"""

from shellac_tpu.version import __version__
from shellac_tpu.config import (
    ModelConfig,
    MoEConfig,
    ParallelConfig,
    TrainConfig,
)
from shellac_tpu.models.registry import PRESETS, get_model_config
from shellac_tpu.parallel.mesh import make_mesh

__all__ = [
    "__version__",
    "ModelConfig",
    "MoEConfig",
    "ParallelConfig",
    "TrainConfig",
    "PRESETS",
    "get_model_config",
    "make_mesh",
]
