"""Fault-tolerant training: the anomaly sentinel and its rollback contract.

PR 2's serving supervisor keeps a *process* alive; this module keeps a
multi-day *training run* alive. The fit loop feeds every log-boundary
loss (and grad norm) to an `AnomalySentinel`, which detects

  - non-finite loss (NaN/Inf reached the host-visible metric),
  - non-finite gradients (a non-finite `grad_norm` — the in-jit
    `guard_update` already kept the bad update out of the state, but
    the run still needs a verdict), and
  - loss spikes: loss above `spike_factor` times a warmed-up
    exponential moving average of the healthy loss stream,

and resolves a configurable action per anomaly:

  warn      log + count it, keep training (EMA is never polluted by
            anomalous losses, so detection stays armed).
  skip      tolerate the step, but draw one token from the rollback
            budget — a stream of anomalies escalates to fatal.
  rollback  restore the last-good checkpoint (`Checkpointer.restore`
            with `fallback=True`, so a corrupt latest step is walked
            past and quarantined), re-derive the data-iterator skip
            from the restored step, and resume. Also budgeted.
  fatal     raise immediately.

Escalation reuses `utils.failure.RestartBudget`: each skip/rollback
records one attempt, and once the sliding-window budget is spent the
resolved action becomes `fatal` — a poisoned run (bad shard, LR spike
that recurs at the same step every replay) terminates loudly instead of
loop-rolling forever.

Multi-host: detection (`detect`) is split from action resolution
(`flag`) so the fit loop can agree on the verdict across hosts at the
log-boundary sync point — the same allgather pattern as preemption
agreement — and hosts never diverge on whether to roll back.

Every event lands in the shared obs registry as `shellac_train_*`
series (see docs/observability.md for the catalog).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

from shellac_tpu.obs.train import ResilienceMetrics  # noqa: F401 — re-export
from shellac_tpu.utils.failure import RestartBudget

ACTIONS = ("warn", "skip", "rollback", "fatal")


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One agreed training anomaly with its resolved action."""

    step: int
    kind: str  # nonfinite_loss | nonfinite_grad | loss_spike | peer
    detail: str
    action: str  # one of ACTIONS; escalation may turn skip/rollback fatal

    def __str__(self) -> str:
        return f"{self.kind} at step {self.step} ({self.detail})"


def _nonfinite(x: float) -> bool:
    try:
        return not math.isfinite(float(x))
    except (TypeError, ValueError):
        return True


class AnomalySentinel:
    """Host-side anomaly verdict over the training loss stream.

    `observe(step, loss, grad_norm)` is the single-host entry point:
    it runs detection and, if an anomaly (sustained for `patience`
    consecutive observations) is found, resolves and records it.
    Multi-host loops call `detect` first, agree on `bool(pending)`
    across hosts, then call `flag` with the agreed verdict.
    """

    def __init__(
        self,
        *,
        action: str = "rollback",
        patience: int = 1,
        spike_factor: float = 10.0,
        ema_decay: float = 0.98,
        warmup: int = 5,
        budget: Optional[RestartBudget] = None,
        registry=None,
    ):
        if action not in ACTIONS:
            raise ValueError(f"action must be one of {ACTIONS}, got {action!r}")
        if spike_factor <= 1.0:
            raise ValueError("spike_factor must be > 1")
        if not 0.0 < ema_decay < 1.0:
            raise ValueError("ema_decay must be in (0, 1)")
        self.action = action
        self.patience = max(1, patience)
        self.spike_factor = spike_factor
        self.ema_decay = ema_decay
        self.warmup = max(0, warmup)
        # Default budget: a handful of recoveries per hour. Anomalies
        # spread wider than the window recover forever; a tight loop of
        # them (poisoned data, a replay that re-diverges at the same
        # step) exhausts it and goes fatal.
        self.budget = budget if budget is not None else RestartBudget(
            2, window=3600.0
        )
        self.metrics = ResilienceMetrics(registry)
        self._ema: Optional[float] = None
        self._healthy = 0
        self._streak = 0

    @property
    def loss_ema(self) -> Optional[float]:
        return self._ema

    def detect(
        self, step: int, loss: float, grad_norm: Optional[float] = None
    ) -> Optional[Tuple[str, str]]:
        """Detection only: (kind, detail) or None. Deterministic given
        the same inputs, never consumes budget, never emits metrics —
        safe to run independently on every host before agreement."""
        kind = detail = None
        if _nonfinite(loss):
            kind, detail = "nonfinite_loss", f"loss={loss}"
        elif grad_norm is not None and _nonfinite(grad_norm):
            kind, detail = "nonfinite_grad", f"grad_norm={grad_norm}"
        elif (
            self._ema is not None
            and self._healthy >= self.warmup
            and loss > self.spike_factor * max(self._ema, 1e-6)
        ):
            kind = "loss_spike"
            detail = (
                f"loss {loss:.4g} > {self.spike_factor:g}x EMA "
                f"{self._ema:.4g}"
            )
        if kind is None:
            self._streak = 0
            self._healthy += 1
            d = self.ema_decay
            self._ema = loss if self._ema is None else d * self._ema + (
                1.0 - d
            ) * loss
            return None
        # Anomalous losses never fold into the EMA — a slow ramp of
        # bad values must not drag the reference up until the detector
        # goes blind.
        self._streak += 1
        if self._streak < self.patience:
            return None
        return kind, detail

    def flag(self, step: int, kind: str, detail: str,
             record: bool = True) -> Anomaly:
        """Record an (agreed) anomaly and resolve its action. Skip and
        rollback draw from the budget; once spent, they escalate to
        fatal. Multi-host loops pass record=False and call `record`
        themselves AFTER the cross-host severity agreement, so the
        counter's action label is the action actually taken."""
        action = self.action
        if action in ("skip", "rollback") and not self.budget.allow():
            detail = f"{detail}; recovery budget spent"
            action = "fatal"
        self._streak = 0
        anomaly = Anomaly(step=step, kind=kind, detail=detail, action=action)
        if record:
            self.record(anomaly)
        return anomaly

    def record(self, anomaly: Anomaly) -> None:
        """Emit the anomaly counter with its final resolved action."""
        self.metrics.anomalies.labels(
            kind=anomaly.kind, action=anomaly.action
        ).inc()

    def observe(
        self, step: int, loss: float, grad_norm: Optional[float] = None
    ) -> Optional[Anomaly]:
        """Single-host convenience: detect, then flag on detection."""
        pending = self.detect(step, loss, grad_norm)
        if pending is None:
            return None
        return self.flag(step, *pending)

    def reset(self) -> None:
        """Clear detection state (after a rollback the loss stream
        restarts from the restored step). The budget is NOT reset —
        escalation must survive rollbacks or it could never trip."""
        self._ema = None
        self._healthy = 0
        self._streak = 0
