"""Optimizer and LR schedule construction (optax).

Optimizer state pytrees mirror the parameter pytree, so the same logical
axis rules shard first/second moments ZeRO-style for free.
"""

from __future__ import annotations

import jax
import optax

from shellac_tpu.config import TrainConfig, resolve_dtype


def make_schedule(cfg: TrainConfig) -> optax.Schedule:
    """Linear warmup then cosine decay to min_lr_ratio * peak."""
    warmup = optax.linear_schedule(
        init_value=0.0, end_value=cfg.learning_rate,
        transition_steps=max(cfg.warmup_steps, 1),
    )
    decay = optax.cosine_decay_schedule(
        init_value=cfg.learning_rate,
        decay_steps=max(cfg.total_steps - cfg.warmup_steps, 1),
        alpha=cfg.min_lr_ratio,
    )
    return optax.join_schedules([warmup, decay], [cfg.warmup_steps])


def _decay_mask(params):
    """Weight decay only on matrices; norm scales and biases exempt.

    Stacked per-layer norm scales have shape (n_layers, d), so ndim alone
    cannot distinguish them — exempt anything whose path names a norm.
    """
    def mask(path, p):
        names = [str(getattr(e, "key", e)) for e in path]
        if any("norm" in n for n in names):
            return False
        return p.ndim >= 2

    return jax.tree_util.tree_map_with_path(mask, params)


def _muon_mask(params):
    """muon for the stacked matrix parameters, adamw for the rest.

    Stacking makes the rule crisp: per-layer matrices are ndim >= 3
    ((L, in, out) / (L, E, in, out)), while norms/biases stack to (L, d)
    and the embedding/lm_head are plain 2D — all excluded, matching the
    Muon recipe (embeddings and head stay on adamw).
    """
    def label(path, p):
        return "muon" if getattr(p, "ndim", 0) >= 3 else "adamw"

    return jax.tree_util.tree_map_with_path(label, params)


def _muon_dims(params):
    """MuonDimensionNumbers per parameter: which axes form the matrix.

    Stacked layouts orthogonalize the trailing two dims with everything
    leading as vmapped batch axes — (L, in, out) and expert
    (L, E, in, out) both fall out of `ndim-2 / ndim-1`. MLA's
    wkv_b_k/wkv_b_v (L, kv_rank, heads, dh) are special: the REAL
    matrix is kv_rank -> heads*dh, so the output axis is the (heads,
    dh) pair, not the trailing dim alone.
    """
    from optax.contrib import MuonDimensionNumbers

    def dims(path, p):
        if getattr(p, "ndim", 0) < 3:
            return None  # adamw-labelled; never reaches the muon branch
        names = [str(getattr(e, "key", e)) for e in path]
        if any(n in ("wkv_b_k", "wkv_b_v") for n in names):
            return MuonDimensionNumbers(reduction_axis=1, output_axis=(2, 3))
        return MuonDimensionNumbers(
            reduction_axis=p.ndim - 2, output_axis=p.ndim - 1
        )

    return jax.tree_util.tree_map_with_path(dims, params)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """adamw (default), lion, adafactor, or muon, per cfg.optimizer.

    All share the clip → scale → decoupled weight decay → schedule
    chain, so state sharding and the train step are optimizer-agnostic.
    adafactor's factored second moment cuts optimizer HBM from 2x params
    to ~1x (+ O(rows+cols)); lion keeps only a bf16 momentum; muon
    orthogonalizes momentum for the stacked matrices (b1 is its
    momentum) with adamw handling embeddings/head/norms.
    """
    if cfg.optimizer == "adamw":
        scaler = optax.scale_by_adam(
            b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
            mu_dtype=resolve_dtype(cfg.mu_dtype),
        )
    elif cfg.optimizer == "lion":
        scaler = optax.scale_by_lion(
            b1=cfg.b1, b2=cfg.b2, mu_dtype=resolve_dtype(cfg.mu_dtype)
        )
    elif cfg.optimizer == "adafactor":
        scaler = optax.scale_by_factored_rms(decay_rate=cfg.b2)
    elif cfg.optimizer == "muon":
        # optax.contrib's Muon: EMA momentum + quintic Newton-Schulz
        # orthogonalization + sqrt(max(1, m/n)) shape factor, with
        # dimension numbers vmapping our stacked layer/expert axes.
        # b1 is the momentum; embeddings/head/norms ride adamw.
        from optax.contrib import scale_by_muon

        scaler = optax.multi_transform(
            {
                "muon": scale_by_muon(
                    beta=cfg.b1,
                    mu_dtype=resolve_dtype(cfg.mu_dtype),
                    nesterov=True,
                    weight_dimension_numbers=_muon_dims,
                ),
                "adamw": optax.scale_by_adam(
                    b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                    mu_dtype=resolve_dtype(cfg.mu_dtype),
                ),
            },
            _muon_mask,
        )
    else:
        raise ValueError(
            f"unknown optimizer {cfg.optimizer!r}; "
            "have adamw, lion, adafactor, muon"
        )
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        scaler,
        optax.add_decayed_weights(cfg.weight_decay, mask=_decay_mask),
        optax.scale_by_learning_rate(make_schedule(cfg)),
    )
