"""Optimizer and LR schedule construction (optax).

Optimizer state pytrees mirror the parameter pytree, so the same logical
axis rules shard first/second moments ZeRO-style for free.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from shellac_tpu.config import TrainConfig, resolve_dtype


def make_schedule(cfg: TrainConfig) -> optax.Schedule:
    """Linear warmup then cosine decay to min_lr_ratio * peak."""
    warmup = optax.linear_schedule(
        init_value=0.0, end_value=cfg.learning_rate,
        transition_steps=max(cfg.warmup_steps, 1),
    )
    decay = optax.cosine_decay_schedule(
        init_value=cfg.learning_rate,
        decay_steps=max(cfg.total_steps - cfg.warmup_steps, 1),
        alpha=cfg.min_lr_ratio,
    )
    return optax.join_schedules([warmup, decay], [cfg.warmup_steps])


def _decay_mask(params):
    """Weight decay only on matrices; norm scales and biases exempt.

    Stacked per-layer norm scales have shape (n_layers, d), so ndim alone
    cannot distinguish them — exempt anything whose path names a norm.
    """
    import jax

    def mask(path, p):
        names = [str(getattr(e, "key", e)) for e in path]
        if any("norm" in n for n in names):
            return False
        return p.ndim >= 2

    return jax.tree_util.tree_map_with_path(mask, params)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """adamw (default), lion, or adafactor, per cfg.optimizer.

    All share the clip → scale → decoupled weight decay → schedule
    chain, so state sharding and the train step are optimizer-agnostic.
    adafactor's factored second moment cuts optimizer HBM from 2x params
    to ~1x (+ O(rows+cols)); lion keeps only a bf16 momentum.
    """
    if cfg.optimizer == "adamw":
        scaler = optax.scale_by_adam(
            b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
            mu_dtype=resolve_dtype(cfg.mu_dtype),
        )
    elif cfg.optimizer == "lion":
        scaler = optax.scale_by_lion(
            b1=cfg.b1, b2=cfg.b2, mu_dtype=resolve_dtype(cfg.mu_dtype)
        )
    elif cfg.optimizer == "adafactor":
        scaler = optax.scale_by_factored_rms(decay_rate=cfg.b2)
    else:
        raise ValueError(
            f"unknown optimizer {cfg.optimizer!r}; "
            "have adamw, lion, adafactor"
        )
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        scaler,
        optax.add_decayed_weights(cfg.weight_decay, mask=_decay_mask),
        optax.scale_by_learning_rate(make_schedule(cfg)),
    )
