"""Loss functions (fp32 throughout)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy(
    logits: jax.Array,  # (..., V) fp32
    targets: jax.Array,  # (...) int32
    mask: Optional[jax.Array] = None,  # (...) 0/1
    z_loss_weight: float = 0.0,
) -> Tuple[jax.Array, dict]:
    """Mean token cross-entropy with optional z-loss.

    z-loss (sum log Z squared) keeps the softmax normalizer from drifting
    in bf16 training; weight 0 disables it with no extra compute cost
    after DCE.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    nll = logz - true_logit
    if z_loss_weight:
        nll = nll + z_loss_weight * jnp.square(logz)
    if mask is None:
        denom = jnp.array(nll.size, jnp.float32)
        total = jnp.sum(nll)
    else:
        mask = mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        total = jnp.sum(nll * mask)
    loss = total / denom
    metrics = {
        "loss": loss,
        "perplexity": jnp.exp(jnp.clip(loss, max=30.0)),
        "tokens": denom,
    }
    return loss, metrics


def mlm_mask_tokens(
    key: jax.Array,
    tokens: jax.Array,  # (B, S) int32
    *,
    mask_id: int,
    vocab_size: int,
    mask_prob: float = 0.15,
    random_frac: float = 0.1,
    keep_frac: float = 0.1,
) -> Tuple[jax.Array, jax.Array]:
    """BERT-style corruption for masked-LM training (encoder family).

    Selects mask_prob of positions; of those, 80% become mask_id, 10%
    a random token, 10% stay unchanged. Returns (corrupted_tokens,
    loss_mask) — pair with cross_entropy(logits, tokens, loss_mask) on
    a cfg.causal=False model.
    """
    k_sel, k_kind, k_rand = jax.random.split(key, 3)
    selected = jax.random.uniform(k_sel, tokens.shape) < mask_prob
    kind = jax.random.uniform(k_kind, tokens.shape)
    random_tok = jax.random.randint(
        k_rand, tokens.shape, 0, vocab_size, jnp.int32
    )
    corrupted = jnp.where(kind < 1.0 - random_frac - keep_frac,
                          mask_id, tokens)
    corrupted = jnp.where(
        (kind >= 1.0 - random_frac - keep_frac)
        & (kind < 1.0 - keep_frac),
        random_tok, corrupted,
    )
    corrupted = jnp.where(selected, corrupted, tokens)
    return corrupted.astype(jnp.int32), selected.astype(jnp.float32)
