"""Loss functions (fp32 throughout)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy(
    logits: jax.Array,  # (..., V) fp32
    targets: jax.Array,  # (...) int32
    mask: Optional[jax.Array] = None,  # (...) 0/1
    z_loss_weight: float = 0.0,
) -> Tuple[jax.Array, dict]:
    """Mean token cross-entropy with optional z-loss.

    z-loss (sum log Z squared) keeps the softmax normalizer from drifting
    in bf16 training; weight 0 disables it with no extra compute cost
    after DCE.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    nll = logz - true_logit
    if z_loss_weight:
        nll = nll + z_loss_weight * jnp.square(logz)
    if mask is None:
        denom = jnp.array(nll.size, jnp.float32)
        total = jnp.sum(nll)
    else:
        mask = mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        total = jnp.sum(nll * mask)
    loss = total / denom
    metrics = {
        "loss": loss,
        "perplexity": jnp.exp(jnp.clip(loss, max=30.0)),
        "tokens": denom,
    }
    return loss, metrics


def mlm_mask_tokens(
    key: jax.Array,
    tokens: jax.Array,  # (B, S) int32
    *,
    mask_id: int,
    vocab_size: int,
    mask_prob: float = 0.15,
    random_frac: float = 0.1,
    keep_frac: float = 0.1,
) -> Tuple[jax.Array, jax.Array]:
    """BERT-style corruption for masked-LM training (encoder family).

    Selects mask_prob of positions; of those, 80% become mask_id, 10%
    a random token, 10% stay unchanged. Returns (corrupted_tokens,
    loss_mask) — pair with cross_entropy(logits, tokens, loss_mask) on
    a cfg.causal=False model.
    """
    k_sel, k_kind, k_rand = jax.random.split(key, 3)
    selected = jax.random.uniform(k_sel, tokens.shape) < mask_prob
    kind = jax.random.uniform(k_kind, tokens.shape)
    random_tok = jax.random.randint(
        k_rand, tokens.shape, 0, vocab_size, jnp.int32
    )
    corrupted = jnp.where(kind < 1.0 - random_frac - keep_frac,
                          mask_id, tokens)
    corrupted = jnp.where(
        (kind >= 1.0 - random_frac - keep_frac)
        & (kind < 1.0 - keep_frac),
        random_tok, corrupted,
    )
    corrupted = jnp.where(selected, corrupted, tokens)
    return corrupted.astype(jnp.int32), selected.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Fused (vocab-chunked) cross-entropy
# ---------------------------------------------------------------------------


def _fused_fwd_impl(x, w, targets, vocab_chunk):
    """Online-softmax over vocab chunks; never materializes (N, V).

    x: (N, D) compute dtype; w: (D, V); targets: (N,) int32.
    Returns (nll, lse): nll_i = lse_i - logit_{t_i}.
    """
    d, v = w.shape
    n = x.shape[0]
    nc = v // vocab_chunk
    wr = w.reshape(d, nc, vocab_chunk).transpose(1, 0, 2)  # (nc, D, chunk)

    def body(carry, inp):
        m, s, tgt = carry
        ci, wc = inp
        logits = jax.lax.dot_general(
            x, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (N, chunk)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        idx = targets - ci * vocab_chunk
        in_range = (idx >= 0) & (idx < vocab_chunk)
        got = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, vocab_chunk - 1)[:, None], axis=1
        )[:, 0]
        tgt = jnp.where(in_range, got, tgt)
        return (m_new, s, tgt), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    t0 = jnp.zeros((n,), jnp.float32)
    (m, s, tgt), _ = jax.lax.scan(body, (m0, s0, t0), (jnp.arange(nc), wr))
    lse = m + jnp.log(s)
    return lse - tgt, lse


_FUSED_CACHE = {}


def _fused_for_chunk(vocab_chunk: int):
    """A custom_vjp instance specialized to one (static) chunk size.

    Returns f(x, w, targets) -> (nll, lse); the backward recomputes the
    chunk logits from the saved lse rows instead of keeping (N, V)
    probabilities: dlogits_c = a*p_c - b*onehot_c with a = g_nll+g_lse,
    b = g_nll.
    """
    if vocab_chunk in _FUSED_CACHE:
        return _FUSED_CACHE[vocab_chunk]

    @jax.custom_vjp
    def f(x, w, targets):
        return _fused_fwd_impl(x, w, targets, vocab_chunk)

    def fwd(x, w, targets):
        nll, lse = _fused_fwd_impl(x, w, targets, vocab_chunk)
        return (nll, lse), (x, w, targets, lse)

    def bwd(res, g):
        x, w, targets, lse = res
        g_nll, g_lse = g
        d, v = w.shape
        nc = v // vocab_chunk
        wr = w.reshape(d, nc, vocab_chunk).transpose(1, 0, 2)
        a = (g_nll + g_lse).astype(jnp.float32)
        b = g_nll.astype(jnp.float32)

        def body(dx, inp):
            ci, wc = inp
            logits = jax.lax.dot_general(
                x, wc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            p = jnp.exp(logits - lse[:, None])
            idx = targets - ci * vocab_chunk
            cols = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
            onehot = cols == idx[:, None]
            dlog = (a[:, None] * p - jnp.where(onehot, b[:, None], 0.0))
            dlog = dlog.astype(x.dtype)
            dx = dx + jax.lax.dot_general(
                dlog, wc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dwc = jax.lax.dot_general(
                x, dlog, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (D, chunk)
            return dx, dwc

        dx0 = jnp.zeros(x.shape, jnp.float32)
        dx, dws = jax.lax.scan(body, dx0, (jnp.arange(nc), wr))
        dw = dws.transpose(1, 0, 2).reshape(d, v)
        return dx.astype(x.dtype), dw.astype(w.dtype), None

    f.defvjp(fwd, bwd)
    _FUSED_CACHE[vocab_chunk] = f
    return f


def fused_cross_entropy(
    hidden: jax.Array,  # (..., D) compute dtype — post-final-norm
    w_out: jax.Array,  # (D, V)
    targets: jax.Array,  # (...) int32
    mask: Optional[jax.Array] = None,
    z_loss_weight: float = 0.0,
    vocab_chunk: int = 2048,
) -> Tuple[jax.Array, dict]:
    """cross_entropy without materializing the (N, V) logits.

    The lm-head matmul, log-softmax, and target gather run chunked over
    the vocab with an online logsumexp (forward) and a recomputing
    backward — the full fp32 logits tensor (the largest single residual
    of the train step: batch*seq*V*4 bytes) never exists. Numerics
    match `cross_entropy` to fp32 tolerance (tested, incl. grads).

    V must divide by vocab_chunk; callers fall back to the unfused path
    otherwise. Not meaningful at decode time (S=1).
    """
    d = hidden.shape[-1]
    v = w_out.shape[-1]
    if v % vocab_chunk:
        raise ValueError(f"vocab {v} not divisible by chunk {vocab_chunk}")
    lead = hidden.shape[:-1]
    x = hidden.reshape(-1, d)
    t = targets.reshape(-1).astype(jnp.int32)
    nll, lse = _fused_for_chunk(vocab_chunk)(x, w_out, t)
    nll = nll.reshape(lead)
    lse = lse.reshape(lead)
    if z_loss_weight:
        nll = nll + z_loss_weight * jnp.square(lse)
    if mask is None:
        denom = jnp.array(nll.size, jnp.float32)
        total = jnp.sum(nll)
    else:
        mask = mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        total = jnp.sum(nll * mask)
    loss = total / denom
    metrics = {
        "loss": loss,
        "perplexity": jnp.exp(jnp.clip(loss, max=30.0)),
        "tokens": denom,
    }
    return loss, metrics
