"""Loss functions (fp32 throughout)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy(
    logits: jax.Array,  # (..., V) fp32
    targets: jax.Array,  # (...) int32
    mask: Optional[jax.Array] = None,  # (...) 0/1
    z_loss_weight: float = 0.0,
) -> Tuple[jax.Array, dict]:
    """Mean token cross-entropy with optional z-loss.

    z-loss (sum log Z squared) keeps the softmax normalizer from drifting
    in bf16 training; weight 0 disables it with no extra compute cost
    after DCE.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    nll = logz - true_logit
    if z_loss_weight:
        nll = nll + z_loss_weight * jnp.square(logz)
    if mask is None:
        denom = jnp.array(nll.size, jnp.float32)
        total = jnp.sum(nll)
    else:
        mask = mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        total = jnp.sum(nll * mask)
    loss = total / denom
    metrics = {
        "loss": loss,
        "perplexity": jnp.exp(jnp.clip(loss, max=30.0)),
        "tokens": denom,
    }
    return loss, metrics
