"""Train-loop chaos harness: deterministic fault injection.

Drives the end-to-end chaos tests (tests/test_fault_injection.py) and
doubles as a drill kit against a real run directory: every injector
reproduces a failure long training jobs actually hit —

  - `poison_batches`: one batch's loss goes NaN at a chosen step (a
    bad shard row, a bf16 overflow) — exercises the in-jit update
    guard plus the sentinel's rollback path;
  - `truncate_step` / `scramble_step` / `drop_item`: a checkpoint step
    is partially written or bit-rotted on disk — exercises
    `Checkpointer.verify` and the fallback-restore walk;
  - `fake_interrupted_save`: the debris a kill mid-save leaves behind
    (an uncommitted orbax tmp directory) — exercises the startup
    sweep that keeps it from ever being restored as "latest".

Injectors only touch the filesystem / the data stream; none of them
reach into engine or loop internals, so what the chaos tests prove is
the public failure contract (docs/training.md, "Failure semantics").
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Iterator

import numpy as np

from shellac_tpu.training.checkpoint import TMP_DIR_MARKER


def poison_batches(
    it: Iterator[dict], *, at_step: int, start_step: int = 0
) -> Iterator[dict]:
    """Yield from `it`, NaN-poisoning the batch consumed by training
    step `at_step` (1-indexed, matching the loop's step counter).

    The poison rides the loss mask (added if absent), so inputs stay
    valid token ids but the step's loss and gradients go non-finite —
    the realistic shape of a corrupt shard row. Each wrapper poisons
    its step at most once, so which SCENARIO you get is decided by who
    builds iterators: wrap only the initial iterator and a rollback's
    rebuilt stream is clean (transient fault); wrap inside the
    `data_factory` and every replay re-poisons (poisoned corpus, which
    must escalate to fatal).

    `start_step` is the step count already consumed before `it` begins
    (a resumed/rolled-back iterator built with `skip=start_step`), so
    `at_step` always addresses the same global training step.
    """
    if at_step < 1:
        raise ValueError("at_step is a 1-indexed training step")
    for i, batch in enumerate(it, start=start_step + 1):
        if i == at_step:
            batch = dict(batch)
            mask = batch.get("mask")
            shape = np.asarray(batch["inputs"]).shape
            if mask is None:
                mask = np.ones(shape, np.float32)
            batch["mask"] = np.asarray(mask, np.float32).copy()
            batch["mask"][...] = np.nan
        yield batch


def _step_dir(directory: str, step: int) -> str:
    d = os.path.join(os.path.abspath(directory), str(step))
    if not os.path.isdir(d):
        raise FileNotFoundError(f"no checkpoint step directory {d}")
    return d


def _payload_files(step_dir: str, min_bytes: int) -> list:
    out = []
    for root, _, files in os.walk(step_dir):
        for name in files:
            p = os.path.join(root, name)
            if os.path.getsize(p) >= min_bytes:
                out.append(p)
    if not out:
        raise FileNotFoundError(
            f"no files >= {min_bytes} bytes under {step_dir} to corrupt"
        )
    return sorted(out)


def truncate_step(directory: str, step: int, *, min_bytes: int = 64) -> int:
    """Truncate every sizable file of a saved step to half its length —
    the on-disk shape of a write that died partway. Returns the number
    of files damaged."""
    files = _payload_files(_step_dir(directory, step), min_bytes)
    for p in files:
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
    return len(files)


def scramble_step(directory: str, step: int, *, min_bytes: int = 64,
                  seed: int = 0) -> int:
    """Overwrite every sizable file of a saved step with deterministic
    garbage of the same length — bit-rot / torn-write corruption that
    preserves file sizes. Returns the number of files damaged."""
    rng = np.random.default_rng(seed)
    files = _payload_files(_step_dir(directory, step), min_bytes)
    for p in files:
        n = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.write(rng.integers(0, 256, size=n, dtype=np.uint8).tobytes())
    return len(files)


def drop_item(directory: str, step: int, item: str = "default") -> None:
    """Delete a step's item payload wholesale — structural corruption:
    the step directory exists (and is selected by `latest_step`) but
    holds nothing restorable."""
    d = os.path.join(_step_dir(directory, step), item)
    if not os.path.isdir(d):
        raise FileNotFoundError(f"step {step} has no item dir {d}")
    shutil.rmtree(d)


def fake_interrupted_save(directory: str, step: int,
                          age_s: float = 2 * 3600.0) -> str:
    """Fabricate the debris a kill mid-save leaves behind: an
    uncommitted orbax tmp directory for `step` (atomic-rename commit
    means a real mid-save kill leaves exactly this), backdated by
    `age_s` so it reads as ABANDONED — the startup sweep deliberately
    leaves young tmp dirs alone, since those may be another process's
    live async save. Returns the debris path;
    `Checkpointer.__init__`'s sweep must remove it."""
    root = os.path.abspath(directory)
    os.makedirs(root, exist_ok=True)
    debris = os.path.join(root, f"{step}{TMP_DIR_MARKER}1234567890")
    os.makedirs(os.path.join(debris, "default"), exist_ok=True)
    with open(os.path.join(debris, "default", "_METADATA"), "w") as f:
        f.write("{")  # truncated on purpose
    old = time.time() - age_s
    os.utime(debris, (old, old))
    return debris


def tamper_manifest(directory: str, step: int, **overrides) -> str:
    """Rewrite fields of a step's integrity manifest (e.g.
    `leaf_count=999`) so `Checkpointer.verify` must reject the step
    even though the orbax payload itself is intact. Returns the
    manifest path."""
    path = os.path.join(
        os.path.abspath(directory), "manifests", f"{step}.json"
    )
    with open(path) as f:
        manifest = json.load(f)
    manifest.update(overrides)
    with open(path, "w") as f:
        json.dump(manifest, f)
    return path
