"""Host-side data pipeline.

The framework consumes batches of {"inputs", "targets"} int32 arrays.
Sources:
  - `token_batches`: random contiguous windows from one in-memory token
    array (tests, small corpora).
  - `shard_batches`: streaming reader over binary token shards written
    by `write_token_shard` — the pure-Python counterpart of the native
    (C++) loader in shellac_tpu/runtime, which it transparently uses
    when the compiled library is available.

Every iterator yields numpy on host; `device_prefetch` moves batches to
device (with the right sharding) one step ahead of consumption so the
TPU never waits on the host.
"""

from __future__ import annotations

import queue
import struct
import threading
from typing import Iterator, Optional, Sequence

import jax
import numpy as np

_MAGIC = b"STSH"  # shellac tpu shard
_HEADER = struct.Struct("<4sIQ")  # magic, version, num_tokens


def write_token_shard(path: str, tokens: np.ndarray) -> None:
    """Write int32 tokens as a binary shard (header + raw little-endian)."""
    tokens = np.ascontiguousarray(tokens, dtype=np.int32)
    with open(path, "wb") as f:
        f.write(_HEADER.pack(_MAGIC, 1, tokens.size))
        f.write(tokens.tobytes())


def read_token_shard(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        magic, version, n = _HEADER.unpack(f.read(_HEADER.size))
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a token shard (bad magic {magic!r})")
        if version != 1:
            raise ValueError(f"{path}: unsupported shard version {version}")
        data = np.frombuffer(f.read(n * 4), dtype=np.int32)
        if data.size != n:
            raise ValueError(f"{path}: truncated shard ({data.size} != {n})")
        return data


def token_batches(
    tokens: np.ndarray,
    *,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    num_batches: Optional[int] = None,
    skip: int = 0,
) -> Iterator[dict]:
    """Random contiguous windows: inputs = w[:-1], targets = w[1:].

    `skip` fast-forwards the sampler past that many batches without
    materializing them, so a resumed run (checkpoint at step N ->
    skip=N) continues the SAME deterministic stream instead of
    replaying batches it already trained on.
    """
    tokens = np.asarray(tokens, dtype=np.int32)
    if tokens.size < seq_len + 1:
        raise ValueError(f"corpus of {tokens.size} tokens < seq_len+1")
    rng = np.random.default_rng(seed)
    for _ in range(skip):
        rng.integers(0, tokens.size - seq_len, size=batch_size)
    produced = 0
    while num_batches is None or produced < num_batches:
        # Valid starts are [0, size - seq_len - 1] inclusive: the window
        # takes seq_len + 1 tokens. integers() has an exclusive high.
        starts = rng.integers(0, tokens.size - seq_len, size=batch_size)
        window = np.stack([tokens[s : s + seq_len + 1] for s in starts])
        yield {"inputs": window[:, :-1], "targets": window[:, 1:]}
        produced += 1


def distribute_batches(it: Iterator[dict], mesh) -> Iterator[dict]:
    """Per-process local batches -> global jax.Arrays on a multi-host
    mesh.

    On a pod, jit with non-addressable batch shardings cannot consume
    host numpy; every process instead contributes its LOCAL slice of
    the global batch and the runtime assembles the global array
    (jax.make_array_from_process_local_data). The iterator on each
    process must therefore yield that process's share: distinct streams
    (seed offset by process_index) when the mesh's batch axes span
    processes, or IDENTICAL streams when the batch is replicated across
    processes (tp-only meshes) — the CLI picks the seed accordingly.

    Single-process meshes pass batches through untouched (jit places
    host numpy directly).
    """
    if jax.process_count() == 1:
        yield from it
        return
    from shellac_tpu.parallel.sharding import logical_to_spec
    from jax.sharding import NamedSharding

    nbatch = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    nproc = jax.process_count()
    if nbatch > 1 and nbatch % nproc:
        raise ValueError(
            f"batch axes (dp*fsdp={nbatch}) must be a multiple of the "
            f"{nproc} processes: with shards spanning process "
            "boundaries, two processes would contribute different rows "
            "to the same shard region"
        )
    sh = NamedSharding(mesh, logical_to_spec(("batch", "seq")))
    for batch in it:
        yield {
            k: jax.make_array_from_process_local_data(sh, np.asarray(v))
            for k, v in batch.items()
        }


def shard_batches(
    paths: Sequence[str],
    *,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    num_batches: Optional[int] = None,
    use_native: bool = True,
    skip: int = 0,
) -> Iterator[dict]:
    """Batches drawn from a set of token shards (round-robin by epoch).

    Uses the native C++ loader when built (mmap + prefetch threads);
    falls back to the pure-Python reader transparently. `skip` resumes
    the stream past already-trained batches (see token_batches); the
    native reader's prefetch threads make its order non-reproducible
    across run shapes, so there skipping discards real batches — cheap
    (host memcpy), and it preserves the don't-retrain-the-head
    property.
    """
    if use_native:
        try:
            from shellac_tpu.runtime.loader import NativeShardReader

            reader = NativeShardReader(paths, seed=seed)
            it = reader.batches(
                batch_size=batch_size, seq_len=seq_len,
                num_batches=num_batches + skip
                if num_batches is not None else None,
            )
            for _ in range(skip):
                next(it, None)
            yield from it
            return
        except (ImportError, OSError):
            pass
    corpus = np.concatenate([read_token_shard(p) for p in paths])
    yield from token_batches(
        corpus, batch_size=batch_size, seq_len=seq_len, seed=seed,
        num_batches=num_batches, skip=skip,
    )


def pack_documents(
    docs,
    *,
    seq_len: int,
    pad_id: int = 0,
) -> Iterator[dict]:
    """Greedy first-fit packing of documents into fixed-length rows.

    Yields one row at a time: {"inputs", "targets" (seq_len,),
    "segment_ids" (seq_len,) int32 — 0 marks padding, and "mask"
    (seq_len,) fp32 — 1 only where the target stays inside the same
    document}. Feed through `batch_rows` to group into batches. Combined
    with forward(segment_ids=...), each packed document trains exactly
    as if it were alone in the row (block-diagonal attention, restarted
    positions) — no cross-document leakage, no padding waste beyond the
    final row tail.

    Documents longer than seq_len + 1 are truncated.
    """
    row_tok: list = []
    row_seg: list = []
    seg = 1

    def emit():
        t = np.full((seq_len + 1,), pad_id, np.int32)
        g = np.zeros((seq_len + 1,), np.int32)
        t[: len(row_tok)] = row_tok
        g[: len(row_seg)] = row_seg
        same = (g[1:] == g[:-1]) & (g[:-1] > 0)
        return {
            "inputs": t[:-1],
            "targets": t[1:],
            "segment_ids": g[:-1],
            "mask": same.astype(np.float32),
        }

    for doc in docs:
        d = np.asarray(doc, np.int32).reshape(-1)[: seq_len + 1]
        if d.size < 2:
            continue
        if row_tok and len(row_tok) + d.size > seq_len + 1:
            yield emit()
            row_tok, row_seg = [], []
        row_tok.extend(d.tolist())
        row_seg.extend([seg] * d.size)
        seg += 1
    if row_tok:
        yield emit()


def batch_rows(rows: Iterator[dict], batch_size: int) -> Iterator[dict]:
    """Group per-row dicts into stacked batches (drops a partial tail)."""
    buf: list = []
    for r in rows:
        buf.append(r)
        if len(buf) == batch_size:
            yield {
                k: np.stack([x[k] for x in buf]) for k in buf[0]
            }
            buf = []


def device_prefetch(
    it: Iterator[dict],
    *,
    sharding=None,
    depth: int = 2,
) -> Iterator[dict]:
    """Move batches to device ahead of consumption (double buffering).

    A small background thread keeps `depth` device-resident batches
    queued so the host-to-HBM copy overlaps the previous step's compute.
    """
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = object()
    closed = threading.Event()

    def put(batch):
        if sharding is not None:
            return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
        return jax.tree.map(jax.device_put, batch)

    def send(item) -> bool:
        """Enqueue unless the consumer abandoned the generator — a
        worker parked forever in q.put() outlives its test/run and
        leaks a thread into the rest of the process."""
        while not closed.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in it:
                if not send(put(batch)):
                    return
        except BaseException as e:  # re-raised in the consumer
            send(e)
        else:
            send(stop)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is stop:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # Runs on normal exhaustion AND on generator close/GC: release
        # a worker mid-put and let it exit.
        closed.set()
        try:
            q.get_nowait()
        except queue.Empty:
            pass
