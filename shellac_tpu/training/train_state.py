"""TrainState pytree and sharding inference for the full optimizer state.

Optimizer moments (adam mu/nu) mirror the parameter pytree, so their
shardings are derived by *path-suffix matching* against the parameter
logical-axes tree: any state leaf whose tree path ends with a parameter's
path inherits that parameter's PartitionSpec; everything else (step
counters, scalars) is replicated. This keeps ZeRO-style optimizer
sharding automatic for any optax chain.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shellac_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec


@flax.struct.dataclass
class TrainState:
    step: Any
    params: Any
    opt_state: Any
    # Exponential moving average of params (TrainConfig.ema_decay);
    # None when disabled. Leaves mirror params, so sharding inference
    # (path-suffix matching below) covers them automatically.
    ema_params: Any = None


def _key_str(entry) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _is_axes_leaf(x) -> bool:
    return isinstance(x, tuple) and all(a is None or isinstance(a, str) for a in x)


def state_specs(abstract_state, param_axes, rules=DEFAULT_RULES):
    """PartitionSpec pytree for a TrainState (or any state pytree).

    abstract_state: jax.eval_shape of the state.
    param_axes: logical-axes pytree for the *params* subtree.
    """
    flat_axes = jax.tree_util.tree_flatten_with_path(
        param_axes, is_leaf=_is_axes_leaf
    )[0]
    by_path = {
        tuple(_key_str(e) for e in path): axes for path, axes in flat_axes
    }

    def spec_for(path, leaf):
        names = tuple(_key_str(e) for e in path)
        for plen in range(len(names), 0, -1):
            suffix = names[-plen:]
            if suffix in by_path:
                axes = by_path[suffix]
                if len(axes) == getattr(leaf, "ndim", len(axes)):
                    return logical_to_spec(axes, rules)
        return P()

    flat_state, treedef = jax.tree_util.tree_flatten_with_path(abstract_state)
    specs = [spec_for(path, leaf) for path, leaf in flat_state]
    return jax.tree_util.tree_unflatten(treedef, specs)


def state_shardings(mesh: Mesh, abstract_state, param_axes, rules=DEFAULT_RULES):
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        state_specs(abstract_state, param_axes, rules),
        is_leaf=lambda x: isinstance(x, P),
    )
