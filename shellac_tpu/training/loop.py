"""The training loop: steps + checkpoints + metrics + failure recovery.

Host-device sync discipline: the loop only fetches scalars every
`log_every` steps, so the device queue stays full between syncs; the
anomaly sentinel therefore reacts within one log interval, which is the
standard tradeoff (tighten log_every for faster tripping).

Failure semantics (docs/training.md): every log-boundary loss/grad_norm
feeds an `AnomalySentinel` (non-finite + EMA loss-spike detection).
Its `rollback` action restores the last-good checkpoint — walking past
corrupt steps via `Checkpointer.restore(fallback=True)` — re-derives
the data stream from the restored step (`data_factory`), and resumes;
repeated anomalies drain the sentinel's RestartBudget and escalate to
fatal. Multi-host runs agree on the verdict at the log-boundary sync
point (the same allgather as preemption agreement), so hosts never
diverge on whether to roll back.
"""

from __future__ import annotations

import dataclasses
import signal
import threading
import time
from typing import Callable, Iterator, Optional

import jax

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.obs.train import train_interval_histogram
from shellac_tpu.training.resilience import ACTIONS, AnomalySentinel
from shellac_tpu.training.trainer import init_train_state, make_train_step
from shellac_tpu.utils.failure import Heartbeat, RestartBudget
from shellac_tpu.utils.metrics import MetricsLogger
from shellac_tpu.utils.tracing import StepTimer


# Declared in the obs bundle layer (obs/train.py), which owns the
# shellac_* namespace; aliased here for the two fit loops below.
_interval_histogram = train_interval_histogram


def fit(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    data_iter: Optional[Iterator[dict]],
    *,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 500,
    log_path: Optional[str] = None,
    log_every: int = 10,
    resume: bool = True,
    heartbeat_path: Optional[str] = None,
    max_restores: int = 2,
    pipeline_microbatches: Optional[int] = None,
    handle_preemption: bool = True,
    sentinel: Optional[AnomalySentinel] = None,
    anomaly_action: str = "rollback",
    data_factory: Optional[Callable[[int], Iterator[dict]]] = None,
):
    """Train until train_cfg.total_steps; returns the final TrainState.

    With handle_preemption (and a checkpoint_dir), SIGTERM — the TPU-VM
    maintenance/preemption signal — stops the loop at the next step
    boundary and writes a final checkpoint, so `resume=True` restarts
    where the preempted run left off instead of at the last periodic
    save.

    Anomaly handling: `sentinel` (or a default `AnomalySentinel` with
    `anomaly_action` and a RestartBudget of `max_restores` recoveries
    per hour) judges every log-boundary loss. Rollbacks restore the
    last-good checkpoint via the fallback walk and, when `data_factory`
    is given (step -> fresh iterator positioned past `step` batches),
    replay the deterministic data stream — a transient fault then
    finishes bit-identical to an unfaulted run. Without a factory the
    loop keeps consuming `data_iter`, which recovers but replays no
    data (the stream has already advanced past the rolled-back steps).

    With `heartbeat_path`, the loop beats a liveness file at 1 Hz at
    step boundaries, with forced beats bracketing every restore —
    an external watchdog gets a full staleness window while a run is
    busy recovering (size its timeout above the worst restore).
    """
    multi = mesh is not None and jax.process_count() > 1
    if multi:
        # Multi-host: every process runs this same loop in SPMD. Local
        # batches assemble into global arrays; only process 0 writes
        # the metrics file and heartbeat (checkpoint saves are
        # collective — every process participates). Both log-boundary
        # agreement sites below share these bindings.
        import numpy as _np

        from jax.experimental import multihost_utils as mhu

        from shellac_tpu.training.data import distribute_batches

        if data_iter is not None:
            data_iter = distribute_batches(data_iter, mesh)
        if data_factory is not None:
            host_factory = data_factory

            def data_factory(s):
                return distribute_batches(host_factory(s), mesh)

        if jax.process_index() != 0:
            log_path = None
            heartbeat_path = None

    ckpt = None
    if checkpoint_dir is not None:
        from shellac_tpu.training.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir)

    heartbeat = Heartbeat(heartbeat_path) if heartbeat_path else None
    hb_last = [0.0]

    def beat(at_step: int, force: bool = False) -> None:
        # 1 Hz at the step boundary (same cadence as the serving
        # scheduler), rate-limited so fast tiny-model steps don't turn
        # into an fsync storm. Forced beats bracket every restore so an
        # external watchdog gets a full staleness window while a (slow,
        # possibly multi-step fallback) restore is in flight instead of
        # killing the recovering run.
        if heartbeat is None:
            return
        now = time.monotonic()
        if force or now - hb_last[0] >= 1.0:
            heartbeat.beat(at_step)
            hb_last[0] = now

    key = jax.random.PRNGKey(train_cfg.seed)
    if ckpt is not None and resume and ckpt.latest_step() is not None:
        # Never materialize the random init just to throw it away: trace
        # it abstractly for the state structure, restore into that. The
        # fallback walk quarantines a corrupt latest step instead of
        # bricking resume on it.
        abstract = jax.eval_shape(
            lambda: init_train_state(model_cfg, train_cfg, key, mesh=mesh)
        )
        beat(ckpt.latest_step() or 0, force=True)
        state = ckpt.restore(
            abstract_state=abstract, mesh=mesh, model_cfg=model_cfg,
            fallback=True,
        )
        if data_factory is not None:
            # Re-derive the stream from the step actually restored: a
            # fallback walk may have landed below the latest step the
            # caller computed its skip from.
            data_iter = data_factory(int(jax.device_get(state.step)))
    else:
        state = init_train_state(model_cfg, train_cfg, key, mesh=mesh)
    if data_iter is None:
        # data_iter=None + data_factory is the cheap calling convention
        # (the CLI uses it): the stream is built exactly once, at the
        # step that actually starts the run, instead of the caller
        # paying a skip fast-forward that a resume restore immediately
        # throws away and re-derives.
        if data_factory is None:
            raise ValueError("fit needs data_iter or data_factory")
        data_iter = data_factory(int(jax.device_get(state.step)))

    step_fn = make_train_step(
        model_cfg, train_cfg, mesh=mesh,
        pipeline_microbatches=pipeline_microbatches,
    )
    logger = MetricsLogger(log_path, every=1)
    if sentinel is None:
        sentinel = AnomalySentinel(
            action=anomaly_action,
            budget=RestartBudget(max_restores, window=3600.0),
        )
    timer = StepTimer(histogram=_interval_histogram())
    restores = 0

    preempted = threading.Event()
    old_handler = None
    install_handler = (
        handle_preemption
        and threading.current_thread() is threading.main_thread()
    )
    if install_handler:
        def _on_term(signum, frame):
            preempted.set()

        old_handler = signal.signal(signal.SIGTERM, _on_term)

    step = int(jax.device_get(state.step))
    stop = False
    try:
        # Context-managed logger: the JSONL file is flushed and closed
        # even when a step (or the final checkpoint save) raises.
        with logger:
            while step < train_cfg.total_steps and not stop:
                try:
                    batch = next(data_iter)
                except StopIteration:
                    break
                state, metrics = step_fn(state, batch)
                step += 1
                beat(step)

                if not multi and preempted.is_set():
                    stop = True
                if step % log_every == 0 or step >= train_cfg.total_steps:
                    loss = float(jax.device_get(metrics["loss"]))  # sync point
                    host_metrics = {
                        k: jax.device_get(v) for k, v in metrics.items()
                    }
                    gn = host_metrics.get("grad_norm")
                    pending = sentinel.detect(
                        step, loss,
                        grad_norm=None if gn is None else float(gn),
                    )
                    if multi:
                        # Preemption signals land per-VM at different
                        # times, and an anomaly verdict acted on by one
                        # host alone would desynchronize the step
                        # collectives (one host enters the restore while
                        # the others keep training), deadlocking the
                        # job. Agree on BOTH verdicts at the log
                        # boundary (the existing sync point) —
                        # maintenance grace periods and anomaly blast
                        # radii are both much longer than a log
                        # interval.
                        flags = mhu.process_allgather(_np.asarray(
                            [preempted.is_set(), pending is not None]
                        ))
                        if bool(_np.asarray(flags)[..., 0].any()):
                            preempted.set()
                            stop = True
                        if pending is None and bool(
                            _np.asarray(flags)[..., 1].any()
                        ):
                            pending = (
                                "peer", "anomaly flagged by another host"
                            )
                    dt = timer.tick()
                    if dt is not None:
                        host_metrics["steps_per_sec"] = log_every / dt
                    logger.log(step, host_metrics)
                    beat(step)

                    if pending is not None:
                        # Multi-host defers the counter until after the
                        # severity agreement below, so the action label
                        # is the action actually taken.
                        anomaly = sentinel.flag(step, *pending,
                                                record=not multi)
                        if multi:
                            # The recovery budget's window is wall-
                            # clock, so a window-edge race could
                            # resolve DIFFERENT actions on different
                            # hosts — and one host entering the
                            # collective restore alone deadlocks the
                            # pod. Agree by severity: every host takes
                            # the most severe resolved action (a split
                            # fatal/rollback becomes fatal everywhere —
                            # loud, never wedged).
                            sev = int(_np.asarray(mhu.process_allgather(
                                _np.asarray(
                                    [ACTIONS.index(anomaly.action)]
                                )
                            )).max())
                            if ACTIONS[sev] != anomaly.action:
                                anomaly = dataclasses.replace(
                                    anomaly, action=ACTIONS[sev],
                                    detail=anomaly.detail
                                    + "; escalated to agree with peers",
                                )
                            sentinel.record(anomaly)
                        # Logged BEFORE any raise: the terminal anomaly
                        # must land in the runbook's primary artifact
                        # (the JSONL log), not just the exception text.
                        logger.log(step, {
                            "anomaly_kind": anomaly.kind,
                            "anomaly_action": anomaly.action,
                        })
                        if anomaly.action == "rollback" and ckpt is not None:
                            # An async periodic save may still be in
                            # flight — and orbax lists it in all_steps
                            # already. Restoring (or even verifying) it
                            # uncommitted would quarantine a healthy
                            # checkpoint; wait for the commit first.
                            ckpt.wait()
                        if anomaly.action == "rollback" and (
                            ckpt is None or ckpt.latest_step() is None
                        ):
                            raise RuntimeError(
                                f"training anomaly: {anomaly}; rollback "
                                "requested but there is no checkpoint "
                                "to restore"
                            )
                        if anomaly.action == "fatal":
                            raise RuntimeError(
                                f"training anomaly: {anomaly}; "
                                "action=fatal"
                            )
                        if anomaly.action == "rollback":
                            restores += 1
                            sentinel.metrics.rollbacks.inc()
                            beat(step, force=True)  # entering recovery
                            abstract = jax.eval_shape(lambda s: s, state)
                            state = None  # free the diverged state first
                            state = ckpt.restore(
                                abstract_state=abstract, mesh=mesh,
                                model_cfg=model_cfg, fallback=True,
                            )
                            step = int(jax.device_get(state.step))
                            if data_factory is not None:
                                # Re-derive the stream position from the
                                # restored step: the deterministic skip
                                # path replays exactly the batches the
                                # rolled-back steps consumed.
                                data_iter = data_factory(step)
                            sentinel.reset()
                            beat(step, force=True)
                            logger.log(step, {
                                "restored_after": str(anomaly),
                                "restores": restores,
                            })
                            continue
                        # warn/skip: keep training (skip already drew
                        # from the budget inside flag()).

                if ckpt is not None and step % checkpoint_every == 0:
                    ckpt.save(step, state)

            if ckpt is not None:
                # Final save — including the preemption exit — always
                # WAITS: returning (or dying) with the write still in
                # flight is how truncated latest checkpoints are made.
                ckpt.save(int(jax.device_get(state.step)), state,
                          force=True, wait=True)
            if preempted.is_set():
                logger.log(step, {"preempted": 1})
    finally:
        if install_handler:
            signal.signal(signal.SIGTERM, old_handler)
        if ckpt is not None:
            # Shutdown path: close() waits for any in-flight async
            # save, so even an exception unwinding past a periodic
            # save cannot truncate it.
            ckpt.close()
    return state


def fit_lora(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    lora_cfg,
    base_params,
    data_iter: Iterator[dict],
    *,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 500,
    log_path: Optional[str] = None,
    log_every: int = 10,
    resume: bool = True,
):
    """Adapter-only fine-tuning: train a LoRAState over frozen
    base_params until train_cfg.total_steps; returns the final
    LoRAState.

    Checkpoints hold ONLY the adapters and their optimizer state (rank-r
    small), so saves are near-free and the base checkpoint is never
    rewritten. Resume restores from checkpoint_dir like fit(); the
    anomaly-rollback and preemption machinery is deliberately omitted
    — LoRA runs are short and rerunnable.
    """
    from shellac_tpu.training.lora import init_lora_state, make_lora_train_step

    ckpt = None
    if checkpoint_dir is not None:
        from shellac_tpu.training.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir)

    key = jax.random.PRNGKey(train_cfg.seed)
    if ckpt is not None and resume and ckpt.latest_step() is not None:
        abstract = jax.eval_shape(
            lambda: init_lora_state(
                model_cfg, train_cfg, lora_cfg, key, mesh=mesh
            )
        )
        # No fallback walk here ON PURPOSE: fit_lora has no
        # data_factory, so a restore landing below the latest step
        # would silently train on a misaligned stream. A corrupt
        # adapter checkpoint raises instead — LoRA runs are short and
        # rerunnable (same reason the anomaly machinery is omitted).
        state = ckpt.restore(abstract_state=abstract)
    else:
        state = init_lora_state(model_cfg, train_cfg, lora_cfg, key, mesh=mesh)

    step_fn = make_lora_train_step(model_cfg, train_cfg, lora_cfg, mesh=mesh)
    timer = StepTimer(histogram=_interval_histogram())

    step = int(jax.device_get(state.step))
    try:
        with MetricsLogger(log_path, every=1) as logger:
            while step < train_cfg.total_steps:
                try:
                    batch = next(data_iter)
                except StopIteration:
                    break
                state, metrics = step_fn(state, base_params, batch)
                step += 1
                if step % log_every == 0 or step >= train_cfg.total_steps:
                    host_metrics = {
                        k: jax.device_get(v) for k, v in metrics.items()
                    }
                    dt = timer.tick()
                    if dt is not None:
                        host_metrics["steps_per_sec"] = log_every / dt
                    logger.log(step, host_metrics)
                if ckpt is not None and step % checkpoint_every == 0:
                    ckpt.save(step, state)

            if ckpt is not None:
                ckpt.save(int(jax.device_get(state.step)), state, force=True,
                          wait=True)
    finally:
        if ckpt is not None:
            # Same shutdown guarantee as fit(): close() waits for any
            # in-flight async save before releasing the manager.
            ckpt.close()
    return state
