"""The training loop: steps + checkpoints + metrics + failure recovery.

Host-device sync discipline: the loop only fetches scalars every
`log_every` steps, so the device queue stays full between syncs; the
failure detector therefore reacts within one log interval, which is the
standard tradeoff (tighten log_every for faster tripping).
"""

from __future__ import annotations

import signal
import threading
from typing import Iterator, Optional

import jax

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.obs import get_registry, log_buckets
from shellac_tpu.training.trainer import init_train_state, make_train_step
from shellac_tpu.utils.failure import FailureDetector, Heartbeat
from shellac_tpu.utils.metrics import MetricsLogger
from shellac_tpu.utils.tracing import StepTimer


def _interval_histogram():
    """Step-interval wall-time distribution in the shared registry, so
    training pace is scrapable alongside serving latency (one series
    per process; registration is idempotent)."""
    return get_registry().histogram(
        "shellac_train_log_interval_seconds",
        "Wall time between metric log boundaries (log_every steps)",
        buckets=log_buckets(0.001, 600.0),
    )


def fit(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    data_iter: Iterator[dict],
    *,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 500,
    log_path: Optional[str] = None,
    log_every: int = 10,
    resume: bool = True,
    heartbeat_path: Optional[str] = None,
    max_restores: int = 2,
    pipeline_microbatches: Optional[int] = None,
    handle_preemption: bool = True,
):
    """Train until train_cfg.total_steps; returns the final TrainState.

    With handle_preemption (and a checkpoint_dir), SIGTERM — the TPU-VM
    maintenance/preemption signal — stops the loop at the next step
    boundary and writes a final checkpoint, so `resume=True` restarts
    where the preempted run left off instead of at the last periodic
    save.
    """
    multi = mesh is not None and jax.process_count() > 1
    if multi:
        # Multi-host: every process runs this same loop in SPMD. Local
        # batches assemble into global arrays; only process 0 writes
        # the metrics file and heartbeat (checkpoint saves are
        # collective — every process participates).
        from shellac_tpu.training.data import distribute_batches

        data_iter = distribute_batches(data_iter, mesh)
        if jax.process_index() != 0:
            log_path = None
            heartbeat_path = None

    ckpt = None
    if checkpoint_dir is not None:
        from shellac_tpu.training.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir)

    key = jax.random.PRNGKey(train_cfg.seed)
    if ckpt is not None and resume and ckpt.latest_step() is not None:
        # Never materialize the random init just to throw it away: trace
        # it abstractly for the state structure, restore into that.
        abstract = jax.eval_shape(
            lambda: init_train_state(model_cfg, train_cfg, key, mesh=mesh)
        )
        state = ckpt.restore(
            abstract_state=abstract, mesh=mesh, model_cfg=model_cfg
        )
    else:
        state = init_train_state(model_cfg, train_cfg, key, mesh=mesh)

    step_fn = make_train_step(
        model_cfg, train_cfg, mesh=mesh,
        pipeline_microbatches=pipeline_microbatches,
    )
    logger = MetricsLogger(log_path, every=1)
    detector = FailureDetector()
    heartbeat = Heartbeat(heartbeat_path) if heartbeat_path else None
    timer = StepTimer(histogram=_interval_histogram())
    restores = 0

    preempted = threading.Event()
    old_handler = None
    install_handler = (
        handle_preemption
        and threading.current_thread() is threading.main_thread()
    )
    if install_handler:
        def _on_term(signum, frame):
            preempted.set()

        old_handler = signal.signal(signal.SIGTERM, _on_term)

    step = int(jax.device_get(state.step))
    stop = False
    # Context-managed logger: the JSONL file is flushed and closed even
    # when a step (or the final checkpoint save) raises.
    with logger:
        while step < train_cfg.total_steps and not stop:
            try:
                batch = next(data_iter)
            except StopIteration:
                break
            state, metrics = step_fn(state, batch)
            step += 1

            if not multi and preempted.is_set():
                stop = True
            if step % log_every == 0 or step >= train_cfg.total_steps:
                if multi:
                    # Preemption signals land per-VM at different
                    # times; a process acting on its local flag alone
                    # would enter the final collective save while the
                    # others still run step collectives, deadlocking
                    # the job. Agree at the log boundary (the existing
                    # sync point) — maintenance grace periods are much
                    # longer than a log interval.
                    from jax.experimental import multihost_utils as mhu

                    import numpy as _np

                    if bool(mhu.process_allgather(
                        _np.asarray([preempted.is_set()])
                    ).any()):
                        preempted.set()
                        stop = True
                loss = float(jax.device_get(metrics["loss"]))  # sync point
                dt = timer.tick()
                host_metrics = {
                    k: jax.device_get(v) for k, v in metrics.items()
                }
                if dt is not None:
                    host_metrics["steps_per_sec"] = log_every / dt
                logger.log(step, host_metrics)
                if heartbeat is not None:
                    heartbeat.beat(step)

                reason = detector.check(loss)
                if reason is not None:
                    if (ckpt is None or ckpt.latest_step() is None
                            or restores >= max_restores):
                        raise RuntimeError(
                            f"training failure at step {step}: {reason}; "
                            "no checkpoint to restore (or restore budget "
                            "spent)"
                        )
                    restores += 1
                    abstract = jax.eval_shape(lambda s: s, state)
                    state = None  # free the diverged state before restoring
                    state = ckpt.restore(
                        abstract_state=abstract, mesh=mesh,
                        model_cfg=model_cfg
                    )
                    step = int(jax.device_get(state.step))
                    detector.reset()
                    logger.log(
                        step,
                        {"restored_after": reason, "restores": restores},
                    )
                    continue

            if ckpt is not None and step % checkpoint_every == 0:
                ckpt.save(step, state)

        if ckpt is not None:
            ckpt.save(int(jax.device_get(state.step)), state, force=True,
                      wait=True)
        if preempted.is_set():
            logger.log(step, {"preempted": 1})
    if install_handler:
        signal.signal(signal.SIGTERM, old_handler)
    return state


def fit_lora(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    lora_cfg,
    base_params,
    data_iter: Iterator[dict],
    *,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 500,
    log_path: Optional[str] = None,
    log_every: int = 10,
    resume: bool = True,
):
    """Adapter-only fine-tuning: train a LoRAState over frozen
    base_params until train_cfg.total_steps; returns the final
    LoRAState.

    Checkpoints hold ONLY the adapters and their optimizer state (rank-r
    small), so saves are near-free and the base checkpoint is never
    rewritten. Resume restores from checkpoint_dir like fit(); the
    divergence-restore and preemption machinery is deliberately omitted
    — LoRA runs are short and rerunnable.
    """
    from shellac_tpu.training.lora import init_lora_state, make_lora_train_step

    ckpt = None
    if checkpoint_dir is not None:
        from shellac_tpu.training.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir)

    key = jax.random.PRNGKey(train_cfg.seed)
    if ckpt is not None and resume and ckpt.latest_step() is not None:
        abstract = jax.eval_shape(
            lambda: init_lora_state(
                model_cfg, train_cfg, lora_cfg, key, mesh=mesh
            )
        )
        state = ckpt.restore(abstract_state=abstract)
    else:
        state = init_lora_state(model_cfg, train_cfg, lora_cfg, key, mesh=mesh)

    step_fn = make_lora_train_step(model_cfg, train_cfg, lora_cfg, mesh=mesh)
    timer = StepTimer(histogram=_interval_histogram())

    step = int(jax.device_get(state.step))
    with MetricsLogger(log_path, every=1) as logger:
        while step < train_cfg.total_steps:
            try:
                batch = next(data_iter)
            except StopIteration:
                break
            state, metrics = step_fn(state, base_params, batch)
            step += 1
            if step % log_every == 0 or step >= train_cfg.total_steps:
                host_metrics = {
                    k: jax.device_get(v) for k, v in metrics.items()
                }
                dt = timer.tick()
                if dt is not None:
                    host_metrics["steps_per_sec"] = log_every / dt
                logger.log(step, host_metrics)
            if ckpt is not None and step % checkpoint_every == 0:
                ckpt.save(step, state)

        if ckpt is not None:
            ckpt.save(int(jax.device_get(state.step)), state, force=True,
                      wait=True)
    return state
