"""Knowledge distillation: train a student against a frozen teacher.

The classic Hinton recipe (public method; the reference repo for this
project is empty, SURVEY.md §0): the student matches the teacher's
temperature-softened token distribution via KL divergence, optionally
mixed with the ordinary next-token cross-entropy on hard targets.

TPU-first shape decisions mirror training/dpo.py: the teacher forward
runs inside the same jitted step under stop_gradient (no separate eval
step or host round-trip), teacher params ride as a step argument so
they are never baked into the executable as constants, and the KL
reduces in fp32 over the full vocab — one fused softmax/logsumexp pair
per model, no materialized probability tensors beyond the logits XLA
already holds.

The teacher may be a DIFFERENT architecture (teacher_cfg): any model
this framework can run — including a converted HF checkpoint — can
teach, as long as the vocabularies match.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.training.losses import cross_entropy
from shellac_tpu.training.optimizer import make_optimizer
from shellac_tpu.training.train_state import TrainState, state_shardings
from shellac_tpu.training.trainer import _LazyShardedStep, batch_shardings


@dataclass(frozen=True)
class DistillConfig:
    """Distillation objective configuration.

    temperature: softening applied to BOTH distributions; the KL term
      carries the standard T^2 factor so gradients keep their scale.
    alpha: weight on the KD term; (1 - alpha) goes to the hard-target
      cross-entropy. alpha=1 is pure distillation.
    kind: "forward" (KL(teacher || student) — mass-covering, the
      standard choice) or "reverse" (KL(student || teacher) —
      mode-seeking, the on-policy/generation-flavored variant).
    """

    temperature: float = 2.0
    alpha: float = 0.5
    kind: str = "forward"

    def validate(self) -> "DistillConfig":
        if self.temperature <= 0:
            raise ValueError(
                f"temperature={self.temperature} must be positive"
            )
        if not 0.0 <= self.alpha <= 1.0:
            raise ValueError(f"alpha={self.alpha} must be in [0, 1]")
        if self.kind not in ("forward", "reverse"):
            raise ValueError(
                f"kind={self.kind!r}; have forward, reverse"
            )
        return self

    def replace(self, **kw) -> "DistillConfig":
        return dataclasses.replace(self, **kw)


def distill_loss(
    student_logits,  # (B, S, V) fp32
    teacher_logits,  # (B, S, V) fp32, already stop-gradient
    dcfg: DistillConfig,
    mask=None,  # (B, S) f32 — 1.0 on positions that count
):
    """Temperature-softened KL between teacher and student, meaned over
    unmasked positions. Returns (loss, metrics)."""
    t = dcfg.temperature
    s_lp = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, -1)
    t_lp = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / t, -1)
    if dcfg.kind == "forward":
        # KL(T || S) = sum p_T (log p_T - log p_S)
        kl = jnp.sum(jnp.exp(t_lp) * (t_lp - s_lp), axis=-1)
    else:
        kl = jnp.sum(jnp.exp(s_lp) * (s_lp - t_lp), axis=-1)
    if mask is None:
        denom = kl.size
        kl_mean = jnp.sum(kl) / denom
    else:
        m = mask.astype(jnp.float32)
        kl_mean = jnp.sum(kl * m) / jnp.maximum(jnp.sum(m), 1.0)
    # T^2 keeps soft-target gradient magnitudes comparable to the hard
    # CE as the temperature changes (Hinton et al.).
    loss = (t * t) * kl_mean
    match = (
        jnp.argmax(student_logits, -1) == jnp.argmax(teacher_logits, -1)
    ).astype(jnp.float32)
    if mask is None:
        agreement = jnp.mean(match)
    else:
        # Same positions as the loss: padding must not dilute the
        # convergence metric.
        m = mask.astype(jnp.float32)
        agreement = jnp.sum(match * m) / jnp.maximum(jnp.sum(m), 1.0)
    return loss, {"kd_loss": loss, "teacher_agreement": agreement}


def make_distill_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    distill_cfg: DistillConfig,
    teacher_cfg: Optional[ModelConfig] = None,
    mesh: Optional[Mesh] = None,
    attn_impl: str = "auto",
    jit: bool = True,
):
    """Build `distill_step(state, teacher_params, batch) -> (state, metrics)`.

    batch: {"inputs" (B,S) i32, "targets" (B,S) i32, "mask" (B,S) f32?}.
    teacher_cfg defaults to the student's config (self-distillation /
    same-shape teacher); pass the teacher's own ModelConfig otherwise.
    The state is DONATED: teacher params must not alias state.params.
    """
    distill_cfg = distill_cfg.validate()
    teacher_cfg = teacher_cfg or model_cfg
    if teacher_cfg.vocab_size != model_cfg.vocab_size:
        raise ValueError(
            f"teacher vocab {teacher_cfg.vocab_size} != student vocab "
            f"{model_cfg.vocab_size}: distillation matches token "
            "distributions, the vocabularies must be identical"
        )
    optimizer = make_optimizer(train_cfg)
    alpha = distill_cfg.alpha

    def loss_fn(params, teacher_params, batch):
        student_logits = transformer.forward(
            model_cfg, params, batch["inputs"], mesh=mesh,
            attn_impl=attn_impl,
        )
        teacher_logits = jax.lax.stop_gradient(
            transformer.forward(
                teacher_cfg, teacher_params, batch["inputs"], mesh=mesh,
                attn_impl=attn_impl,
            )
        )
        kd, metrics = distill_loss(
            student_logits, teacher_logits, distill_cfg,
            mask=batch.get("mask"),
        )
        loss = alpha * kd
        if alpha < 1.0:
            ce, ce_metrics = cross_entropy(
                student_logits, batch["targets"], batch.get("mask"),
                train_cfg.z_loss_weight,
            )
            loss = loss + (1.0 - alpha) * ce
            metrics["ce_loss"] = ce_metrics["loss"]
        metrics["loss"] = loss
        return loss, metrics

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def distill_step(state: TrainState, teacher_params, batch):
        from shellac_tpu.utils.failure import all_finite, guard_update

        (_, metrics), grads = grad_fn(state.params, teacher_params, batch)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_ema = state.ema_params
        if train_cfg.ema_decay is not None:
            d = train_cfg.ema_decay
            new_ema = jax.tree.map(
                lambda e, p: (e * d + p.astype(e.dtype) * (1.0 - d)).astype(
                    e.dtype
                ),
                state.ema_params, new_params,
            )
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        if train_cfg.skip_nonfinite_updates:
            ok = all_finite(grads)
            new_params = guard_update(state.params, new_params, ok)
            new_opt_state = guard_update(state.opt_state, new_opt_state, ok)
            if new_ema is not None:
                new_ema = guard_update(state.ema_params, new_ema, ok)
            metrics["update_skipped"] = 1.0 - ok.astype(jnp.float32)
        return TrainState(
            step=state.step + 1, params=new_params,
            opt_state=new_opt_state, ema_params=new_ema,
        ), metrics

    if not jit:
        return distill_step

    if mesh is None:
        return jax.jit(distill_step, donate_argnums=(0,))

    def jit_with_shardings(state, teacher_params, batch):
        abstract_state = jax.eval_shape(lambda s: s, state)
        st_sh = state_shardings(
            mesh, abstract_state, transformer.logical_axes(model_cfg)
        )
        t_abstract = jax.eval_shape(lambda p: p, teacher_params)
        t_sh = state_shardings(
            mesh, t_abstract, transformer.logical_axes(teacher_cfg)
        )
        b_sh = batch_shardings(mesh)
        batch_in = jax.tree.map(lambda _: b_sh, batch)
        return jax.jit(
            distill_step,
            in_shardings=(st_sh, t_sh, batch_in),
            out_shardings=(st_sh, None),
            donate_argnums=(0,),
        )

    return _LazyShardedStep(jit_with_shardings)


def fit_distill(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    distill_cfg: DistillConfig,
    teacher_params,
    data_iter,
    *,
    teacher_cfg: Optional[ModelConfig] = None,
    mesh: Optional[Mesh] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 500,
    log_path: Optional[str] = None,
    log_every: int = 10,
    resume: bool = True,
):
    """Distillation loop; returns the final (student) TrainState.

    Mirrors fit(): checkpoints the full student TrainState under
    checkpoint_dir with sharded resume. The teacher is frozen — it is
    never checkpointed.
    """
    from shellac_tpu.training.trainer import init_train_state
    from shellac_tpu.utils.metrics import MetricsLogger
    from shellac_tpu.utils.tracing import StepTimer

    distill_cfg = distill_cfg.validate()
    key = jax.random.PRNGKey(train_cfg.seed)
    ckpt = None
    if checkpoint_dir is not None:
        from shellac_tpu.training.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir)
    if ckpt is not None and resume and ckpt.latest_step() is not None:
        abstract = jax.eval_shape(
            lambda: init_train_state(model_cfg, train_cfg, key, mesh=mesh)
        )
        state = ckpt.restore(
            abstract_state=abstract, mesh=mesh, model_cfg=model_cfg
        )
    else:
        state = init_train_state(model_cfg, train_cfg, key, mesh=mesh)

    step_fn = make_distill_step(
        model_cfg, train_cfg, distill_cfg, teacher_cfg=teacher_cfg,
        mesh=mesh,
    )
    logger = MetricsLogger(log_path, every=1)
    timer = StepTimer()

    step = int(jax.device_get(state.step))
    while step < train_cfg.total_steps:
        try:
            batch = next(data_iter)
        except StopIteration:
            break
        state, metrics = step_fn(state, teacher_params, batch)
        step += 1
        if step % log_every == 0 or step >= train_cfg.total_steps:
            host_metrics = {k: jax.device_get(v) for k, v in metrics.items()}
            dt = timer.tick()
            if dt is not None:
                host_metrics["steps_per_sec"] = log_every / dt
            logger.log(step, host_metrics)
        if ckpt is not None and step % checkpoint_every == 0:
            ckpt.save(step, state)

    if ckpt is not None:
        ckpt.save(int(jax.device_get(state.step)), state, force=True,
                  wait=True)
    logger.close()
    return state
