"""LoRA parameter-efficient fine-tuning.

TPU-first design: instead of patching matmuls with per-call low-rank
side-paths (the torch idiom of wrapping `nn.Linear`), the adapters are
**merged into the weight pytree once per step** — `W + (alpha/r) A·B` is
a single batched einsum over the stacked layer axis, and the merged
weights then flow through the unmodified `transformer.forward`. XLA sees
the exact same program it already compiles well; the merge itself is
O(L·d·r·f) — negligible next to one forward pass — and under `remat` it
is recomputed rather than stored.

Only the adapter pytree is differentiated: the base params enter the
jitted step as a frozen (non-donated) argument, so the optimizer state
is rank-r small and the base weights can stay in bf16 on device.

The reference repo for this project is empty (SURVEY.md §0); there is no
upstream PEFT implementation to cite. This follows the public LoRA
formulation (Hu et al., 2021): A ~ N(0, 1/fan_in), B = 0, scaled by
alpha/rank.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.training.losses import cross_entropy
from shellac_tpu.training.optimizer import make_optimizer
from shellac_tpu.training.train_state import state_shardings

# Per-layer matmul weights LoRA can target. Shapes are taken from the
# base parameter tree, so the same names cover dense stacks (L, in, out),
# MoE expert stacks (L, E, in, out — one adapter pair per expert), and
# interleaved dense/MoE layouts (grouped under "dense"/"moe" sub-stacks).
_TARGETS: Tuple[str, ...] = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
)

# MLA parameter trees replace wq/wk/wv with the latent projections.
# wkv_b_k/wkv_b_v are stored (L, kv_rank, heads, dh) but are really the
# (kv_rank -> heads*dh) expansion matrices: their adapters fold the
# trailing head dims (see _folded).
_MLA_TARGETS: Tuple[str, ...] = (
    "wq", "wq_a", "wq_b", "wkv_a", "wkv_b_k", "wkv_b_v", "wo",
    "w_gate", "w_up", "w_down",
)
_FOLDED: Tuple[str, ...] = ("wkv_b_k", "wkv_b_v")

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")
DEFAULT_MLA_TARGETS = ("wkv_a", "wkv_b_k", "wkv_b_v", "wo")


def _folded(name: str) -> bool:
    return name in _FOLDED


@dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank

    def validate(self, model_cfg: ModelConfig) -> "LoRAConfig":
        """Check targets against the model family; returns the resolved
        config (the generic wq/wk/wv/wo default maps onto the MLA
        projections for MLA models — callers must use the result)."""
        cfg = self
        if model_cfg.mla is not None:
            if cfg.targets == DEFAULT_TARGETS:
                q = (("wq",) if model_cfg.mla.q_lora_rank is None
                     else ("wq_a", "wq_b"))
                cfg = cfg.replace(targets=q + DEFAULT_MLA_TARGETS)
            allowed = set(_MLA_TARGETS)
            if model_cfg.mla.q_lora_rank is None:
                allowed -= {"wq_a", "wq_b"}
            else:
                allowed -= {"wq"}
        else:
            allowed = set(_TARGETS)
        unknown = set(cfg.targets) - allowed
        if unknown:
            raise ValueError(
                f"unknown LoRA targets {sorted(unknown)}; "
                f"have {sorted(allowed)}"
            )
        if cfg.rank < 1:
            raise ValueError(f"rank must be >= 1, got {cfg.rank}")
        return cfg

    def replace(self, **kw) -> "LoRAConfig":
        return dataclasses.replace(self, **kw)


def init_lora(
    model_cfg: ModelConfig, lora_cfg: LoRAConfig, key: jax.Array
) -> Dict[str, Any]:
    """Adapter pytree mirroring the base layer layout.

    Flat stacks: {"layers": {target: {"a": (L,in,r), "b": (L,r,out)}}}.
    MoE expert weights gain an expert axis ((L,E,in,r) / (L,E,r,out) —
    an independent adapter pair per expert); interleaved stacks mirror
    the {"dense": ..., "moe": ...} grouping.

    B starts at zero so the adapted model is exactly the base model at
    step 0 (standard LoRA init). MLA's wkv_b_k/wkv_b_v fold their
    trailing (heads, dh) dims: a is (L, kv_rank, r), b is
    (L, r, heads, dh) — the adapter of the REAL expansion matrix.
    """
    lora_cfg = lora_cfg.validate(model_cfg)
    base_shapes = jax.eval_shape(
        lambda k: transformer.init_params(model_cfg, k), key
    )["layers"]
    r = lora_cfg.rank
    pdt = model_cfg.params_dtype

    kd, km = jax.random.split(key)
    stack_keys = {"dense": kd, "moe": km, None: key}

    def init_stack(stack, name):
        out: Dict[str, Any] = {}
        keys = jax.random.split(stack_keys[name], len(lora_cfg.targets))
        for t, k in zip(lora_cfg.targets, keys):
            if t not in stack:
                # Two-stack layouts: MoE-only targets are absent from
                # the dense stack and vice versa.
                continue
            if _folded(t):
                *lead, fan_in, h, dh = stack[t].shape
                tail = (h, dh)
            else:
                *lead, fan_in, fan_out = stack[t].shape
                tail = (fan_out,)
            a = (jax.random.normal(k, (*lead, fan_in, r), jnp.float32)
                 * fan_in ** -0.5).astype(pdt)
            out[t] = {"a": a, "b": jnp.zeros((*lead, r, *tail), pdt)}
        return out

    return {"layers": transformer.map_layer_stacks(base_shapes, init_stack)}


def lora_logical_axes(
    model_cfg: ModelConfig, lora_cfg: LoRAConfig
) -> Dict[str, Any]:
    """Logical axes matching init_lora's structure.

    Derived from the base weight's own axes: the rank axis is
    replicated; leading/in/out axes inherit the base sharding (incl.
    the experts axis for MoE targets) so the merge einsum is local on
    each device.
    """
    lora_cfg = lora_cfg.validate(model_cfg)
    base_axes = transformer.logical_axes(model_cfg)["layers"]

    def axes_stack(stack, _name):
        out: Dict[str, Any] = {}
        for t in lora_cfg.targets:
            if t not in stack:
                continue
            wa = stack[t]
            if _folded(t):
                # base: (..., None, heads, None) -> a drops the head
                # tail, b keeps it (rank axis replicated). Works for
                # flat (L, ...) and grouped (ng, every-1, ...) leads.
                out[t] = {
                    "a": (*wa[:-2], None),
                    "b": (*wa[:-3], None, *wa[-2:]),
                }
            else:
                out[t] = {
                    "a": (*wa[:-1], None),
                    "b": (*wa[:-2], None, wa[-1]),
                }
        return out

    return {"layers": transformer.map_layer_stacks(base_axes, axes_stack)}


def merge_lora(params, lora, lora_cfg: LoRAConfig):
    """Return params with `W + scale * A @ B` for each targeted weight.

    One batched einsum per target over all leading axes (stacked layers,
    groups, experts); computed in fp32 then cast back to the base weight
    dtype.
    """
    def merge_stack(stack, name):
        lstack = lora["layers"][name] if name else lora["layers"]
        merged = dict(stack)
        for t, ab in lstack.items():
            w = merged[t]
            sub = ("...ir,...rhd->...ihd" if _folded(t)
                   else "...ir,...ro->...io")
            delta = jnp.einsum(
                sub, ab["a"].astype(jnp.float32),
                ab["b"].astype(jnp.float32),
            )
            merged[t] = (w.astype(jnp.float32)
                         + lora_cfg.scale * delta).astype(w.dtype)
        return merged

    out = dict(params)
    out["layers"] = transformer.map_layer_stacks(params["layers"], merge_stack)
    return out


@flax.struct.dataclass
class LoRAState:
    """Trainable state for a LoRA run: adapters + their optimizer state.

    The frozen base params are deliberately *not* part of the state — they
    are passed to the step separately and never donated or updated.
    """

    step: Any
    lora: Any
    opt_state: Any


def init_lora_state(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    lora_cfg: LoRAConfig,
    key: jax.Array,
    mesh=None,
) -> LoRAState:
    optimizer = make_optimizer(train_cfg)

    def init_fn(key):
        lora = init_lora(model_cfg, lora_cfg, key)
        return LoRAState(
            step=jnp.zeros((), jnp.int32),
            lora=lora,
            opt_state=optimizer.init(lora),
        )

    if mesh is None:
        return jax.jit(init_fn)(key)
    abstract = jax.eval_shape(init_fn, key)
    shardings = state_shardings(
        mesh, abstract, lora_logical_axes(model_cfg, lora_cfg)
    )
    return jax.jit(init_fn, out_shardings=shardings)(key)


def make_lora_train_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    lora_cfg: LoRAConfig,
    mesh=None,
    attn_impl: str = "auto",
):
    """Build `step(state, base_params, batch) -> (state, metrics)`.

    Gradients flow only into the adapters; base_params are a frozen
    input. Under a mesh, shardings are attached lazily on first call
    (same pattern as make_train_step).
    """
    lora_cfg = lora_cfg.validate(model_cfg)
    optimizer = make_optimizer(train_cfg)

    def loss_fn(lora, base_params, batch):
        merged = merge_lora(base_params, lora, lora_cfg)
        logits, aux = transformer.forward(
            model_cfg, merged, batch["inputs"], mesh=mesh,
            attn_impl=attn_impl,
            segment_ids=batch.get("segment_ids"),  # packed-data contract
            return_aux=True,
        )
        loss, metrics = cross_entropy(
            logits, batch["targets"], batch.get("mask"),
            train_cfg.z_loss_weight,
        )
        if model_cfg.moe is not None:
            loss = loss + aux["aux"]
        return loss, metrics

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(state: LoRAState, base_params, batch):
        from shellac_tpu.utils.failure import all_finite, guard_update

        (_, metrics), grads = grad_fn(state.lora, base_params, batch)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.lora
        )
        new_lora = optax.apply_updates(state.lora, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        if train_cfg.skip_nonfinite_updates:
            ok = all_finite(grads)
            new_lora = guard_update(state.lora, new_lora, ok)
            new_opt_state = guard_update(state.opt_state, new_opt_state, ok)
            metrics["update_skipped"] = 1.0 - ok.astype(jnp.float32)
        return (
            LoRAState(
                step=state.step + 1, lora=new_lora, opt_state=new_opt_state
            ),
            metrics,
        )

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))

    def jit_with_shardings(state, base_params, batch):
        from shellac_tpu.training.trainer import batch_shardings

        abstract_state = jax.eval_shape(lambda s: s, state)
        st_sh = state_shardings(
            mesh, abstract_state, lora_logical_axes(model_cfg, lora_cfg)
        )
        abstract_p = jax.eval_shape(lambda p: p, base_params)
        p_sh = state_shardings(
            mesh, abstract_p, transformer.logical_axes(model_cfg)
        )
        b_sh = batch_shardings(mesh)
        batch_in = jax.tree.map(lambda _: b_sh, batch)
        return jax.jit(
            step,
            in_shardings=(st_sh, p_sh, batch_in),
            out_shardings=(st_sh, None),
            donate_argnums=(0,),
        )

    from shellac_tpu.training.trainer import _LazyShardedStep

    return _LazyShardedStep(jit_with_shardings)
