"""Distributed checkpoint / resume on orbax.

Orbax writes each array shard from the device that owns it (OCDBT
format), so saving a ZeRO-sharded TrainState never gathers parameters to
one host, and restore places shards directly onto the target mesh via
abstract arrays carrying NamedShardings.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from shellac_tpu.models import transformer
from shellac_tpu.training.train_state import state_shardings


class Checkpointer:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ):
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    @property
    def directory(self) -> str:
        return str(self._mngr.directory)

    def save(self, step: int, state: Any, *, force: bool = False, wait: bool = False) -> bool:
        """Save (async by default). Returns True if a save was started."""
        if step in self._mngr.all_steps():
            if wait:
                self._mngr.wait_until_finished()
            return False
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if wait:
            self._mngr.wait_until_finished()
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(
        self,
        step: Optional[int] = None,
        *,
        abstract_state: Any = None,
        mesh=None,
        model_cfg=None,
    ) -> Any:
        """Restore a TrainState.

        With `mesh` + `model_cfg` (or an `abstract_state` of
        jax.ShapeDtypeStructs carrying shardings), arrays are restored
        directly sharded; otherwise each leaf lands on the first local
        device — which also lets checkpoints SAVED sharded restore
        without any mesh (pod checkpoint → single-chip eval/generate,
        elastic down-scale).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if abstract_state is None:
            return self._mngr.restore(step)
        if mesh is not None and model_cfg is not None:
            shardings = state_shardings(
                mesh, abstract_state, transformer.logical_axes(model_cfg)
            )
            abstract_state = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                abstract_state,
                shardings,
            )
        else:
            # Restoring WITHOUT a target mesh must still work for
            # checkpoints SAVED sharded (train on a pod, eval/generate
            # on one chip, or elastic down-scale): orbax requires
            # concrete target shardings for deserialization, so pin
            # leaves that carry none to the first LOCAL device (a
            # global jax.devices()[0] is non-addressable from other
            # processes). Leaves already carrying a sharding keep it —
            # the documented sharded-abstract_state path.
            one = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
            abstract_state = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype,
                    sharding=getattr(a, "sharding", None) or one,
                ),
                abstract_state,
            )
        try:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract_state)
            )
        except Exception:
            # Dtype drift (e.g. a checkpoint written with fp32 adam mu
            # restored under a bf16-mu config) is the one recoverable
            # failure: confirm the saved dtypes actually differ from the
            # requested ones before retrying, so corrupt/partial steps
            # surface their original error instead.
            meta = self._mngr.item_metadata(step)
            drifted = any(
                a.dtype != m.dtype
                for a, m in zip(
                    jax.tree.leaves(abstract_state), jax.tree.leaves(meta)
                )
            )
            if not drifted:
                raise
            restored = self._restore_saved_dtypes(step, abstract_state, meta)
            return jax.tree.map(
                lambda x, a: x.astype(a.dtype) if x.dtype != a.dtype else x,
                restored,
                abstract_state,
            )

    def _restore_saved_dtypes(self, step: int, abstract_state: Any, meta: Any) -> Any:
        as_saved = jax.tree.map(
            lambda a, m: jax.ShapeDtypeStruct(
                a.shape, m.dtype, sharding=getattr(a, "sharding", None)
            ),
            abstract_state,
            meta,
        )
        return self._mngr.restore(
            step, args=ocp.args.StandardRestore(as_saved)
        )

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()
