"""Distributed checkpoint / resume on orbax, with integrity tracking.

Orbax writes each array shard from the device that owns it (OCDBT
format), so saving a ZeRO-sharded TrainState never gathers parameters to
one host, and restore places shards directly onto the target mesh via
abstract arrays carrying NamedShardings.

Integrity contract (docs/training.md, "Failure semantics"):

  - every `save` also writes a per-step manifest (leaf count, tree-
    structure digest, per-leaf shapes/dtypes) under `manifests/`;
  - `verify(step)` checks a saved step against its manifest without
    reading array data;
  - `restore(..., fallback=True)` walks steps newest→oldest past
    corrupt/partial ones, quarantining each bad step (directory
    renamed `<step>.corrupt`, never re-selected by `latest_step`);
  - construction sweeps interrupted-save debris (uncommitted orbax tmp
    directories), so a kill mid-save can never be restored as
    "latest" — the commit is an atomic rename, and anything left
    un-renamed is garbage by definition.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Any, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from shellac_tpu.models import transformer
from shellac_tpu.training.train_state import state_shardings

# Orbax commits a step by renaming `<step><marker><ts>` to `<step>`;
# anything still carrying the marker is an interrupted save.
TMP_DIR_MARKER = ".orbax-checkpoint-tmp-"
# Tmp debris younger than this may be ANOTHER process's live async
# save (eval/serve opening a directory a trainer is writing) — leave
# it; it is never selectable as a step either way. Older debris is an
# abandoned interrupted save and is removed.
DEBRIS_TTL_S = 3600.0
CORRUPT_SUFFIX = ".corrupt"
_MANIFEST_DIRNAME = "manifests"
_MANIFEST_VERSION = 1
_CORRUPT_MANIFEST = object()


def _metrics():
    """The shared shellac_train_* resilience instruments (idempotent
    registration; imported lazily to keep this module importable
    without the obs wiring in scope)."""
    from shellac_tpu.training.resilience import ResilienceMetrics

    return ResilienceMetrics()


def _key_str(entry) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _leaf_rows(tree: Any) -> List[Tuple[str, Tuple[int, ...], str]]:
    """Sorted (path, shape, dtype) rows for every leaf. Sorted because
    orbax metadata comes back as nested dicts whose flattening order
    (sorted keys) differs from a dataclass pytree's field order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return sorted(
        (
            "/".join(_key_str(e) for e in path),
            tuple(int(s) for s in x.shape),
            str(x.dtype),
        )
        for path, x in flat
    )


def _rows_digest(rows: List[Tuple[str, Tuple[int, ...], str]]) -> str:
    canonical = json.dumps(
        [[p, list(s), d] for p, s, d in rows], separators=(",", ":")
    )
    return hashlib.sha256(canonical.encode()).hexdigest()


def latest_step_on_disk(directory: str) -> Optional[int]:
    """Newest committed step in a checkpoint directory, by directory
    scan alone — no CheckpointManager (with its background threads and
    startup sweeps) is built. For read-only peeks like the CLI's
    resume-skip computation; quarantined (`*.corrupt`) and uncommitted
    tmp directories are never counted."""
    root = os.path.abspath(directory)
    if not os.path.isdir(root):
        return None
    steps = [int(name) for name in os.listdir(root)
             if name.isdigit() and os.path.isdir(os.path.join(root, name))]
    return max(steps) if steps else None


class Checkpointer:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ):
        self._root = os.path.abspath(directory)
        self._manifest_dir = os.path.join(self._root, _MANIFEST_DIRNAME)
        # Steps this process has seen fail verification/restore; kept
        # alongside the on-disk rename so non-zero processes (which do
        # not touch the shared directory) exclude them identically.
        self._quarantined: set = set()
        # Newest async-saved step not yet known committed (gauge defers
        # to the next wait/save/close — a commit barrier).
        self._pending_last_good: Optional[int] = None
        self._sweep_interrupted_saves()
        self._mngr = ocp.CheckpointManager(
            self._root,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
            # Registered up front so `item_metadata` (verify, the
            # dtype-drift probe) works before any restore call.
            item_handlers=ocp.StandardCheckpointHandler(),
        )
        self._sweep_orphan_manifests()

    @property
    def directory(self) -> str:
        return str(self._mngr.directory)

    # ---- integrity: sweep / manifest / verify / quarantine -----------

    def _sweep_interrupted_saves(self) -> List[str]:
        """Remove uncommitted orbax tmp directories before the manager
        scans for steps. A kill mid-save leaves exactly this debris
        (commit is an atomic rename), and it must never shadow or be
        mistaken for a real step. Only debris older than DEBRIS_TTL_S
        is deleted: a fresh tmp dir may be a LIVE async save from a
        concurrent process (eval/serve opening the directory mid-
        train), and tmp names are unrestorable either way — hygiene
        can wait, clobbering a live write cannot be undone."""
        removed: List[str] = []
        if jax.process_index() != 0 or not os.path.isdir(self._root):
            return removed
        now = time.time()
        for name in sorted(os.listdir(self._root)):
            if TMP_DIR_MARKER not in name:
                continue
            path = os.path.join(self._root, name)
            try:
                if now - os.path.getmtime(path) < DEBRIS_TTL_S:
                    continue
            except OSError:
                continue
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
        return removed

    def _sweep_orphan_manifests(self) -> None:
        """Drop manifests whose step no longer exists (garbage-
        collected by max_to_keep, or a save that never committed).
        Same freshness guard as the tmp-dir sweep: a manifest is
        legitimately written BEFORE its async step directory commits,
        so a young step-less manifest may belong to a concurrent
        trainer's in-flight save — deleting it would silently strip
        that step of integrity checking forever."""
        if jax.process_index() != 0 or not os.path.isdir(self._manifest_dir):
            return
        now = time.time()
        for name in sorted(os.listdir(self._manifest_dir)):
            step = name[:-5] if name.endswith(".json") else None
            if step is None or not step.isdigit():
                continue
            path = os.path.join(self._manifest_dir, name)
            try:
                if now - os.path.getmtime(path) < DEBRIS_TTL_S:
                    continue
            except OSError:
                continue
            if not os.path.isdir(os.path.join(self._root, step)):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._manifest_dir, f"{int(step)}.json")

    def _write_manifest(self, step: int, state: Any) -> None:
        """Per-step integrity record, written atomically by process 0.
        Shapes/dtypes are host metadata — no device sync."""
        if jax.process_index() != 0:
            return
        rows = _leaf_rows(state)
        manifest = {
            "format": _MANIFEST_VERSION,
            "step": int(step),
            "leaf_count": len(rows),
            "tree_digest": _rows_digest(rows),
            "leaves": [[p, list(s), d] for p, s, d in rows],
        }
        os.makedirs(self._manifest_dir, exist_ok=True)
        tmp = self._manifest_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, self._manifest_path(step))
        # NB: no orphan sweep here — the async save's step directory
        # commits (atomic rename) after this write, so mid-run the
        # manifest legitimately precedes its step. Stale manifests from
        # max_to_keep GC are cleaned at the next construction.

    def _read_manifest(self, step: int):
        """The step's manifest dict, None when absent (pre-manifest
        checkpoint), or `_CORRUPT_MANIFEST` when present but
        unreadable (manifest writes are atomic, so that means rot)."""
        try:
            with open(self._manifest_path(step)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError):
            return _CORRUPT_MANIFEST

    def verify(self, step: int) -> Optional[str]:
        """Integrity-check a saved step: None if it passes, else the
        failure reason.

        Checks, cheapest first: the step is a finalized (committed)
        checkpoint, its orbax item metadata is readable, and — when an
        integrity manifest exists — leaf count, tree-structure digest,
        and every leaf's shape/dtype match. Array data is not re-read;
        data-level rot that survives these checks surfaces as a restore
        error, which the fallback walk treats identically.
        """
        if step in self._quarantined:
            return "step is quarantined"
        if step not in self._mngr.all_steps():
            return f"step {step} is not a finalized checkpoint"
        try:
            meta = self._mngr.item_metadata(step)
        except Exception as e:  # truncated ocdbt/zarr metadata, etc.
            return f"unreadable checkpoint metadata ({type(e).__name__}: {e})"
        if meta is None:
            return "checkpoint has no restorable item"
        manifest = self._read_manifest(step)
        if manifest is None:
            # Pre-manifest checkpoint: metadata readability is the
            # strongest check available.
            return None
        if manifest is _CORRUPT_MANIFEST:
            return "unreadable integrity manifest"
        rows = _leaf_rows(meta)
        if len(rows) != manifest["leaf_count"]:
            return (
                f"leaf count {len(rows)} != manifest "
                f"{manifest['leaf_count']}"
            )
        if _rows_digest(rows) != manifest["tree_digest"]:
            want = {p: (tuple(s), d) for p, s, d in manifest["leaves"]}
            for p, s, d in rows:
                if p not in want:
                    return f"unexpected leaf {p!r}"
                if want[p] != (s, d):
                    return (
                        f"leaf {p!r} is {s}/{d}, manifest says "
                        f"{want[p][0]}/{want[p][1]}"
                    )
            return "tree structure digest mismatch"
        return None

    def quarantine(self, step: int, reason: str = "") -> None:
        """Take a bad step out of circulation: the directory is renamed
        `<step>.corrupt` (kept for forensics, never re-selected by
        `latest_step`) and its manifest dropped. Only process 0 touches
        the shared directory; every process excludes the step locally.
        """
        self._quarantined.add(step)
        if jax.process_index() == 0:
            src = os.path.join(self._root, str(step))
            # A step number can be quarantined more than once (rolled
            # back past, re-saved, re-corrupted): each incident gets a
            # unique destination, or the rename would fail silently and
            # leave the bad step selectable as latest forever.
            dst = src + CORRUPT_SUFFIX
            n = 1
            while os.path.exists(dst):
                n += 1
                dst = f"{src}{CORRUPT_SUFFIX}.{n}"
            try:
                if os.path.isdir(src):
                    os.rename(src, dst)
                    with open(os.path.join(dst, "QUARANTINE.json"), "w") as f:
                        json.dump(
                            {"step": int(step), "reason": reason,
                             "time": time.time()}, f,
                        )
            except OSError:
                pass  # the local exclusion above still holds
            try:
                os.remove(self._manifest_path(step))
            except OSError:
                pass
        try:
            self._mngr.reload()
        except Exception:
            pass
        _metrics().quarantined.inc()

    # ---- save / restore ----------------------------------------------

    def save(self, step: int, state: Any, *, force: bool = False, wait: bool = False) -> bool:
        """Save (async by default). Returns True if a save was started."""
        # Filtered view: a quarantined step number re-reached after a
        # rollback must be RE-SAVED (and hosts whose stale listing
        # still shows the renamed dir must not skip the collective).
        if step in self.all_steps():
            if wait:
                self._mngr.wait_until_finished()
                self._flush_last_good()
            return False
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        # _mngr.save waits out the PREVIOUS async save before starting
        # this one, so only now is the pending step known committed.
        self._flush_last_good()
        if saved:
            # A re-save of a once-quarantined step number is a fresh,
            # healthy checkpoint — stop excluding it locally (the
            # corrupt directory keeps its .corrupt name regardless).
            self._quarantined.discard(step)
            self._write_manifest(step, state)
            # The last_good_step gauge moves only once the save COMMITS
            # (next wait/save/close): advancing it while the async
            # write is in flight would hide exactly the saves-are-
            # failing condition the gauge exists to expose.
            self._pending_last_good = int(step)
        if wait:
            self._mngr.wait_until_finished()
            self._flush_last_good()
        return saved

    def _flush_last_good(self) -> None:
        """Report the newest step whose save is known committed."""
        if self._pending_last_good is not None:
            _metrics().last_good_step.set(self._pending_last_good)
            self._pending_last_good = None

    def all_steps(self) -> List[int]:
        return [s for s in self._mngr.all_steps()
                if s not in self._quarantined]

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def restore(
        self,
        step: Optional[int] = None,
        *,
        abstract_state: Any = None,
        mesh=None,
        model_cfg=None,
        fallback: bool = False,
    ) -> Any:
        """Restore a TrainState.

        With `mesh` + `model_cfg` (or an `abstract_state` of
        jax.ShapeDtypeStructs carrying shardings), arrays are restored
        directly sharded; otherwise each leaf lands on the first local
        device — which also lets checkpoints SAVED sharded restore
        without any mesh (pod checkpoint → single-chip eval/generate,
        elastic down-scale).

        With `fallback=True`, a step that fails verification or restore
        is quarantined and the walk continues at the next-newest step,
        so one corrupt/partial checkpoint cannot brick resume.

        Multi-host: verification reads the shared checkpoint metadata,
        so every process reaches the same verdict and the walk stays in
        lockstep. A per-process I/O failure INSIDE a collective restore
        is the one divergence this cannot absorb — but that already
        stalls any collective orbax restore, walk or no walk; the
        external watchdog (heartbeat staleness) is the backstop there.
        """
        if fallback:
            return self._restore_fallback(
                step, abstract_state=abstract_state, mesh=mesh,
                model_cfg=model_cfg,
            )
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return self._restore_step(
            step, abstract_state=abstract_state, mesh=mesh,
            model_cfg=model_cfg,
        )

    def _restore_fallback(
        self, step: Optional[int], *, abstract_state, mesh, model_cfg
    ) -> Any:
        newest = True
        last_err: Optional[Exception] = None
        while True:
            steps = [s for s in self.all_steps()
                     if step is None or s <= step]
            if not steps:
                at = f" at or below step {step}" if step is not None else ""
                raise FileNotFoundError(
                    f"no intact checkpoints in {self.directory}{at}"
                ) from last_err
            s = max(steps)
            reason = self.verify(s)
            if reason is None:
                if self._request_mismatch(s, abstract_state):
                    # The CALLER asked for a different structure than
                    # was saved (wrong preset/config/EMA flag). The
                    # step is healthy — quarantining it (and then
                    # every older step, which all mismatch the same
                    # way) would rename a run's whole history .corrupt
                    # over a config typo; and orbax would silently
                    # restore wrong-shaped garbage rather than raise.
                    raise ValueError(
                        f"requested state structure does not match "
                        f"checkpoint step {s} in {self.directory} "
                        "(wrong preset/config/optimizer/EMA flags?); "
                        "refusing to restore"
                    )
                try:
                    out = self._restore_step(
                        s, abstract_state=abstract_state, mesh=mesh,
                        model_cfg=model_cfg,
                    )
                    if not newest:
                        _metrics().fallback_restores.inc()
                    _metrics().last_good_step.set(int(s))
                    return out
                except Exception as e:
                    last_err = e
                    reason = f"restore failed ({type(e).__name__}: {e})"
            self.quarantine(s, reason)
            newest = False

    def _request_mismatch(self, step: int, abstract_state: Any) -> bool:
        """True when a restore failure is the CALLER's fault: the
        requested abstract structure (leaf paths/shapes) differs from
        what the step verifiably holds. Dtypes are ignored — saved-vs-
        requested dtype drift is legitimate and handled in
        _restore_step. Unreadable saved-side records mean disk damage,
        never a request mismatch."""
        if abstract_state is None:
            return False
        manifest = self._read_manifest(step)
        if isinstance(manifest, dict):
            saved = [(p, tuple(sh)) for p, sh, _ in manifest["leaves"]]
        else:
            try:
                meta = self._mngr.item_metadata(step)
                saved = [(p, sh) for p, sh, _ in _leaf_rows(meta)]
            except Exception:
                return False
        want = [(p, sh) for p, sh, _ in _leaf_rows(abstract_state)]
        return sorted(saved) != sorted(want)

    def _restore_step(
        self, step: int, *, abstract_state, mesh, model_cfg
    ) -> Any:
        if abstract_state is None:
            return self._mngr.restore(step)
        if mesh is not None and model_cfg is not None:
            shardings = state_shardings(
                mesh, abstract_state, transformer.logical_axes(model_cfg)
            )
            abstract_state = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                abstract_state,
                shardings,
            )
        else:
            # Restoring WITHOUT a target mesh must still work for
            # checkpoints SAVED sharded (train on a pod, eval/generate
            # on one chip, or elastic down-scale): orbax requires
            # concrete target shardings for deserialization, so pin
            # leaves that carry none to the first LOCAL device (a
            # global jax.devices()[0] is non-addressable from other
            # processes). Leaves already carrying a sharding keep it —
            # the documented sharded-abstract_state path.
            one = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
            abstract_state = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype,
                    sharding=getattr(a, "sharding", None) or one,
                ),
                abstract_state,
            )
        try:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract_state)
            )
        except Exception:
            # Dtype drift (e.g. a checkpoint written with fp32 adam mu
            # restored under a bf16-mu config) is the one recoverable
            # failure: confirm the saved dtypes actually differ from the
            # requested ones before retrying, so corrupt/partial steps
            # surface their original error instead. The probe itself can
            # raise on a structurally corrupt step (truncated ocdbt
            # metadata) — guard it, so the ORIGINAL restore error
            # surfaces and a fallback walk can take over.
            try:
                meta = self._mngr.item_metadata(step)
                a_leaves = jax.tree.leaves(abstract_state)
                m_leaves = jax.tree.leaves(meta)
                drifted = len(a_leaves) == len(m_leaves) and any(
                    a.dtype != m.dtype
                    for a, m in zip(a_leaves, m_leaves)
                )
            except Exception:
                drifted = False
            if not drifted:
                raise
            restored = self._restore_saved_dtypes(step, abstract_state, meta)
            return jax.tree.map(
                lambda x, a: x.astype(a.dtype) if x.dtype != a.dtype else x,
                restored,
                abstract_state,
            )

    def _restore_saved_dtypes(self, step: int, abstract_state: Any, meta: Any) -> Any:
        as_saved = jax.tree.map(
            lambda a, m: jax.ShapeDtypeStruct(
                a.shape, m.dtype, sharding=getattr(a, "sharding", None)
            ),
            abstract_state,
            meta,
        )
        return self._mngr.restore(
            step, args=ocp.args.StandardRestore(as_saved)
        )

    def wait(self) -> None:
        self._mngr.wait_until_finished()
        self._flush_last_good()

    def close(self) -> None:
        """Close the underlying manager, WAITING for any in-flight
        async save first — closing mid-write would leave the newest
        step truncated (and then only the startup sweep/fallback walk
        would save the run)."""
        self._mngr.wait_until_finished()
        self._flush_last_good()
        self._mngr.close()
