"""Evaluation: token-weighted NLL / perplexity over a data stream.

One jitted eval step returns *summed* negative log-likelihood and token
count (not per-batch means), so the stream-level aggregate is exact even
with ragged masks or a final short batch.
"""

from __future__ import annotations

import math
from typing import Iterator, Optional

import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig
from shellac_tpu.models import transformer


def make_eval_step(model_cfg: ModelConfig, mesh=None, attn_impl: str = "auto"):
    """Build `eval_step(params, batch) -> (nll_sum fp32, token_count fp32)`."""

    def eval_step(params, batch):
        logits = transformer.forward(
            model_cfg, params, batch["inputs"], mesh=mesh, attn_impl=attn_impl
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(
            logp, batch["targets"][..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones_like(nll)
        mask = mask.astype(jnp.float32)
        return jnp.sum(nll * mask), jnp.sum(mask)

    # Nothing donatable: eval threads no state (params are reused every
    # batch and each batch arrives fresh from the host).
    return jax.jit(eval_step)  # shellac: ignore[SH001]


def evaluate(
    model_cfg: ModelConfig,
    params,
    data_iter: Iterator[dict],
    *,
    mesh=None,
    max_batches: Optional[int] = None,
) -> dict:
    """Returns {"loss", "perplexity", "tokens", "batches"} over the stream."""
    step = make_eval_step(model_cfg, mesh=mesh)
    total_nll = 0.0
    total_tok = 0.0
    batches = 0
    for batch in data_iter:
        nll, tok = step(params, batch)
        total_nll += float(nll)
        total_tok += float(tok)
        batches += 1
        if max_batches is not None and batches >= max_batches:
            break
    if total_tok == 0:
        raise ValueError("evaluate: empty data stream")
    loss = total_nll / total_tok
    return {
        "loss": loss,
        "perplexity": math.exp(min(loss, 30.0)),
        "tokens": int(total_tok),
        "batches": batches,
    }
