"""Training step construction.

`make_train_step` returns a jitted step with donated state; under a mesh
the state/batch shardings are attached so XLA partitions the whole step
(forward, backward, optimizer) and inserts collectives over ICI.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.training.losses import cross_entropy
from shellac_tpu.training.optimizer import make_optimizer
from shellac_tpu.training.train_state import TrainState, state_shardings
from shellac_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec


def batch_shardings(mesh: Mesh, rules=DEFAULT_RULES):
    """Sharding for {"inputs","targets","mask"}: batch over dp/fsdp, seq over sp."""
    spec = logical_to_spec(("batch", "seq"), rules)
    return NamedSharding(mesh, spec)


def init_train_state(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    key: jax.Array,
    mesh: Optional[Mesh] = None,
) -> TrainState:
    optimizer = make_optimizer(train_cfg)

    def init_fn(key):
        params = transformer.init_params(model_cfg, key)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            ema_params=(
                jax.tree.map(lambda p: p, params)
                if train_cfg.ema_decay is not None else None
            ),
        )

    if mesh is None:
        return jax.jit(init_fn)(key)
    abstract = jax.eval_shape(init_fn, key)
    shardings = state_shardings(mesh, abstract, transformer.logical_axes(model_cfg))
    return jax.jit(init_fn, out_shardings=shardings)(key)


def make_train_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    mesh: Optional[Mesh] = None,
    attn_impl: str = "auto",
    jit: bool = True,
    pipeline_microbatches: Optional[int] = None,
):
    """Build `train_step(state, batch) -> (state, metrics)`.

    batch: {"inputs": (B,S) i32, "targets": (B,S) i32, "mask": (B,S) f32?}.
    With grad_accum > 1 the leading batch dim is split into microbatches
    scanned sequentially, accumulating grads in fp32.
    """
    if train_cfg.quant is not None:
        # Opt into quantized compute for this train step only; the
        # model config itself (and any checkpoint metadata derived from
        # it) stays unquantized.
        model_cfg = model_cfg.replace(quant_training=train_cfg.quant).validate()
    optimizer = make_optimizer(train_cfg)
    accum = train_cfg.grad_accum

    fused_chunk = train_cfg.fused_loss_chunk
    if fused_chunk is not None and (
        model_cfg.logit_softcap is not None
        or model_cfg.vocab_size % fused_chunk
    ):
        # Softcap changes the logit function itself; indivisible vocabs
        # have no even chunking. Both fall back to the unfused path.
        fused_chunk = None

    def loss_fn(params, batch):
        if fused_chunk is not None:
            from shellac_tpu.training.losses import fused_cross_entropy

            hidden, aux = transformer.forward(
                model_cfg, params, batch["inputs"], mesh=mesh,
                attn_impl=attn_impl, segment_ids=batch.get("segment_ids"),
                pipeline_microbatches=pipeline_microbatches,
                return_aux=True, return_hidden=True,
            )
            w_out = transformer.output_weights(
                model_cfg, params, model_cfg.compute_dtype
            )
            loss, metrics = fused_cross_entropy(
                hidden, w_out, batch["targets"], batch.get("mask"),
                train_cfg.z_loss_weight, vocab_chunk=fused_chunk,
            )
            if model_cfg.moe is not None:
                metrics["moe_aux_loss"] = aux["aux"]
                metrics["moe_balance_loss"] = aux["balance_loss"]
                metrics["moe_router_z_loss"] = aux["router_z_loss"]
                metrics["moe_dropped_frac"] = aux["dropped_frac"]
                loss = loss + aux["aux"]
            return loss, metrics
        logits, aux = transformer.forward(
            model_cfg, params, batch["inputs"], mesh=mesh, attn_impl=attn_impl,
            segment_ids=batch.get("segment_ids"),
            pipeline_microbatches=pipeline_microbatches, return_aux=True,
        )
        loss, metrics = cross_entropy(
            logits, batch["targets"], batch.get("mask"), train_cfg.z_loss_weight
        )
        if model_cfg.moe is not None:
            metrics["moe_aux_loss"] = aux["aux"]
            metrics["moe_balance_loss"] = aux["balance_loss"]
            metrics["moe_router_z_loss"] = aux["router_z_loss"]
            metrics["moe_dropped_frac"] = aux["dropped_frac"]
            loss = loss + aux["aux"]
        return loss, metrics

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def compute_grads(params, batch):
        if accum <= 1:
            (_, metrics), grads = grad_fn(params, batch)
            return grads, metrics

        def micro(grads_acc, mb):
            (_, metrics), grads = grad_fn(params, mb)
            grads_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), grads_acc, grads
            )
            return grads_acc, metrics

        mbs = jax.tree.map(
            lambda x: x.reshape(accum, x.shape[0] // accum, *x.shape[1:]), batch
        )
        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        grads, metrics_stack = jax.lax.scan(micro, zero_grads, mbs)
        grads = jax.tree.map(lambda g: g / accum, grads)
        metrics = jax.tree.map(lambda m: jnp.mean(m, axis=0), metrics_stack)
        metrics["tokens"] = metrics["tokens"] * accum
        return grads, metrics

    def train_step(state: TrainState, batch):
        from shellac_tpu.utils.failure import all_finite, guard_update

        grads, metrics = compute_grads(state.params, batch)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_ema = state.ema_params
        if train_cfg.ema_decay is not None:
            d = train_cfg.ema_decay
            new_ema = jax.tree.map(
                lambda e, p: (e * d + p.astype(e.dtype) * (1.0 - d)).astype(
                    e.dtype
                ),
                state.ema_params, new_params,
            )
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        if train_cfg.skip_nonfinite_updates:
            # Guard on the loss too: an overflowed loss with grads that
            # still came out finite (clipping, a masked-out NaN term)
            # means the update direction is untrustworthy — skip it the
            # same way, so the host-side sentinel sees the anomaly in
            # `update_skipped` while the state stays clean.
            ok = all_finite(grads) & jnp.isfinite(metrics["loss"])
            new_params = guard_update(state.params, new_params, ok)
            new_opt_state = guard_update(state.opt_state, new_opt_state, ok)
            if new_ema is not None:
                new_ema = guard_update(state.ema_params, new_ema, ok)
            metrics["update_skipped"] = 1.0 - ok.astype(jnp.float32)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt_state,
            ema_params=new_ema,
        )
        return new_state, metrics

    if not jit:
        return train_step

    if mesh is None:
        return jax.jit(train_step, donate_argnums=(0,))

    # Attach explicit shardings so the compiled step is fully partitioned.
    def jit_with_shardings(state, batch):
        abstract_state = jax.eval_shape(lambda s: s, state)
        param_axes = transformer.logical_axes(model_cfg)
        st_sh = state_shardings(mesh, abstract_state, param_axes)
        b_sh = batch_shardings(mesh)
        batch_in = jax.tree.map(lambda _: b_sh, batch)
        return jax.jit(
            train_step,
            in_shardings=(st_sh, batch_in),
            out_shardings=(st_sh, None),
            donate_argnums=(0,),
        )

    return _LazyShardedStep(jit_with_shardings)


class _LazyShardedStep:
    """Defers jit-with-shardings until the first call, when the concrete
    state/batch structure (which depends on the optax chain) is known.
    Generic over the step arity (also reused by the LoRA step)."""

    def __init__(self, build):
        self._build = build
        self._jitted = None

    def __call__(self, *args):
        if self._jitted is None:
            self._jitted = self._build(*args)
        return self._jitted(*args)

    def lower(self, *args):
        return self._build(*args).lower(*args)
