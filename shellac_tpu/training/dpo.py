"""Direct Preference Optimization (DPO) fine-tuning.

Preference alignment without a reward model or RL loop: given paired
(chosen, rejected) completions, the policy is trained so that its
log-ratio against a frozen reference model ranks chosen above rejected
(Rafailov et al., 2023 — public method; the reference repo for this
project is empty, SURVEY.md §0).

TPU-first shape decisions:
  - Chosen and rejected rows CONCATENATE along batch for one forward
    (2B, S): one MXU-friendly batched pass instead of two half-size
    ones, and XLA shards it like any other batch.
  - The reference forward runs inside the same jitted step under
    `stop_gradient` — no separate eval step, no host round-trip; the
    reference params ride as a step argument (donating/closing over
    them would bake ~2x param constants into the executable).
  - Sequence log-probs reduce in fp32 over completion-masked targets.

Batch format (all (B, S)):
  {"chosen": i32 tokens, "rejected": i32 tokens,
   "chosen_mask": f32 — 1.0 on COMPLETION tokens (the targets being
   scored; prompt and pad positions 0.0), "rejected_mask": f32}
Rows are prompt + completion concatenated; masks select which target
positions count toward the sequence log-prob.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.training.optimizer import make_optimizer
from shellac_tpu.training.train_state import TrainState, state_shardings
from shellac_tpu.training.trainer import _LazyShardedStep, batch_shardings


@dataclass(frozen=True)
class DPOConfig:
    """DPO objective configuration.

    beta: inverse-temperature on the implicit reward (log-ratio scale).
    loss_type: "sigmoid" (DPO), "ipo" (Azar et al. squared objective on
      the raw log-ratio difference), or "hinge" (SLiC-style max-margin).
    label_smoothing: cDPO — probability the preference label is flipped
      (sigmoid loss only).
    reference_free: score against a uniform reference (log-ratios become
      plain policy log-probs); no ref_params forward runs.
    """

    beta: float = 0.1
    loss_type: str = "sigmoid"
    label_smoothing: float = 0.0
    reference_free: bool = False

    def validate(self) -> "DPOConfig":
        if self.loss_type not in ("sigmoid", "ipo", "hinge"):
            raise ValueError(
                f"loss_type={self.loss_type!r}; have sigmoid, ipo, hinge"
            )
        if not 0.0 <= self.label_smoothing < 0.5:
            raise ValueError(
                f"label_smoothing={self.label_smoothing} must be in [0, 0.5)"
            )
        if self.label_smoothing and self.loss_type != "sigmoid":
            raise ValueError(
                "label_smoothing is defined for the sigmoid loss only"
            )
        if self.beta <= 0:
            raise ValueError(f"beta={self.beta} must be positive")
        return self

    def replace(self, **kw) -> "DPOConfig":
        return dataclasses.replace(self, **kw)


def sequence_logprobs(
    model_cfg: ModelConfig, params, tokens, mask, *,
    mesh=None, attn_impl: str = "auto",
):
    """Summed next-token log-probs over masked target positions.

    tokens (B, S) i32; mask (B, S) f32 where mask[:, t] == 1.0 means the
    TARGET at position t (i.e. predicting tokens[:, t] from the prefix)
    counts. Position 0 can never be a target. Returns (B,) fp32.
    """
    logits = transformer.forward(
        model_cfg, params, tokens[:, :-1], mesh=mesh, attn_impl=attn_impl
    )  # (B, S-1, V) fp32
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    token_lp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.sum(token_lp * mask[:, 1:].astype(jnp.float32), axis=-1)


def dpo_loss(
    policy_chosen, policy_rejected, ref_chosen, ref_rejected,
    dpo_cfg: DPOConfig,
):
    """(loss (scalar), metrics dict) from per-sequence log-probs."""
    beta = dpo_cfg.beta
    chosen_ratio = policy_chosen - ref_chosen
    rejected_ratio = policy_rejected - ref_rejected
    h = chosen_ratio - rejected_ratio  # log-ratio difference
    if dpo_cfg.loss_type == "sigmoid":
        ls = dpo_cfg.label_smoothing
        losses = (
            -(1.0 - ls) * jax.nn.log_sigmoid(beta * h)
            - ls * jax.nn.log_sigmoid(-beta * h)
        )
    elif dpo_cfg.loss_type == "ipo":
        # Squared distance of the raw log-ratio difference from the
        # 1/(2*beta) target margin.
        losses = jnp.square(h - 1.0 / (2.0 * beta))
    else:  # hinge
        losses = jax.nn.relu(1.0 - beta * h)
    loss = jnp.mean(losses)
    metrics = {
        "loss": loss,
        "reward_chosen": jnp.mean(beta * chosen_ratio),
        "reward_rejected": jnp.mean(beta * rejected_ratio),
        "reward_margin": jnp.mean(beta * h),
        "accuracy": jnp.mean((h > 0).astype(jnp.float32)),
        "policy_chosen_logprob": jnp.mean(policy_chosen),
    }
    return loss, metrics


def make_dpo_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    dpo_cfg: DPOConfig,
    mesh: Optional[Mesh] = None,
    attn_impl: str = "auto",
    jit: bool = True,
):
    """Build `dpo_step(state, ref_params, batch) -> (state, metrics)`.

    ref_params is the frozen reference pytree (typically the SFT
    checkpoint the policy was initialized from); pass params with the
    same sharding as the trainable ones. With reference_free=True pass
    None.

    The state is DONATED: ref_params must not alias state.params'
    buffers (when starting DPO from the same checkpoint, copy one side,
    e.g. `jax.tree.map(jnp.copy, params)` — XLA rejects
    `f(donate(a), a)` at call time otherwise).
    """
    dpo_cfg = dpo_cfg.validate()
    optimizer = make_optimizer(train_cfg)

    def both_logprobs(params, batch):
        # One (2B, S) forward scores chosen and rejected together.
        tokens = jnp.concatenate([batch["chosen"], batch["rejected"]], 0)
        mask = jnp.concatenate(
            [batch["chosen_mask"], batch["rejected_mask"]], 0
        )
        lp = sequence_logprobs(
            model_cfg, params, tokens, mask, mesh=mesh, attn_impl=attn_impl
        )
        b = batch["chosen"].shape[0]
        return lp[:b], lp[b:]

    def loss_fn(params, ref_params, batch):
        pc, pr = both_logprobs(params, batch)
        if dpo_cfg.reference_free:
            rc = jnp.zeros_like(pc)
            rr = jnp.zeros_like(pr)
        else:
            rc, rr = jax.tree.map(
                jax.lax.stop_gradient, both_logprobs(ref_params, batch)
            )
        return dpo_loss(pc, pr, rc, rr, dpo_cfg)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def dpo_step(state: TrainState, ref_params, batch):
        from shellac_tpu.utils.failure import all_finite, guard_update

        (_, metrics), grads = grad_fn(state.params, ref_params, batch)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_ema = state.ema_params
        if train_cfg.ema_decay is not None:
            d = train_cfg.ema_decay
            new_ema = jax.tree.map(
                lambda e, p: (e * d + p.astype(e.dtype) * (1.0 - d)).astype(
                    e.dtype
                ),
                state.ema_params, new_params,
            )
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        if train_cfg.skip_nonfinite_updates:
            ok = all_finite(grads)
            new_params = guard_update(state.params, new_params, ok)
            new_opt_state = guard_update(state.opt_state, new_opt_state, ok)
            if new_ema is not None:
                new_ema = guard_update(state.ema_params, new_ema, ok)
            metrics["update_skipped"] = 1.0 - ok.astype(jnp.float32)
        new_state = TrainState(
            step=state.step + 1, params=new_params,
            opt_state=new_opt_state, ema_params=new_ema,
        )
        return new_state, metrics

    if not jit:
        return dpo_step

    if mesh is None:
        return jax.jit(dpo_step, donate_argnums=(0,))

    def jit_with_shardings(state, ref_params, batch):
        abstract_state = jax.eval_shape(lambda s: s, state)
        param_axes = transformer.logical_axes(model_cfg)
        st_sh = state_shardings(mesh, abstract_state, param_axes)
        ref_sh = None if ref_params is None else st_sh.params
        b_sh = batch_shardings(mesh)
        batch_in = jax.tree.map(lambda _: b_sh, batch)
        return jax.jit(
            dpo_step,
            in_shardings=(st_sh, ref_sh, batch_in),
            out_shardings=(st_sh, None),
            donate_argnums=(0,),
        )

    return _LazyShardedStep(jit_with_shardings)


def preference_batches(
    path: str,
    batch_size: int,
    max_len: int,
    *,
    tokenizer=None,
    loop: bool = True,
    seed: int = 0,
    skip: int = 0,
):
    """Iterator of DPO batches from a JSONL file of preference pairs.

    skip: number of leading batches to drop — the deterministic
    per-epoch shuffle makes this reproduce the stream position a
    resumed run left off at.

    Each line holds {"prompt": ..., "chosen": ..., "rejected": ...}
    where the fields are either token-id lists or strings (strings need
    `tokenizer`). Rows become prompt+completion sequences right-padded
    to max_len with completion-target masks; over-long rows keep the
    full completion and truncate the prompt's LEFT (the completion is
    what is being scored).
    """
    import json as _json

    import numpy as np

    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = _json.loads(line)

            def ids(v):
                if isinstance(v, str):
                    if tokenizer is None:
                        raise ValueError(
                            "text fields need a tokenizer; pre-tokenized "
                            "rows hold token-id lists"
                        )
                    return list(tokenizer.encode(v))
                return list(v)

            rows.append((ids(r["prompt"]), ids(r["chosen"]),
                         ids(r["rejected"])))
    if not rows:
        raise ValueError(f"no preference pairs in {path}")
    if len(rows) < batch_size:
        raise ValueError(
            f"{path} holds {len(rows)} pairs < batch_size={batch_size}; "
            "the batcher drops ragged tails, so this would yield nothing"
        )

    def render(prompt, completion):
        comp = completion[:max_len - 1]  # >= 1 prompt token must remain
        keep = max_len - len(comp)
        p = prompt[-keep:] if len(prompt) > keep else prompt
        toks = p + comp
        mask = [0.0] * len(p) + [1.0] * len(comp)
        pad = max_len - len(toks)
        return toks + [0] * pad, mask + [0.0] * pad

    rng = np.random.RandomState(seed)
    order = np.arange(len(rows))
    while True:
        rng.shuffle(order)
        for start in range(0, len(order) - batch_size + 1, batch_size):
            if skip > 0:
                skip -= 1
                continue
            idx = order[start:start + batch_size]
            c_t, c_m, r_t, r_m = [], [], [], []
            for i in idx:
                prompt, chosen, rejected = rows[i]
                t, m = render(prompt, chosen)
                c_t.append(t)
                c_m.append(m)
                t, m = render(prompt, rejected)
                r_t.append(t)
                r_m.append(m)
            yield {
                "chosen": jnp.asarray(np.asarray(c_t, np.int32)),
                "chosen_mask": jnp.asarray(np.asarray(c_m, np.float32)),
                "rejected": jnp.asarray(np.asarray(r_t, np.int32)),
                "rejected_mask": jnp.asarray(np.asarray(r_m, np.float32)),
            }
        if not loop:
            return


def fit_dpo(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    dpo_cfg: DPOConfig,
    data_iter,
    *,
    init_params=None,
    ref_params=None,
    mesh: Optional[Mesh] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 500,
    log_path: Optional[str] = None,
    log_every: int = 10,
    resume: bool = True,
):
    """DPO training loop; returns the final TrainState.

    init_params: starting policy weights (typically a restored SFT
    checkpoint); random init when None. ref_params: frozen reference;
    defaults to a COPY of the starting policy (the standard DPO setup).
    Checkpoints hold the full TrainState under checkpoint_dir and
    resume like fit().
    """
    from shellac_tpu.training.optimizer import make_optimizer as _mk_opt
    from shellac_tpu.training.trainer import init_train_state
    from shellac_tpu.utils.metrics import MetricsLogger
    from shellac_tpu.utils.tracing import StepTimer

    dpo_cfg = dpo_cfg.validate()
    key = jax.random.PRNGKey(train_cfg.seed)

    def init_from(params):
        # Optimizer state around PROVIDED weights — never materializes
        # the random init just to throw it away.
        opt = _mk_opt(train_cfg)
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=opt.init(params),
            ema_params=(jax.tree.map(lambda p: p, params)
                        if train_cfg.ema_decay is not None else None),
        )

    ckpt = None
    if checkpoint_dir is not None:
        from shellac_tpu.training.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir)
    resuming = ckpt is not None and resume and ckpt.latest_step() is not None

    if resuming:
        abstract = jax.eval_shape(
            lambda: init_train_state(model_cfg, train_cfg, key, mesh=mesh)
        )
        state = ckpt.restore(
            abstract_state=abstract, mesh=mesh, model_cfg=model_cfg
        )
    elif init_params is not None:
        if mesh is None:
            state = jax.jit(init_from)(init_params)
        else:
            abstract = jax.eval_shape(init_from, init_params)
            shardings = state_shardings(
                mesh, abstract, transformer.logical_axes(model_cfg)
            )
            state = jax.jit(init_from, out_shardings=shardings)(init_params)
    else:
        state = init_train_state(model_cfg, train_cfg, key, mesh=mesh)

    if ref_params is None and not dpo_cfg.reference_free:
        # The reference anchors to the ORIGINAL starting policy — on
        # resume it must NOT be rebuilt from the half-trained restored
        # weights (the KL anchor would move every restart). Copies
        # throughout: the step donates the state, and XLA rejects a
        # donated buffer aliased by another argument.
        if init_params is not None:
            ref_params = jax.tree.map(jnp.copy, init_params)
        elif resuming:
            # Random-init base: regenerate it from the run's seed — the
            # same weights the original invocation started from.
            ref_params = init_train_state(
                model_cfg, train_cfg, key, mesh=mesh
            ).params
        else:
            ref_params = jax.tree.map(jnp.copy, state.params)

    step_fn = make_dpo_step(model_cfg, train_cfg, dpo_cfg, mesh=mesh)
    logger = MetricsLogger(log_path, every=1)
    timer = StepTimer()

    step = int(jax.device_get(state.step))
    while step < train_cfg.total_steps:
        try:
            batch = next(data_iter)
        except StopIteration:
            break
        state, metrics = step_fn(state, ref_params, batch)
        step += 1
        if step % log_every == 0 or step >= train_cfg.total_steps:
            host_metrics = {k: jax.device_get(v) for k, v in metrics.items()}
            dt = timer.tick()
            if dt is not None:
                host_metrics["steps_per_sec"] = log_every / dt
            logger.log(step, host_metrics)
        if ckpt is not None and step % checkpoint_every == 0:
            ckpt.save(step, state)

    if ckpt is not None:
        ckpt.save(int(jax.device_get(state.step)), state, force=True,
                  wait=True)
    logger.close()
    return state
