"""Tokenizers: dependency-free byte-level, plus an optional HF wrapper.

The byte tokenizer is the zero-infrastructure path: UTF-8 bytes are the
ids (0..255), with BOS/EOS/PAD appended above. It needs no vocabulary
file, no network, and round-trips any text exactly — the right default
for tests, smoke corpora, and byte-level models.

`HFTokenizer` adapts a HuggingFace `transformers` tokenizer (loaded from
a local path — this environment has no egress) to the same interface.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np


class ByteTokenizer:
    """UTF-8 bytes as token ids; specials above the byte range."""

    BOS = 256
    EOS = 257
    PAD = 258

    vocab_size = 259

    def encode(self, text: str, *, bos: bool = False, eos: bool = False) -> np.ndarray:
        ids: List[int] = list(text.encode("utf-8"))
        if bos:
            ids.insert(0, self.BOS)
        if eos:
            ids.append(self.EOS)
        return np.asarray(ids, dtype=np.int32)

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(int(i) for i in np.asarray(ids).reshape(-1) if int(i) < 256)
        return data.decode("utf-8", errors="replace")

    def token_bytes(self, tid: int):
        """Exact surface bytes of one token (None for specials).

        Constrained decoding's byte-level DFA needs this: a byte token
        carrying part of a multi-byte UTF-8 character is NOT decodable
        on its own (decode() would replace it with U+FFFD), but it
        advances the byte automaton exactly."""
        return bytes([tid]) if 0 <= tid < 256 else None

    def encode_documents(
        self, docs: Iterable[str], *, eos_between: bool = True
    ) -> np.ndarray:
        """Concatenate documents into one token stream (EOS-separated)."""
        parts = []
        for d in docs:
            parts.append(self.encode(d))
            if eos_between:
                parts.append(np.asarray([self.EOS], np.int32))
        if not parts:
            return np.zeros((0,), np.int32)
        return np.concatenate(parts)


class HFTokenizer:
    """Adapter over a local HuggingFace tokenizer directory."""

    def __init__(self, path: str):
        try:
            from transformers import AutoTokenizer
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "HFTokenizer needs the `transformers` package"
            ) from e
        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)

    def encode(self, text: str, *, bos: bool = False, eos: bool = False) -> np.ndarray:
        ids = self._tok.encode(text, add_special_tokens=False)
        if bos and self._tok.bos_token_id is not None:
            ids = [self._tok.bos_token_id] + ids
        if eos and self._tok.eos_token_id is not None:
            ids = ids + [self._tok.eos_token_id]
        return np.asarray(ids, dtype=np.int32)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(
            [int(i) for i in np.asarray(ids).reshape(-1)],
            skip_special_tokens=True,
        )

    def encode_documents(
        self, docs: Iterable[str], *, eos_between: bool = True
    ) -> np.ndarray:
        parts = []
        eos_id = self._tok.eos_token_id
        for d in docs:
            parts.append(self.encode(d))
            if eos_between and eos_id is not None:
                parts.append(np.asarray([eos_id], np.int32))
        if not parts:
            return np.zeros((0,), np.int32)
        return np.concatenate(parts)


def get_tokenizer(spec: str = "byte"):
    """"byte", a trained BPE .json file, or a local HF tokenizer dir."""
    if spec == "byte":
        return ByteTokenizer()
    if spec.endswith(".json"):
        return BPETokenizer(spec)
    return HFTokenizer(spec)


class BPETokenizer:
    """Byte-level BPE trained on YOUR corpus (the `tokenizers` library
    does the heavy lifting; this wraps it in the framework interface).

    Train once with `BPETokenizer.train(files, vocab_size)`, save to a
    single JSON file, reload anywhere with `BPETokenizer(path)`. The
    byte-level pre-tokenizer guarantees lossless round-trips for
    arbitrary text (no unknown tokens).
    """

    BOS_TOKEN = "<|bos|>"
    EOS_TOKEN = "<|eos|>"
    PAD_TOKEN = "<|pad|>"

    def __init__(self, path: str):
        from tokenizers import Tokenizer

        self._tok = Tokenizer.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        self.bos_id = self._tok.token_to_id(self.BOS_TOKEN)
        self.eos_id = self._tok.token_to_id(self.EOS_TOKEN)
        self.pad_id = self._tok.token_to_id(self.PAD_TOKEN)
        missing = [t for t, i in (
            (self.BOS_TOKEN, self.bos_id), (self.EOS_TOKEN, self.eos_id),
            (self.PAD_TOKEN, self.pad_id),
        ) if i is None]
        if missing:
            raise ValueError(
                f"{path} lacks the specials {missing} — not a tokenizer "
                "trained by BPETokenizer.train (for HF tokenizer.json "
                "files, pass the tokenizer DIRECTORY instead)"
            )

    @classmethod
    def train(
        cls, files: Sequence[str], vocab_size: int, out_path: str,
    ) -> "BPETokenizer":
        """Train byte-level BPE on text files; writes out_path (JSON)."""
        from tokenizers import Tokenizer, decoders, models, pre_tokenizers
        from tokenizers.trainers import BpeTrainer

        tok = Tokenizer(models.BPE())
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
        trainer = BpeTrainer(
            vocab_size=vocab_size,
            special_tokens=[cls.BOS_TOKEN, cls.EOS_TOKEN, cls.PAD_TOKEN],
            initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        )
        tok.train(list(files), trainer)
        tok.save(out_path)
        return cls(out_path)

    def encode(self, text: str, *, bos: bool = False,
               eos: bool = False) -> np.ndarray:
        ids = self._tok.encode(text).ids
        if bos:
            ids = [self.bos_id] + ids
        if eos:
            ids = ids + [self.eos_id]
        return np.asarray(ids, dtype=np.int32)

    def decode(self, ids: Sequence[int]) -> str:
        specials = {self.bos_id, self.eos_id, self.pad_id}
        return self._tok.decode(
            [int(i) for i in np.asarray(ids).reshape(-1)
             if int(i) not in specials]
        )

    def encode_documents(
        self, docs: Iterable[str], *, eos_between: bool = True
    ) -> np.ndarray:
        parts = []
        for d in docs:
            parts.append(self.encode(d))
            if eos_between:
                parts.append(np.asarray([self.eos_id], np.int32))
        if not parts:
            return np.zeros((0,), np.int32)
        return np.concatenate(parts)
