from shellac_tpu.training.losses import cross_entropy
from shellac_tpu.training.optimizer import make_optimizer, make_schedule
from shellac_tpu.training.train_state import TrainState, state_shardings, state_specs
from shellac_tpu.training.trainer import (
    batch_shardings,
    init_train_state,
    make_train_step,
)
from shellac_tpu.training.evaluate import evaluate, make_eval_step
from shellac_tpu.training.loop import fit
from shellac_tpu.training.resilience import (
    Anomaly,
    AnomalySentinel,
    ResilienceMetrics,
)
from shellac_tpu.training.lora import (
    LoRAConfig,
    LoRAState,
    init_lora,
    init_lora_state,
    make_lora_train_step,
    merge_lora,
)

__all__ = [
    "Anomaly",
    "AnomalySentinel",
    "ResilienceMetrics",
    "evaluate",
    "make_eval_step",
    "LoRAConfig",
    "LoRAState",
    "init_lora",
    "init_lora_state",
    "make_lora_train_step",
    "merge_lora",
    "cross_entropy",
    "make_optimizer",
    "make_schedule",
    "TrainState",
    "state_shardings",
    "state_specs",
    "init_train_state",
    "make_train_step",
    "batch_shardings",
    "fit",
]
