"""shellac_tpu.analysis — a JAX/TPU-aware static lint engine.

AST-level checks for the silent hazards an XLA-compiled codebase
accumulates: missing buffer donation on state-threading jits (SH001),
host syncs in jitted code or decode hot loops (SH002), trace-time
nondeterminism (SH003), leftover debug aids (SH004), set-iteration
order dependence (SH005), dead config flags (SH006), and sharding-
constraint asymmetry between paired paths (SH007).

Run it with `python -m shellac_tpu.analysis <paths>` or
`python -m shellac_tpu lint <paths>`; see docs/static_analysis.md.
"""

from shellac_tpu.analysis.engine import (
    Finding,
    all_rules,
    lint_files,
    lint_paths,
)

__all__ = ["Finding", "all_rules", "lint_files", "lint_paths"]
