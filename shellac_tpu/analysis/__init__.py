"""shellac_tpu.analysis — a JAX/TPU-aware static lint engine.

AST-level checks for the silent hazards an XLA-compiled codebase
accumulates: missing buffer donation on state-threading jits (SH001),
host syncs in jitted code or decode hot loops (SH002), trace-time
nondeterminism (SH003), leftover debug aids (SH004), set-iteration
order dependence (SH005), dead config flags (SH006), and sharding-
constraint asymmetry between paired paths (SH007).

The concurrency pass (`concurrency.py`) covers the threaded serving
stack: unguarded cross-thread state (SH010), callbacks invoked under a
held lock (SH011), lock-order inversion (SH012), blocking calls under
a lock (SH013), and non-daemon threads with no join-on-close path
(SH014) — with `# shellac: guarded-by(<lock>)` annotations that both
document and feed the held-lock model. The contract pass
(`contracts.py`) checks cross-layer drift: every `shellac_*` metric
name declared in an obs bundle and cataloged in docs/observability.md
(SH015), every flight-recorder event kind in the docs' event catalog
(SH016).

Run it with `python -m shellac_tpu.analysis <paths>` or
`python -m shellac_tpu lint <paths>`; see docs/static_analysis.md.
"""

from shellac_tpu.analysis.engine import (
    Finding,
    all_rules,
    lint_files,
    lint_paths,
)

__all__ = ["Finding", "all_rules", "lint_files", "lint_paths"]
