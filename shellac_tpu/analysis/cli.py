"""Command-line front end for the lint engine.

Two equivalent entry points:

    python -m shellac_tpu.analysis [paths...] [options]
    python -m shellac_tpu lint [paths...] [options]

Exit status: 0 when the tree is clean, 1 when findings (or parse
errors) exist, 2 on bad usage. `--format json` emits a machine-readable
report that `scripts/lint_report.py` can diff for "no new findings"
CI gating.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from shellac_tpu.analysis.engine import all_rules, lint_paths

REPORT_VERSION = 1


def _split_codes(value: Optional[str]):
    if not value:
        return None
    return [c.strip() for c in value.split(",") if c.strip()]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shellac_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "paths", nargs="*", default=["shellac_tpu"],
        help=".py files and/or directories to lint "
             "(default: shellac_tpu)",
    )
    p.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="human text (default) or a JSON report",
    )
    p.add_argument(
        "--select", metavar="CODES",
        help="comma-separated rule codes to run (default: all)",
    )
    p.add_argument(
        "--ignore", metavar="CODES",
        help="comma-separated rule codes to skip",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def report_dict(findings, paths) -> dict:
    by_rule: dict = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "version": REPORT_VERSION,
        "paths": list(paths),
        "findings": [f.to_dict() for f in findings],
        "summary": {
            "findings": len(findings),
            "by_rule": dict(sorted(by_rule.items())),
        },
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for code, cls in all_rules().items():
            print(f"{code} {cls.name}: {cls.summary}")
        return 0

    try:
        findings = lint_paths(
            args.paths,
            select=_split_codes(args.select),
            ignore=_split_codes(args.ignore),
        )
    except (OSError, KeyError, UnicodeDecodeError) as e:
        # Unreadable/mis-encoded targets and unknown rule codes are
        # usage errors (2), distinct from "findings exist" (1).
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(report_dict(findings, args.paths), indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"{n} finding{'s' if n != 1 else ''}"
              if n else "clean: no findings")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
