"""Cross-layer drift contracts: metrics and recorder-event catalogs.

The repo maintains two human-readable catalogs by hand:
`docs/observability.md` lists every `shellac_*` metric family and the
flight-recorder event catalog. Until these rules, nothing checked that
the code and the catalogs agree — a new counter or event kind shipped
in a PR quietly drifts out of the operator docs. These ProjectRules
close the loop:

- SH015: every literal `shellac_*` metric name passed to
  `.counter(/.gauge(/.histogram(` in non-test code must (a) when
  registered outside `obs/`, also appear in an `obs/` module — the
  bundle layer owns the namespace — and (b) appear in
  `docs/observability.md`.
- SH016: every literal flight-recorder event kind (the second argument
  of a `.record(trace_id, "kind", ...)` call) must appear backticked
  in the docs' event catalog.

Both halves gate on their contract source being present in the scanned
tree: the docs file is located by walking up from the scanned paths
(only paths that exist on disk are consulted, so in-memory test
snippets never bind to the live repo's docs), and the obs-namespace
half only runs when the scan includes `obs/` modules. `python -m
shellac_tpu.analysis shellac_tpu` from the repo root therefore checks
the real contract, while fixture trees built under tmp dirs carry
their own miniature `docs/observability.md`.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from shellac_tpu.analysis.engine import (
    FileContext,
    Finding,
    ProjectRule,
    register,
)

_INSTRUMENT_METHODS = {"counter", "gauge", "histogram"}
_DOC_RELPATH = Path("docs") / "observability.md"
#: Recorder event kinds are short kebab-case words; anything else as a
#: second argument to `.record()` is some other API.
_KIND_RE = re.compile(r"^[a-z][a-z0-9-]*$")


def _find_doc(ctxs: Sequence[FileContext]) -> Optional[str]:
    """docs/observability.md text, located by walking up from scanned
    paths that actually exist on disk (in-memory snippets with fake
    paths never resolve, so unit fixtures stay hermetic)."""
    for ctx in ctxs:
        p = Path(ctx.path)
        if not p.exists():
            continue
        for parent in p.resolve().parents:
            doc = parent / _DOC_RELPATH
            if doc.is_file():
                try:
                    return doc.read_text(encoding="utf-8")
                except OSError:
                    return None
    return None


def _in_obs(ctx: FileContext) -> bool:
    return "obs" in Path(ctx.path).parts


# ---------------------------------------------------------------------
# SH015 — metric name drift
# ---------------------------------------------------------------------


@register
class MetricCatalogDrift(ProjectRule):
    code = "SH015"
    name = "metric-catalog-drift"
    summary = (
        "a literal shellac_* metric name registered in code is missing "
        "from the obs namespace layer or from the "
        "docs/observability.md catalog — the operator docs have "
        "drifted from the code"
    )

    def _emits(self, ctx: FileContext
               ) -> Iterable[Tuple[ast.Call, str]]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _INSTRUMENT_METHODS
                    and node.args):
                continue
            a0 = node.args[0]
            if (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)
                    and a0.value.startswith("shellac_")):
                yield node, a0.value

    def check_project(self, ctxs: Sequence[FileContext]
                      ) -> Iterable[Finding]:
        obs_present = any(_in_obs(c) for c in ctxs)
        obs_literals: Set[str] = set()
        if obs_present:
            for ctx in ctxs:
                if not _in_obs(ctx):
                    continue
                for node in ast.walk(ctx.tree):
                    if (isinstance(node, ast.Constant)
                            and isinstance(node.value, str)
                            and node.value.startswith("shellac_")):
                        obs_literals.add(node.value)
        doc = _find_doc(ctxs)
        for ctx in ctxs:
            if ctx.is_test:
                continue
            for node, name in self._emits(ctx):
                if (obs_present and not _in_obs(ctx)
                        and name not in obs_literals):
                    yield self.finding(
                        ctx, node,
                        f"metric {name!r} is registered outside obs/ "
                        "and declared in no obs module — the bundle "
                        "layer owns the shellac_* namespace; move the "
                        "registration (or mirror the name) into an "
                        "obs bundle",
                    )
                if doc is not None and name not in doc:
                    yield self.finding(
                        ctx, node,
                        f"metric {name!r} is not cataloged in "
                        "docs/observability.md — add it to the metric "
                        "catalog so the operator docs track the code",
                    )


# ---------------------------------------------------------------------
# SH016 — flight-recorder event-kind drift
# ---------------------------------------------------------------------


@register
class EventCatalogDrift(ProjectRule):
    code = "SH016"
    name = "event-catalog-drift"
    summary = (
        "a flight-recorder event kind recorded in code does not appear "
        "in docs/observability.md's event catalog — /debug timelines "
        "would carry events the runbook never names"
    )

    def _kinds(self, ctx: FileContext
               ) -> Iterable[Tuple[ast.Call, str]]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "record"
                    and len(node.args) >= 2):
                continue
            a1 = node.args[1]
            if (isinstance(a1, ast.Constant)
                    and isinstance(a1.value, str)
                    and _KIND_RE.match(a1.value)):
                yield node, a1.value

    def check_project(self, ctxs: Sequence[FileContext]
                      ) -> Iterable[Finding]:
        doc = _find_doc(ctxs)
        if doc is None:
            return
        for ctx in ctxs:
            if ctx.is_test:
                continue
            for node, kind in self._kinds(ctx):
                if f"`{kind}`" not in doc:
                    yield self.finding(
                        ctx, node,
                        f"recorder event kind {kind!r} is not in "
                        "docs/observability.md's event catalog — add "
                        "a catalog row (event, src, recorded-at, "
                        "fields) so timelines stay self-describing",
                    )
