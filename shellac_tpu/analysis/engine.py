"""Core of the static lint engine: findings, suppressions, registry.

The engine is deliberately jax-free: it parses Python source with `ast`
and never imports the modules it checks, so `lint` runs in milliseconds
on a CPU-only CI box before any test environment exists. Rules live in
`shellac_tpu.analysis.rules`; this module provides the machinery they
plug into:

- `Finding`: one diagnostic, with a `file:line:col` span.
- `Suppression` parsing: `# shellac: ignore[SH001]` trailing a code
  line silences that line; the same comment standing alone at column 0
  silences the named rules for the whole file. A comment may name
  several rules: `# shellac: ignore[SH001,SH004]`.
- `Rule` / `ProjectRule`: per-file AST rules and whole-tree rules
  (SH006 needs every file to decide whether a config field is read).
- `lint_paths` / `lint_files`: the entry points the CLI and the test
  suite share.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

#: Rule code for files the engine cannot parse at all.
PARSE_ERROR = "SH000"

_SUPPRESS_RE = re.compile(
    r"#\s*shellac:\s*ignore\[([A-Za-z0-9_,\s]+)\]"
)


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One diagnostic: rule code + location + human message."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Suppressions:
    """Per-file map of `# shellac: ignore[...]` comments.

    Two scopes, by comment placement:
    - trailing a code line -> suppresses the named rules on that line;
    - alone at column 0    -> suppresses the named rules file-wide.
    """

    def __init__(self, source: str):
        self.file_level: set = set()
        self.by_line: Dict[int, set] = {}
        # Tokenize rather than regex-scan raw lines so a marker inside
        # a string literal (e.g. worker source embedded in a test) can
        # never suppress rules in the enclosing file.
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(source).readline)
            )
        except (tokenize.TokenError, IndentationError):
            return  # unparsable source surfaces as SH000, not here
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            lineno, col = tok.start
            if col == 0:
                self.file_level |= rules
            else:
                self.by_line.setdefault(lineno, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        return rule in self.file_level or rule in self.by_line.get(line, ())


class FileContext:
    """One parsed file handed to rules: path, source, tree, test flag."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppressions = Suppressions(source)
        name = Path(path).name
        parts = Path(path).parts
        self.is_test = (
            name.startswith("test_")
            or name == "conftest.py"
            or "tests" in parts
        )


class Rule:
    """A per-file AST check. Subclasses set `code`/`name`/`summary` and
    implement `check(ctx)` yielding Findings (suppressions are applied
    by the engine, not the rule)."""

    code: str = ""
    name: str = ""
    summary: str = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        return Finding(
            path=ctx.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            rule=self.code,
            message=message,
        )


class ProjectRule(Rule):
    """A whole-tree check: sees every FileContext at once (SH006 must
    know all read sites before calling a config field dead)."""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator adding a Rule subclass to the global registry."""
    if not cls.code:
        raise ValueError(f"rule class {cls.__name__} has no code")
    if cls.code in _REGISTRY:
        raise ValueError(f"duplicate rule code {cls.code}")
    _REGISTRY[cls.code] = cls
    return cls


def all_rules() -> Dict[str, type]:
    # Importing the rule modules registers them; deferred so engine
    # stays cheap to import and free of cycles.
    from shellac_tpu.analysis import (  # noqa: F401
        concurrency,
        contracts,
        rules,
    )

    return dict(sorted(_REGISTRY.items()))


def iter_python_files(paths: Sequence[str]) -> List[Path]:
    """Expand files/directories into a sorted, de-duplicated .py list."""
    seen = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            found = sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            found = [p]
        else:
            raise FileNotFoundError(f"not a .py file or directory: {raw}")
        for f in found:
            if f not in seen:
                seen.append(f)
    return seen


def _selected(codes: Dict[str, type], select: Optional[Sequence[str]],
              ignore: Optional[Sequence[str]]) -> Dict[str, type]:
    out = dict(codes)
    if select:
        unknown = set(select) - set(out)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        out = {c: r for c, r in out.items() if c in set(select)}
    if ignore:
        unknown = set(ignore) - set(codes)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        out = {c: r for c, r in out.items() if c not in set(ignore)}
    return out


def lint_files(sources: Dict[str, str], select: Optional[Sequence[str]] = None,
               ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint a {path: source} mapping (the testable core of the engine)."""
    rule_classes = _selected(all_rules(), select, ignore)
    rules = [cls() for cls in rule_classes.values()]

    ctxs: List[FileContext] = []
    findings: List[Finding] = []
    for path, source in sources.items():
        try:
            ctxs.append(FileContext(path, source))
        except SyntaxError as e:
            findings.append(Finding(
                path=path, line=e.lineno or 1, col=(e.offset or 0) + 1,
                rule=PARSE_ERROR, message=f"cannot parse: {e.msg}",
            ))

    by_path = {c.path: c for c in ctxs}
    for rule in rules:
        if isinstance(rule, ProjectRule):
            raw = rule.check_project(ctxs)
        else:
            raw = (f for ctx in ctxs for f in rule.check(ctx))
        for f in raw:
            ctx = by_path.get(f.path)
            if ctx is not None and ctx.suppressions.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    return sorted(findings)


def lint_paths(paths: Sequence[str], select: Optional[Sequence[str]] = None,
               ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint files and directory trees from disk."""
    files = iter_python_files(paths)
    sources = {}
    for f in files:
        sources[str(f)] = f.read_text(encoding="utf-8")
    return lint_files(sources, select=select, ignore=ignore)
