"""Concurrency-contract rules: lock discipline for the threaded stack.

PRs 2-14 grew roughly ten threaded subsystems — the supervisor's
scheduler generations, the tier's health poller and push pool, the obs
layer's incident/spool/SLO/fleet/recorder locks — whose thread-safety
rules existed only as comments and chaos tests. These rules make the
discipline mechanical. Like the rest of the engine the analysis is
pure `ast`: no code is imported or executed, and everything resolves
module-locally (no imports are followed) except the cross-class lock
graph, which SH012 assembles over the whole scanned tree.

The model, built once per file:

- a class's *locks* are its `self.X = threading.Lock()/RLock()/
  Condition()` attributes (plus module-level lock globals);
- its *spawn roots* are methods handed to `threading.Thread(target=`
  or an executor's `.submit`/`.map`, and the reachability closure over
  `self.*` calls from those roots is "runs on a spawned thread";
- held-lock sets are propagated through `with self._lock:` regions and
  into same-class `self.method()` calls (bounded by a visited set), so
  a helper that only ever runs under its caller's lock is analyzed
  with that lock held.

`# shellac: guarded-by(<lock>)` is the annotation half: trailing a
line it asserts the named lock is held for that line's accesses;
trailing a `def` line it asserts the whole function runs with the
lock held (the `*_locked` caller-holds-lock convention). It both
documents the contract and feeds the held-set model — which means it
can *surface* findings too (a blocking call inside a guarded-by
function is now visibly under a lock). `# shellac: ignore[CODE]`
works as everywhere else.

Rules:

- SH010 unguarded shared state across threads
- SH011 user-supplied callback invoked while a lock is held
- SH012 lock-order inversion (cross-class acquisition graph)
- SH013 blocking call under a held lock
- SH014 non-daemon thread with no join-on-close path
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from shellac_tpu.analysis.engine import (
    FileContext,
    Finding,
    ProjectRule,
    Rule,
    register,
)
from shellac_tpu.analysis.rules import _callable_names, _chain, _iter_calls

_GUARDED_RE = re.compile(
    r"#\s*shellac:\s*guarded-by\(([A-Za-z0-9_.\s,]+)\)"
)

#: Constructors whose result is a mutex-like guard (Condition wraps a
#: lock and is acquired the same way; Event is NOT a lock).
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_THREAD_CTORS = {"threading.Thread", "Thread"}
_QUEUE_CTORS = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "Queue", "SimpleQueue",
}
#: Dotted calls that block the calling thread (network, disk-scale, or
#: device round trips) — SH013's subject when a lock is held.
_BLOCKING_CHAINS = {
    "time.sleep",
    "urllib.request.urlopen",
    "socket.create_connection",
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "subprocess.call",
    "jax.device_get",
}
#: Zero-argument method calls that block indefinitely.
_BLOCKING_METHODS = {"join", "wait", "result", "acquire"}

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


class GuardedBy:
    """Per-file `# shellac: guarded-by(<lock>)` annotation map.

    Trailing a code line -> the named locks are held for that line.
    Trailing a `def` line -> held throughout that function (the
    `*_locked` caller-holds-the-lock convention).
    """

    def __init__(self, source: str, tree: ast.AST):
        self.by_line: Dict[int, FrozenSet[str]] = {}
        self._spans: List[Tuple[int, int, FrozenSet[str]]] = []
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(source).readline)
            )
        except (tokenize.TokenError, IndentationError):
            return
        raw: Dict[int, Set[str]] = {}
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _GUARDED_RE.search(tok.string)
            if not m:
                continue
            locks = {x.strip() for x in m.group(1).split(",") if x.strip()}
            raw.setdefault(tok.start[0], set()).update(locks)
        if not raw:
            return
        # A guarded-by trailing a `def` line scopes to the whole body.
        def_lines: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, _FuncDef) and node.lineno in raw:
                def_lines.add(node.lineno)
                self._spans.append((
                    node.lineno, node.end_lineno or node.lineno,
                    frozenset(raw[node.lineno]),
                ))
        for line, locks in raw.items():
            if line not in def_lines:
                self.by_line[line] = frozenset(locks)

    def line_locks(self, line: int) -> FrozenSet[str]:
        out = self.by_line.get(line, frozenset())
        for a, b, locks in self._spans:
            if a <= line <= b:
                out = out | locks
        return out

    def fn_locks(self, fn: ast.AST) -> FrozenSet[str]:
        line = getattr(fn, "lineno", -1)
        for a, _b, locks in self._spans:
            if a == line:
                return locks
        return frozenset()


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` -> "X"."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassModel:
    """Module-local concurrency facts for one class."""

    def __init__(self, name: str, node: ast.ClassDef,
                 methods: Dict[str, ast.FunctionDef]):
        self.name = name
        self.node = node
        self.methods = methods
        self.locks: Set[str] = set()
        self.queue_attrs: Set[str] = set()
        self.callback_attrs: Set[str] = set()
        #: attr -> class names it may be constructed from (SH012's
        #: cross-class edges).
        self.attr_classes: Dict[str, Set[str]] = {}
        self.spawn_roots: Set[str] = set()
        #: line spans of nested defs handed to Thread(target=...) —
        #: closures that run on a spawned thread without being methods.
        self.spawn_spans: List[Tuple[int, int]] = []
        self.thread_methods: Set[str] = set()
        self.internal_callees: Set[str] = set()
        #: (lineno, col) of AugAssign targets — read-modify-write sites.
        self.aug_targets: Set[Tuple[int, int]] = set()

    def populate(self, module: "_ModuleModel") -> None:
        nested_defs: Dict[str, ast.AST] = {}
        for mname, fn in self.methods.items():
            for node in ast.walk(fn):
                if isinstance(node, _FuncDef) and node is not fn:
                    nested_defs[node.name] = node
                if isinstance(node, ast.AugAssign):
                    t = node.target
                    self.aug_targets.add((t.lineno, t.col_offset))
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        self._classify_attr(attr, node.value, module,
                                            in_init=(mname == "__init__"),
                                            fn=fn)
                elif (isinstance(node, ast.AnnAssign)
                        and node.value is not None):
                    attr = _self_attr(node.target)
                    if attr is not None:
                        self._classify_attr(attr, node.value, module,
                                            in_init=(mname == "__init__"),
                                            fn=fn)
            for call in _iter_calls(fn):
                self._note_spawn(call, nested_defs)
                for cname in _callable_names(call.func):
                    if cname in self.methods:
                        self.internal_callees.add(cname)
        # Class-body lock attributes (rare, but cheap to honour).
        for node in self.node.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                if _chain(node.value.func) in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.locks.add(t.id)
        self.thread_methods = self._closure(self.spawn_roots)

    def _classify_attr(self, attr: str, value: ast.AST,
                       module: "_ModuleModel", in_init: bool,
                       fn: ast.FunctionDef) -> None:
        for call in ast.walk(value):
            if not isinstance(call, ast.Call):
                continue
            chain = _chain(call.func)
            if chain in _LOCK_CTORS:
                self.locks.add(attr)
            elif chain in _QUEUE_CTORS:
                self.queue_attrs.add(attr)
            elif chain is not None and chain in module.class_names:
                self.attr_classes.setdefault(attr, set()).add(chain)
        if in_init:
            params = {
                a.arg for a in (list(fn.args.posonlyargs)
                                + list(fn.args.args)
                                + list(fn.args.kwonlyargs))
                if a.arg != "self"
            }
            for name in ast.walk(value):
                if isinstance(name, ast.Name) and name.id in params:
                    self.callback_attrs.add(attr)
                    break

    def _note_spawn(self, call: ast.Call,
                    nested_defs: Dict[str, ast.AST]) -> None:
        targets: List[ast.AST] = []
        if _chain(call.func) in _THREAD_CTORS:
            targets += [kw.value for kw in call.keywords
                        if kw.arg == "target"]
        elif (isinstance(call.func, ast.Attribute)
                and call.func.attr in ("submit", "map") and call.args):
            targets.append(call.args[0])
        for t in targets:
            for name in _callable_names(t):
                if name in self.methods:
                    self.spawn_roots.add(name)
                elif name in nested_defs:
                    d = nested_defs[name]
                    self.spawn_spans.append(
                        (d.lineno, d.end_lineno or d.lineno))

    def _closure(self, roots: Set[str]) -> Set[str]:
        out: Set[str] = set()
        stack = [r for r in roots if r in self.methods]
        while stack:
            m = stack.pop()
            if m in out:
                continue
            out.add(m)
            for call in _iter_calls(self.methods[m]):
                for name in _callable_names(call.func):
                    if name in self.methods and name not in out:
                        stack.append(name)
        return out

    def scan_roots(self) -> List[str]:
        """Entry methods for held-set scans: methods no other method of
        the class calls, plus the spawn roots. A helper only reachable
        under its caller's lock is then analyzed with that lock held
        instead of with a spurious empty set."""
        roots = [m for m in self.methods
                 if m not in self.internal_callees]
        roots += [r for r in self.spawn_roots if r not in roots]
        return roots or list(self.methods)

    def in_spawn_span(self, line: int) -> bool:
        return any(a <= line <= b for a, b in self.spawn_spans)


class _Access:
    """One `self.X` read/write site with its held-lock set."""

    __slots__ = ("attr", "write", "aug", "method", "held", "node",
                 "threaded")

    def __init__(self, attr, write, aug, method, held, node, threaded):
        self.attr = attr
        self.write = write
        self.aug = aug
        self.method = method
        self.held = held
        self.node = node
        self.threaded = threaded


class _ScanResult:
    """Everything one interprocedural held-set scan of a class found."""

    def __init__(self) -> None:
        self.accesses: List[_Access] = []
        #: (method, call node, held) for every Call site.
        self.calls: List[Tuple[str, ast.Call, FrozenSet[str]]] = []
        #: (held-before, acquired tokens, node) for every lock `with`.
        self.acquisitions: List[
            Tuple[FrozenSet[str], FrozenSet[str], ast.AST]] = []


class _ModuleModel:
    """Per-file concurrency model, cached on the FileContext."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.guarded = GuardedBy(ctx.source, ctx.tree)
        self.module_locks: Set[str] = set()
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                if _chain(node.value.func) in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks.add(t.id)
        classes = {n.name: n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.ClassDef)}
        self.class_names = set(classes)
        self.classes: Dict[str, _ClassModel] = {}
        for name, node in classes.items():
            cm = _ClassModel(name, node, _merged(classes, node))
            self.classes[name] = cm
        #: lock attr name -> owning classes, for `obj.lock` resolution.
        self.lock_attr_owner: Dict[str, List[str]] = {}
        for cm in self.classes.values():
            cm.populate(self)
        for cm in self.classes.values():
            for lk in cm.locks:
                self.lock_attr_owner.setdefault(lk, []).append(cm.name)
        self._scans: Dict[str, _ScanResult] = {}

    def lock_tokens(self, cm: Optional[_ClassModel],
                    expr: ast.AST) -> List[str]:
        """Lock tokens acquired by `with <expr>:` — a self lock attr
        ("_lock"), a module-level lock global, or another object's
        lock attr resolved by unique owner ("Replica.lock")."""
        attr = _self_attr(expr)
        if attr is not None:
            if cm is not None and attr in cm.locks:
                return [attr]
            return []
        if isinstance(expr, ast.Attribute):
            owners = self.lock_attr_owner.get(expr.attr, [])
            if len(owners) == 1:
                return [f"{owners[0]}.{expr.attr}"]
            return []
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return [expr.id]
        return []

    def scan(self, cm: _ClassModel) -> _ScanResult:
        """Interprocedural held-set walk over one class (cached)."""
        cached = self._scans.get(cm.name)
        if cached is not None:
            return cached
        res = _ScanResult()
        seen: Set[Tuple[str, FrozenSet[str]]] = set()

        def run(mname: str, held: FrozenSet[str]) -> None:
            key = (mname, held)
            if key in seen or len(seen) > 4000:
                return
            seen.add(key)
            fn = cm.methods[mname]
            held = held | self.guarded.fn_locks(fn)
            for st in fn.body:
                visit(mname, st, held)

        def visit(mname: str, node: ast.AST,
                  held: FrozenSet[str]) -> None:
            line = getattr(node, "lineno", None)
            eff = held if line is None else (
                held | self.guarded.line_locks(line))
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None:
                    write = isinstance(node.ctx, (ast.Store, ast.Del))
                    aug = (node.lineno, node.col_offset) in cm.aug_targets
                    res.accesses.append(_Access(
                        attr, write, aug, mname, eff, node,
                        mname in cm.thread_methods
                        or cm.in_spawn_span(node.lineno),
                    ))
            if isinstance(node, (ast.With, ast.AsyncWith)):
                tokens: Set[str] = set()
                for item in node.items:
                    visit(mname, item.context_expr, held)
                    tokens.update(
                        self.lock_tokens(cm, item.context_expr))
                if tokens:
                    res.acquisitions.append(
                        (eff, frozenset(tokens), node))
                inner = held | frozenset(tokens)
                for st in node.body:
                    visit(mname, st, inner)
                return
            if isinstance(node, _FuncDef) or isinstance(node, ast.Lambda):
                # A nested def's body runs when CALLED, not here: scan
                # it with an empty held set rather than the enclosing
                # region's (conservative for SH011/SH013; SH010 still
                # sees its accesses via the spawn-span tagging).
                body = node.body if isinstance(node, _FuncDef) \
                    else [node.body]
                for st in body:
                    visit(mname, st, frozenset())
                return
            if isinstance(node, ast.Call):
                res.calls.append((mname, node, eff))
                callee = _self_attr(node.func)
                if callee in cm.methods:
                    run(callee, eff)
            for child in ast.iter_child_nodes(node):
                visit(mname, child, held)

        for root in cm.scan_roots():
            run(root, frozenset())
        self._scans[cm.name] = res
        return res

    def method_acquires(self, cm: _ClassModel, mname: str,
                        _seen: Optional[Set[str]] = None
                        ) -> FrozenSet[str]:
        """Lock tokens `mname` may acquire, including through same-
        class calls (SH012's cross-class edge targets)."""
        if _seen is None:
            _seen = set()
        if mname in _seen or mname not in cm.methods:
            return frozenset()
        _seen.add(mname)
        out: Set[str] = set()
        for node in ast.walk(cm.methods[mname]):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    out.update(self.lock_tokens(cm, item.context_expr))
            elif isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee is not None:
                    out.update(
                        self.method_acquires(cm, callee, _seen))
        return frozenset(out)


def _merged(classes: Dict[str, ast.ClassDef],
            cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    """Module-local MRO merge, override-wins (the SH002 pattern)."""
    out: Dict[str, ast.FunctionDef] = {}
    for base in cls.bases:
        name = _chain(base)
        if name in classes and classes[name] is not cls:
            out.update(_merged(classes, classes[name]))
    for node in cls.body:
        if isinstance(node, _FuncDef):
            out[node.name] = node
    return out


def _model(ctx: FileContext) -> _ModuleModel:
    m = getattr(ctx, "_concurrency_model", None)
    if m is None:
        m = _ModuleModel(ctx)
        ctx._concurrency_model = m  # type: ignore[attr-defined]
    return m


def _fmt_locks(held: FrozenSet[str]) -> str:
    return "/".join(sorted(held)) if held else "no lock"


# ---------------------------------------------------------------------
# SH010 — unguarded shared state across threads
# ---------------------------------------------------------------------


@register
class UnguardedSharedState(Rule):
    code = "SH010"
    name = "unguarded-shared-state"
    summary = (
        "an attribute written on a spawned-thread path and accessed "
        "elsewhere with no common lock, or a read-modify-write "
        "(`self.x += ...`) with no lock in a lock-owning class — "
        "annotate deliberate lock-free designs with "
        "`# shellac: guarded-by(...)` or ignore[SH010] + rationale"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        model = _model(ctx)
        for cm in model.classes.values():
            if not cm.locks and not cm.spawn_roots:
                continue
            scan = model.scan(cm)
            by_attr: Dict[str, List[_Access]] = {}
            for a in scan.accesses:
                if a.attr in cm.locks or a.method == "__init__":
                    continue
                by_attr.setdefault(a.attr, []).append(a)
            reported: Set[str] = set()
            for attr, accs in sorted(by_attr.items()):
                writes = [a for a in accs if a.write]
                if not writes:
                    continue
                f = self._race(ctx, cm, attr, accs, writes)
                if f is not None and attr not in reported:
                    reported.add(attr)
                    yield f
                    continue
                if cm.locks and attr not in reported:
                    f = self._bare_rmw(ctx, cm, attr, writes)
                    if f is not None:
                        reported.add(attr)
                        yield f

    def _race(self, ctx, cm, attr, accs, writes) -> Optional[Finding]:
        """A write on the spawned-thread side and an access on the
        other side sharing no lock."""
        if not cm.spawn_roots:
            return None
        for w in writes:
            for a in accs:
                if a is w or a.threaded == w.threaded:
                    continue
                if w.held & a.held:
                    continue
                return self.finding(
                    ctx, w.node,
                    f"self.{attr} is written in "
                    f"{w.method!r} ({_fmt_locks(w.held)}) and "
                    f"{'written' if a.write else 'read'} in "
                    f"{a.method!r} ({_fmt_locks(a.held)}) with no "
                    f"common lock, and {cm.name} runs "
                    f"{'/'.join(sorted(cm.spawn_roots))} on a spawned "
                    "thread — guard both sides with one lock or "
                    "annotate the design",
                )
        return None

    def _bare_rmw(self, ctx, cm, attr, writes) -> Optional[Finding]:
        """`self.x += 1` with no lock held in a class that owns locks:
        a read-modify-write is never atomic, and a lock-owning class
        has declared itself cross-thread."""
        for w in writes:
            if w.aug and not w.held:
                return self.finding(
                    ctx, w.node,
                    f"read-modify-write of self.{attr} in "
                    f"{w.method!r} holds none of {cm.name}'s locks "
                    f"({'/'.join(sorted(cm.locks))}) — increments "
                    "are not atomic; move it under the lock or "
                    "annotate with # shellac: guarded-by(...)",
                )
        return None


# ---------------------------------------------------------------------
# SH011 — user-supplied callback invoked while a lock is held
# ---------------------------------------------------------------------


@register
class CallbackUnderLock(Rule):
    code = "SH011"
    name = "callback-under-lock"
    summary = (
        "a constructor-injected callback (or on_* hook) invoked while "
        "a lock is held: a callback that re-enters the holder, or just "
        "stalls, deadlocks every other thread — collect under the "
        "lock, invoke after it drops (the SLOEngine on_transition "
        "pattern)"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        model = _model(ctx)
        seen: Set[Tuple[int, int]] = set()
        for cm in model.classes.values():
            scan = model.scan(cm)
            for _mname, call, held in scan.calls:
                if not held:
                    continue
                attr = _self_attr(call.func)
                if attr is None:
                    continue
                hook = (attr in cm.callback_attrs
                        or ((attr.startswith("on_")
                             or attr.startswith("_on_"))
                            and attr not in cm.methods))
                if not hook or attr in cm.methods:
                    continue
                key = (call.lineno, call.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, call,
                    f"user-supplied callback self.{attr} invoked while "
                    f"holding {_fmt_locks(held)} — a re-entrant or "
                    "slow callback deadlocks the holder; collect "
                    "under the lock and fire after it drops",
                )


# ---------------------------------------------------------------------
# SH012 — lock-order inversion
# ---------------------------------------------------------------------


@register
class LockOrderInversion(ProjectRule):
    code = "SH012"
    name = "lock-order-inversion"
    summary = (
        "two locks are acquired in opposite orders on different paths "
        "(nested `with` blocks and calls into other classes' "
        "lock-taking methods build the acquisition graph; a cycle is "
        "a potential deadlock)"
    )

    def check_project(self, ctxs: Sequence[FileContext]
                      ) -> Iterable[Finding]:
        # node -> {succ: (path, line)}; nodes are "Class.lock" /
        # module-lock names, globally qualified.
        edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
        models = [(_model(ctx), ctx) for ctx in ctxs]
        by_class: Dict[str, Tuple[_ModuleModel, _ClassModel]] = {}
        for model, _ctx in models:
            for cm in model.classes.values():
                by_class.setdefault(cm.name, (model, cm))

        def qual(cm: _ClassModel, token: str) -> str:
            return token if "." in token else f"{cm.name}.{token}"

        def add(a: str, b: str, path: str, line: int) -> None:
            if a != b:
                edges.setdefault(a, {}).setdefault(b, (path, line))

        for model, ctx in models:
            for cm in model.classes.values():
                scan = model.scan(cm)
                for held, acquired, node in scan.acquisitions:
                    for h in held:
                        for t in acquired:
                            add(qual(cm, h), qual(cm, t),
                                ctx.path, node.lineno)
                for _m, call, held in scan.calls:
                    if not held:
                        continue
                    self._cross_edges(cm, call, held, by_class,
                                      qual, add, ctx)
        yield from self._cycles(edges)

    def _cross_edges(self, cm, call, held, by_class, qual, add, ctx):
        """`self.attr.m()` under a lock -> edges into every lock the
        attribute's (module-locally inferred) class may take in m."""
        f = call.func
        if not (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Attribute)):
            return
        attr = _self_attr(f.value)
        if attr is None or attr not in cm.attr_classes:
            return
        for cls_name in sorted(cm.attr_classes[attr]):
            entry = by_class.get(cls_name)
            if entry is None:
                continue
            omodel, ocm = entry
            for t in sorted(omodel.method_acquires(ocm, f.attr)):
                for h in held:
                    add(qual(cm, h), qual(ocm, t),
                        ctx.path, call.lineno)

    def _cycles(self, edges) -> Iterable[Finding]:
        seen_cycles: Set[FrozenSet[str]] = set()
        for start in sorted(edges):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for succ in sorted(edges.get(node, ())):
                    if succ == start:
                        cyc = frozenset(path)
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        fpath, line = edges[node][succ]
                        order = " -> ".join(path + [start])
                        yield Finding(
                            path=fpath, line=line, col=1,
                            rule=self.code,
                            message=(
                                f"lock-order inversion: {order} — "
                                "two threads taking these locks in "
                                "opposite orders deadlock; pick one "
                                "global order or drop the outer lock "
                                "before crossing"
                            ),
                        )
                    elif succ not in path and len(path) < 8:
                        stack.append((succ, path + [succ]))


# ---------------------------------------------------------------------
# SH013 — blocking call under a held lock
# ---------------------------------------------------------------------


@register
class BlockingUnderLock(Rule):
    code = "SH013"
    name = "blocking-under-lock"
    summary = (
        "a blocking call (HTTP/socket/sleep/device_get, untimed "
        "queue.get/join/wait) while holding a lock: every other "
        "thread needing that lock stalls for the full wait — do the "
        "slow work outside the critical section"
    )

    def _blocking(self, cm: _ClassModel, call: ast.Call,
                  held: FrozenSet[str]) -> Optional[str]:
        chain = _chain(call.func)
        if chain in _BLOCKING_CHAINS:
            return chain
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        if (meth in _BLOCKING_METHODS and not call.args
                and not call.keywords):
            # x.join() / x.wait() / x.result() / x.acquire() untimed.
            # Condition.wait while holding ITS OWN lock is the correct
            # protocol — only flag when some OTHER lock is also held.
            recv = _self_attr(call.func.value)
            if meth == "wait" and recv is not None and recv in cm.locks:
                others = held - {recv}
                return f".{meth}() (while also holding " \
                       f"{_fmt_locks(others)})" if others else None
            return f".{meth}()"
        if meth == "get" and not has_timeout and not call.args:
            recv = _self_attr(call.func.value)
            if recv is not None and recv in cm.queue_attrs:
                return f"self.{recv}.get() with no timeout"
        return None

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        model = _model(ctx)
        seen: Set[Tuple[int, int]] = set()
        for cm in model.classes.values():
            if not cm.locks and not model.module_locks:
                continue
            scan = model.scan(cm)
            for _mname, call, held in scan.calls:
                if not held:
                    continue
                what = self._blocking(cm, call, held)
                key = (call.lineno, call.col_offset)
                if what and key not in seen:
                    seen.add(key)
                    yield self.finding(
                        ctx, call,
                        f"blocking call {what} while holding "
                        f"{_fmt_locks(held)} — every thread needing "
                        "the lock stalls for the full wait; move the "
                        "slow work outside the critical section",
                    )


# ---------------------------------------------------------------------
# SH014 — non-daemon thread with no join-on-close path
# ---------------------------------------------------------------------


@register
class ThreadNoJoin(Rule):
    code = "SH014"
    name = "thread-no-join"
    summary = (
        "threading.Thread(...) that is neither daemon=True nor joined "
        "anywhere: the thread outlives close() and hangs interpreter "
        "shutdown (the conftest thread-leak detector's static twin)"
    )

    def _daemon_true(self, call: ast.Call) -> Optional[bool]:
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return None

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.is_test:
            return
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        joined, daemonized = self._join_and_daemon_sites(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and _chain(node.func) in _THREAD_CTORS):
                continue
            d = self._daemon_true(node)
            if d:
                continue
            bound = self._binding(node, parents)
            if bound is not None and (bound in joined
                                      or bound in daemonized):
                continue
            yield self.finding(
                ctx, node,
                ("thread bound to " + bound if bound is not None
                 else "anonymous thread")
                + " is neither daemon=True nor joined on any path — "
                  "it outlives close() and hangs shutdown; pass "
                  "daemon=True or join it in close()/stop()",
            )

    def _binding(self, call: ast.Call,
                 parents: Dict[ast.AST, ast.AST]) -> Optional[str]:
        """"self.X" / "x" the thread is assigned to, else None."""
        node, parent = call, parents.get(call)
        while parent is not None and isinstance(
                parent, (ast.IfExp, ast.BoolOp)):
            node, parent = parent, parents.get(parent)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                attr = _self_attr(t)
                if attr is not None:
                    return f"self.{attr}"
                if isinstance(t, ast.Name):
                    return t.id
        if isinstance(parent, ast.AnnAssign):
            attr = _self_attr(parent.target)
            if attr is not None:
                return f"self.{attr}"
            if isinstance(parent.target, ast.Name):
                return parent.target.id
        return None

    def _join_and_daemon_sites(self, tree: ast.AST
                               ) -> Tuple[Set[str], Set[str]]:
        joined: Set[str] = set()
        daemonized: Set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                recv = node.func.value
                attr = _self_attr(recv)
                if attr is not None:
                    joined.add(f"self.{attr}")
                elif isinstance(recv, ast.Name):
                    joined.add(recv.id)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and t.attr == "daemon"
                            and isinstance(node.value, ast.Constant)
                            and node.value.value):
                        attr = _self_attr(t.value)
                        if attr is not None:
                            daemonized.add(f"self.{attr}")
                        elif isinstance(t.value, ast.Name):
                            daemonized.add(t.value.id)
        return joined, daemonized
