"""The SH rule set: JAX/TPU pitfalls this codebase has actually hit.

Each rule is a small AST check registered with the engine. They are
heuristics tuned for THIS tree — favouring few, high-signal findings
over exhaustive coverage — and every one can be silenced per line or
per file with `# shellac: ignore[CODE]` (see docs/static_analysis.md).

Shared machinery first: dotted-chain extraction and the "traced set" —
functions the linter believes run under `jax.jit` or as a `lax.scan`
body, resolved by decorator, by `jax.jit(f)` call sites, and through
`functools.partial` wrappers, all within a single module (no imports
are followed; the linter never executes the code).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from shellac_tpu.analysis.engine import (
    FileContext,
    Finding,
    ProjectRule,
    Rule,
    register,
)

_JIT_CHAINS = {"jax.jit", "jit", "jax.pjit", "pjit"}
_SCAN_CHAINS = {"jax.lax.scan", "lax.scan"}
_PARTIAL_CHAINS = {"functools.partial", "partial"}
_CONSTRAINT_NAMES = {"with_sharding_constraint", "constrain"}


def _chain(node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute expression ("jax.lax.scan")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _callable_names(node: ast.AST) -> List[str]:
    """Terminal def names a callable expression might resolve to:
    `f` -> [f], `self._step_impl` -> [_step_impl],
    `partial(f, x=1)` -> [f]."""
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    if isinstance(node, ast.Call):
        if _chain(node.func) in _PARTIAL_CHAINS and node.args:
            return _callable_names(node.args[0])
    return []


def _defs_by_name(tree: ast.AST) -> Dict[str, List[ast.FunctionDef]]:
    out: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _jit_decorator_call(dec: ast.AST) -> Optional[ast.Call]:
    """The Call carrying jit kwargs for `@jax.jit(...)` and
    `@partial(jax.jit, ...)` decorators; None for other decorators."""
    if isinstance(dec, ast.Call):
        if _chain(dec.func) in _JIT_CHAINS:
            return dec
        if _chain(dec.func) in _PARTIAL_CHAINS and dec.args:
            if _chain(dec.args[0]) in _JIT_CHAINS:
                return dec
    return None


def _is_jit_decorator(dec: ast.AST) -> bool:
    return _chain(dec) in _JIT_CHAINS or _jit_decorator_call(dec) is not None


def traced_defs(tree: ast.AST) -> Set[ast.FunctionDef]:
    """Functions that (per module-local evidence) run under a tracer:
    jit-decorated, passed to jax.jit(...), or used as a scan body."""
    defs = _defs_by_name(tree)
    traced: Set[ast.FunctionDef] = set()
    for dlist in defs.values():
        for d in dlist:
            if any(_is_jit_decorator(dec) for dec in d.decorator_list):
                traced.add(d)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _chain(node.func) in (_JIT_CHAINS | _SCAN_CHAINS) and node.args:
            for name in _callable_names(node.args[0]):
                traced.update(defs.get(name, []))
    return traced


def _segments(name: str) -> List[str]:
    return [s for s in name.lower().split("_") if s]


_STATEFUL_SEGMENTS = {"train", "step", "decode", "prefill", "update"}


def _iter_calls(node: ast.AST) -> Iterable[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


# ---------------------------------------------------------------------
# SH001 — missing donation on jitted state-threading functions
# ---------------------------------------------------------------------


@register
class MissingDonation(Rule):
    code = "SH001"
    name = "missing-donation"
    summary = (
        "jax.jit of a train/step/decode/prefill/update function without "
        "donate_argnums: the threaded state or KV cache is copied every "
        "call instead of updated in place"
    )

    _DONATE_KW = {"donate_argnums", "donate_argnames"}

    def _has_donate(self, call: ast.Call) -> bool:
        return any(kw.arg in self._DONATE_KW for kw in call.keywords)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _chain(node.func) in _JIT_CHAINS:
                if not node.args or self._has_donate(node):
                    continue
                for name in _callable_names(node.args[0]):
                    if set(_segments(name)) & _STATEFUL_SEGMENTS:
                        yield self.finding(
                            ctx, node,
                            f"jit of {name!r} without donate_argnums/"
                            "donate_argnames — its state/cache buffers "
                            "are copied instead of reused in place",
                        )
                        break
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not (set(_segments(node.name)) & _STATEFUL_SEGMENTS):
                    continue
                for dec in node.decorator_list:
                    call = _jit_decorator_call(dec)
                    if call is not None and self._has_donate(call):
                        continue
                    if _is_jit_decorator(dec):
                        yield self.finding(
                            ctx, dec,
                            f"jit-decorated {node.name!r} without "
                            "donate_argnums/donate_argnames — its state/"
                            "cache buffers are copied instead of reused "
                            "in place",
                        )
                        break


# ---------------------------------------------------------------------
# SH002 — host-device sync in jitted code or per-token decode loops
# ---------------------------------------------------------------------


@register
class HostSync(Rule):
    code = "SH002"
    name = "host-sync"
    summary = (
        "host-device synchronization (.item(), np.asarray, device_get, "
        "block_until_ready) inside a jit-traced function, a per-token "
        "decode loop, or the serving engines' decode-window call-path"
    )

    _SYNC_METHODS = {"item", "block_until_ready"}
    _SYNC_CHAINS = {
        "np.asarray", "numpy.asarray", "np.array", "numpy.array",
        "jax.device_get",
    }
    _LOOP_SEGMENTS = {"decode", "tick"}
    #: Entry points of an Engine class's host-side hot loop: every
    #: method module-locally reachable from these (through self.* /
    #: bare-name calls) is "the decode window call-path". Any sync
    #: there — loop or not — is a per-window or per-admission host
    #: round trip and must be the ONE designed sync or carry a
    #: suppression with its rationale.
    _PATH_ROOTS = {"step", "_decode_tokens"}
    #: Unambiguous sync calls for the call-path scope. np.asarray/
    #: np.array are deliberately excluded here: on the host side of an
    #: engine they overwhelmingly wrap host data (prompt copies, bias
    #: rows), and AST cannot see the operand's device-ness — keep the
    #: call-path check high-signal.
    _PATH_CHAINS = {"jax.device_get"}

    def _sync_call(self, call: ast.Call) -> Optional[str]:
        chain = _chain(call.func)
        if chain in self._SYNC_CHAINS:
            return chain
        return self._method_sync(call)

    def _method_sync(self, call: ast.Call) -> Optional[str]:
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in self._SYNC_METHODS
                and not call.args and not call.keywords):
            return f".{call.func.attr}()"
        return None

    def _path_sync_call(self, call: ast.Call) -> Optional[str]:
        chain = _chain(call.func)
        if chain in self._PATH_CHAINS:
            return chain
        return self._method_sync(call)

    def _engine_path_methods(self, tree: ast.AST, traced):
        """Per Engine-named class: the set of its (module-locally
        resolvable, MRO-merged) methods reachable from the hot-loop
        roots. Base-class methods defined in the same module are
        merged under the subclass pass, override-wins, so a subclass
        hook called from an inherited step() is still on the path."""
        classes = {
            n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
        }

        def merged_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
            out: Dict[str, ast.FunctionDef] = {}
            for base in cls.bases:
                name = _chain(base)
                if name in classes and classes[name] is not cls:
                    out.update(merged_methods(classes[name]))
            for node in cls.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[node.name] = node
            return out

        for cls in classes.values():
            if "Engine" not in cls.name:
                continue
            methods = merged_methods(cls)
            stack = [methods[r] for r in self._PATH_ROOTS if r in methods]
            reach: Set[ast.FunctionDef] = set()
            while stack:
                fn = stack.pop()
                if fn in reach or fn in traced:
                    # Traced defs are the jitted programs — pass (a)
                    # covers those.
                    continue
                reach.add(fn)
                for call in _iter_calls(fn):
                    for name in _callable_names(call.func):
                        callee = methods.get(name)
                        if callee is not None and callee not in reach:
                            stack.append(callee)
            yield cls, reach

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        traced = traced_defs(ctx.tree)
        seen: Set[Tuple[int, int]] = set()
        for fn in traced:
            for call in _iter_calls(fn):
                what = self._sync_call(call)
                key = (call.lineno, call.col_offset)
                if what and key not in seen:
                    seen.add(key)
                    yield self.finding(
                        ctx, call,
                        f"{what} inside jit-traced {fn.name!r} forces a "
                        "host round-trip at trace/run time",
                    )
        # Host-side decode/tick functions: a sync in their LOOP bodies
        # serializes every iteration of the token hot loop.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node in traced:
                continue
            if not (set(_segments(node.name)) & self._LOOP_SEGMENTS):
                continue
            for loop in ast.walk(node):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for call in _iter_calls(loop):
                    what = self._sync_call(call)
                    key = (call.lineno, call.col_offset)
                    if what and key not in seen:
                        seen.add(key)
                        yield self.finding(
                            ctx, call,
                            f"{what} inside a loop of decode-path "
                            f"{node.name!r} syncs the host every "
                            "iteration of the token hot loop",
                        )
        # The serving decode-window call-path: any reachable sync —
        # loop or not — is a per-window/per-admission round trip.
        # History: the per-prefill top-logprobs pull hid here for two
        # rounds because the loop heuristic above could not see it.
        for cls, reach in self._engine_path_methods(ctx.tree, traced):
            for fn in reach:
                for call in _iter_calls(fn):
                    what = self._path_sync_call(call)
                    key = (call.lineno, call.col_offset)
                    if what and key not in seen:
                        seen.add(key)
                        yield self.finding(
                            ctx, call,
                            f"{what} in {fn.name!r}, on "
                            f"{cls.name}'s decode-window call-path — "
                            "every occurrence is a host round trip "
                            "per window/admission; batch it into the "
                            "window's one packed sync or suppress "
                            "with the design rationale",
                        )


# ---------------------------------------------------------------------
# SH003 — Python-side nondeterminism captured under jit/scan
# ---------------------------------------------------------------------


@register
class TraceTimeNondeterminism(Rule):
    code = "SH003"
    name = "trace-nondeterminism"
    summary = (
        "Python RNG or wall-clock call inside a jit/scan-traced "
        "function: the value is baked in at trace time, silently "
        "constant across steps and different across retraces"
    )

    _CHAINS = {
        "time.time", "time.time_ns", "time.perf_counter",
        "time.monotonic", "datetime.now", "datetime.utcnow",
        "datetime.datetime.now", "datetime.datetime.utcnow",
    }
    _PREFIXES = ("np.random.", "numpy.random.")
    # stdlib `random` functions only: `jax.random` is the fix, not the
    # hazard, and `from jax import random` must not trip this rule.
    _PY_RANDOM = {
        "random", "randint", "randrange", "uniform", "choice", "choices",
        "shuffle", "sample", "gauss", "normalvariate", "seed",
        "getrandbits", "betavariate", "expovariate", "triangular",
    }

    def _nondet(self, call: ast.Call) -> Optional[str]:
        chain = _chain(call.func)
        if chain is None:
            return None
        if chain in self._CHAINS:
            return chain
        if chain.startswith(self._PREFIXES):
            return chain
        parts = chain.split(".")
        if (len(parts) == 2 and parts[0] == "random"
                and parts[1] in self._PY_RANDOM):
            return chain
        return None

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in traced_defs(ctx.tree):
            seen: Set[Tuple[int, int]] = set()
            for call in _iter_calls(fn):
                what = self._nondet(call)
                key = (call.lineno, call.col_offset)
                if what and key not in seen:
                    seen.add(key)
                    yield self.finding(
                        ctx, call,
                        f"{what} inside jit/scan-traced {fn.name!r} is "
                        "evaluated once at trace time — use jax.random "
                        "keys / pass values as arguments",
                    )


# ---------------------------------------------------------------------
# SH004 — debug aids left in non-test code
# ---------------------------------------------------------------------


@register
class DebugLeftover(Rule):
    code = "SH004"
    name = "debug-leftover"
    summary = (
        "jax.debug.print/breakpoint, pdb, or breakpoint() left in "
        "non-test code"
    )

    _CHAINS = {
        "jax.debug.print", "jax.debug.breakpoint",
        "pdb.set_trace", "pdb.post_mortem", "pdb.run",
    }

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.is_test:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                chain = _chain(node.func)
                if chain in self._CHAINS or chain == "breakpoint":
                    yield self.finding(
                        ctx, node,
                        f"{chain}() left in non-test code",
                    )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "pdb":
                        yield self.finding(
                            ctx, node, "import pdb left in non-test code"
                        )
            elif isinstance(node, ast.ImportFrom):
                if node.module == "pdb":
                    yield self.finding(
                        ctx, node, "import from pdb left in non-test code"
                    )


# ---------------------------------------------------------------------
# SH005 — set-iteration order dependence
# ---------------------------------------------------------------------


@register
class SetIterationOrder(Rule):
    code = "SH005"
    name = "set-iteration-order"
    summary = (
        "iteration directly over a set: order varies with hash "
        "randomization, so any pytree / argument list built from it "
        "changes structure run to run (guaranteed retraces, shard "
        "drift) — iterate sorted(...) instead"
    )

    def _is_set_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Set):
            return True
        if isinstance(node, ast.Call):
            return _chain(node.func) in ("set", "frozenset")
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
        ):
            return (self._is_set_expr(node.left)
                    or self._is_set_expr(node.right))
        return False

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            iters: List[ast.AST] = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                iters.extend(gen.iter for gen in node.generators)
            for it in iters:
                if self._is_set_expr(it):
                    yield self.finding(
                        ctx, it,
                        "iterating a set directly — order is not "
                        "deterministic across processes; wrap in "
                        "sorted(...)",
                    )


# ---------------------------------------------------------------------
# SH006 — config fields defined but never read (dead flags)
# ---------------------------------------------------------------------


@register
class DeadConfigField(ProjectRule):
    code = "SH006"
    name = "dead-config-field"
    summary = (
        "a dataclass field in config.py is never read anywhere in the "
        "scanned tree (validation does not count): a dead flag that "
        "silently does nothing"
    )

    def _is_dataclass(self, node: ast.ClassDef) -> bool:
        for dec in node.decorator_list:
            chain = _chain(dec.func if isinstance(dec, ast.Call) else dec)
            if chain and chain.split(".")[-1] == "dataclass":
                return True
        return False

    def check_project(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        cfg_ctxs = [c for c in ctxs if Path(c.path).name == "config.py"]
        if not cfg_ctxs:
            return

        fields: List[Tuple[FileContext, str, str, ast.AnnAssign]] = []
        for ctx in cfg_ctxs:
            for node in ctx.tree.body:
                if not (isinstance(node, ast.ClassDef)
                        and self._is_dataclass(node)):
                    continue
                for st in node.body:
                    if (isinstance(st, ast.AnnAssign)
                            and isinstance(st.target, ast.Name)
                            and not st.target.id.startswith("_")):
                        fields.append((ctx, node.name, st.target.id, st))

        reads: Set[str] = set()
        for ctx in ctxs:
            # Reads inside config.py validate() bodies don't make a
            # flag live: a field only validated but never consumed is
            # exactly the dead flag this rule hunts.
            skip: List[Tuple[int, int]] = []
            if ctx in cfg_ctxs:
                for node in ast.walk(ctx.tree):
                    if (isinstance(node, ast.FunctionDef)
                            and node.name == "validate"):
                        skip.append((node.lineno, node.end_lineno or 0))
            for node in ast.walk(ctx.tree):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)):
                    if any(a <= node.lineno <= b for a, b in skip):
                        continue
                    reads.add(node.attr)
                elif isinstance(node, ast.Call):
                    chain = _chain(node.func)
                    if chain in ("getattr", "hasattr") and len(node.args) >= 2:
                        arg = node.args[1]
                        if isinstance(arg, ast.Constant) and isinstance(
                            arg.value, str
                        ):
                            reads.add(arg.value)

        for ctx, cls, field, node in fields:
            if field not in reads:
                yield self.finding(
                    ctx, node,
                    f"config field {cls}.{field} is never read outside "
                    "validation — dead flag (delete it or wire it up)",
                )


# ---------------------------------------------------------------------
# SH007 — sharding-constraint asymmetry between paired paths
# ---------------------------------------------------------------------


@register
class ConstraintAsymmetry(Rule):
    code = "SH007"
    name = "constraint-asymmetry"
    summary = (
        "one half of a paired path (prefill/decode, fwd/bwd, forward/"
        "backward) applies with_sharding_constraint and the other half "
        "applies none: the unconstrained side drifts to whatever layout "
        "XLA picks"
    )

    _PAIRS = [("prefill", "decode"), ("fwd", "bwd"),
              ("forward", "backward")]

    def _constraint_count(self, fns: Sequence[ast.FunctionDef]) -> int:
        n = 0
        for fn in fns:
            for call in _iter_calls(fn):
                name = _chain(call.func)
                if name and name.split(".")[-1] in _CONSTRAINT_NAMES:
                    n += 1
        return n

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        defs = _defs_by_name(ctx.tree)
        reported: Set[frozenset] = set()
        for name, fns in defs.items():
            segs = _segments(name)
            for a, b in self._PAIRS:
                for tag, other_tag in ((a, b), (b, a)):
                    if tag not in segs:
                        continue
                    other = "_".join(
                        other_tag if s == tag else s
                        for s in name.split("_")
                    )
                    if other not in defs or other == name:
                        continue
                    key = frozenset((name, other))
                    if key in reported:
                        continue
                    reported.add(key)
                    mine = self._constraint_count(fns)
                    theirs = self._constraint_count(defs[other])
                    if mine == 0 and theirs > 0:
                        yield self.finding(
                            ctx, fns[0],
                            f"{name!r} applies no sharding constraints "
                            f"but its pair {other!r} applies {theirs} — "
                            "the two paths can shard differently",
                        )
                    elif theirs == 0 and mine > 0:
                        yield self.finding(
                            ctx, defs[other][0],
                            f"{other!r} applies no sharding constraints "
                            f"but its pair {name!r} applies {mine} — "
                            "the two paths can shard differently",
                        )
