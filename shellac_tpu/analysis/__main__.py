import sys

from shellac_tpu.analysis.cli import main

sys.exit(main())
