"""Disaggregated prefill/decode serving (inference/disagg.py +
docs/serving_tier.md §Disaggregated serving).

  - TestWireFormat — the versioned block-transfer format: round trip,
    and loud refusal of truncation, corruption (per-chunk crc32), bad
    magic, and foreign versions.
  - TestEngineRoundTrip — export_slot -> serialize -> deserialize ->
    import_blob onto a FRESH engine continues token-identically to
    the unmigrated run (greedy and per-request-seeded), and the
    refusal matrix (cross-backend, geometry, engine-contract, full
    pool) is loud.
  - TestLiveMigration — THE acceptance criterion: a request served
    prefill-replica -> migrate -> decode-replica over real HTTP is
    byte-identical (non-streamed response bodies; streamed delta
    concatenation + final record) to the same request on a monolithic
    replica, for paged AND paged-int8 backends, with the one trace id
    verifiable in both replicas' /debug/request/<id> timelines
    (kv-export on the prefill side, kv-import on the decode side).
  - TestTierDisagg — the role-aware pair scheduler: a /generate
    through the tier takes the two-leg path (migrations ok), answers
    identically to monolithic serving, and falls back monolithically
    on short prompts (cost), non-migratable features, and a dead
    decode fleet (no_pair) — plus the retry contract: a decode
    replica dying strictly before the first client byte re-runs the
    FULL prefill->migrate path on a fresh pair.
  - TestDisaggChaos — the acceptance chaos scenario: SIGKILL a decode
    replica mid-migration under sustained load; zero failed
    non-streaming requests.

Everything but the wire-format units is marked `slow`: test_disagg.py
is an EARLY-alphabet file, so unmarked engine builds here would eat
the tier-1 wall-clock window; the dedicated `disagg` CI job runs the
module unfiltered (the cache-backends precedent).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference import disagg
from shellac_tpu.inference.cache import PoolExhausted, engine_class
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.inference.tier import TierRouter, make_tier_http_server
from shellac_tpu.models import transformer
from shellac_tpu.obs import Registry
from shellac_tpu.training.tokenizer import ByteTokenizer

PROMPT = [5, 9, 3, 7, 2, 8, 11, 4, 6, 1, 13, 20]
TID = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
TRACE_HDR = {"x-shellac-trace": TID + ";attempt=0"}


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny()
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


def _engine(cfg, params, name, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 96)
    return engine_class(name)(cfg, params, cache_backend=name, **kw)


def _drain(eng):
    out = {}
    while eng.pending:
        out.update(eng.step())
    return out


def _roundtrip(cfg, params, name, kw, wire=True):
    """Monolithic control vs export->import continuation; returns
    (control tokens, migrated tokens, blob)."""
    ctrl = _engine(cfg, params, name)
    ctrl.submit("c", PROMPT, 6, **kw)
    expected = _drain(ctrl)["c"]

    a = _engine(cfg, params, name)
    a.submit("m", PROMPT, 6, prefill_only=True, **kw)
    while not a.frozen_prefills:
        a.step()
    slot = a.frozen_prefills["m"]
    blob = disagg.export_slot(a, slot, a._slots[slot], trace_id=TID)
    assert a.release_frozen("m") is not None
    if wire:
        blob = disagg.MigrationBlob.deserialize(blob.serialize())

    b = _engine(cfg, params, name)
    disagg.import_blob(b, blob, rid="m")
    got = _drain(b)["m"]
    return expected, got, blob


# ---------------------------------------------------------------------
# Wire format (fast: no engines, stays in the tier-1 window)
# ---------------------------------------------------------------------


def _blob():
    return disagg.MigrationBlob(
        {"backend": "paged", "length": 8, "complete": False,
         "request": {"out": [1]}, "trace_id": TID},
        {"k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
         "v": np.ones((5,), np.int8)},
    )


class TestWireFormat:
    def test_round_trip_preserves_header_and_arrays(self):
        blob = _blob()
        for chunk in (7, 64, 1 << 20):
            back = disagg.MigrationBlob.deserialize(
                blob.serialize(chunk_bytes=chunk)
            )
            assert back.header["backend"] == "paged"
            assert back.header["version"] == disagg.VERSION
            assert back.header["trace_id"] == TID
            for name, arr in blob.arrays.items():
                np.testing.assert_array_equal(back.arrays[name], arr)
                assert back.arrays[name].dtype == arr.dtype

    def test_bad_magic_refused(self):
        with pytest.raises(ValueError, match="magic"):
            disagg.MigrationBlob.deserialize(b"NOTKV\x00" + b"x" * 64)

    def test_truncation_refused(self):
        data = _blob().serialize(chunk_bytes=16)
        with pytest.raises(ValueError, match="truncated"):
            disagg.MigrationBlob.deserialize(data[:-3])

    def test_corruption_fails_chunk_crc(self):
        data = bytearray(_blob().serialize(chunk_bytes=16))
        data[-2] ^= 0xFF  # flip a payload byte in the last array
        with pytest.raises(ValueError, match="crc32"):
            disagg.MigrationBlob.deserialize(bytes(data))

    def test_trailing_garbage_refused(self):
        data = _blob().serialize()
        with pytest.raises(ValueError, match="trailing"):
            disagg.MigrationBlob.deserialize(data + b"xx")

    def test_foreign_version_refused(self):
        blob = _blob()
        blob.header["version"] = disagg.VERSION  # serialize overwrites
        data = blob.serialize()
        # Rewrite the header's version field in place.
        hlen = int.from_bytes(data[7:11], "big")
        hdr = json.loads(data[11:11 + hlen])
        hdr["version"] = 99
        hj = json.dumps(hdr).encode()
        forged = data[:7] + len(hj).to_bytes(4, "big") + hj \
            + data[11 + hlen:]
        with pytest.raises(ValueError, match="version"):
            disagg.MigrationBlob.deserialize(forged)

    def test_bad_chunk_bytes_refused(self):
        with pytest.raises(ValueError, match="chunk_bytes"):
            _blob().serialize(chunk_bytes=0)


# ---------------------------------------------------------------------
# Engine-level round trip + refusal matrix
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestEngineRoundTrip:
    @pytest.mark.parametrize("name", ["paged", "paged-int8", "dense"])
    def test_greedy_token_identity(self, tiny_model, name):
        cfg, params = tiny_model
        expected, got, blob = _roundtrip(cfg, params, name,
                                         dict(temperature=0.0))
        assert got == expected
        # The wire header carries the residency manifest + identity.
        assert blob.header["residency"]["backend"] == name
        assert blob.header["trace_id"] == TID

    @pytest.mark.parametrize("name", ["paged", "paged-int8"])
    def test_seeded_sampling_token_identity(self, tiny_model, name):
        cfg, params = tiny_model
        expected, got, _ = _roundtrip(
            cfg, params, name,
            dict(temperature=1.1, top_k=12, top_p=0.9, seed=123),
        )
        assert got == expected

    def test_complete_at_prefill_ships_no_arrays(self, tiny_model):
        cfg, params = tiny_model
        eng = _engine(cfg, params, "paged")
        eng.submit("m", PROMPT, 1, prefill_only=True, temperature=0.0)
        while not eng.frozen_prefills:
            eng.step()
        slot = eng.frozen_prefills["m"]
        blob = disagg.export_slot(eng, slot, eng._slots[slot])
        assert blob.header["complete"] is True
        assert blob.arrays == {}
        assert len(blob.header["request"]["out"]) == 1
        eng.release_frozen("m")

    def test_cross_backend_refused(self, tiny_model):
        cfg, params = tiny_model
        _, _, blob = _roundtrip(cfg, params, "paged",
                                dict(temperature=0.0))
        dense = _engine(cfg, params, "dense")
        with pytest.raises(ValueError, match="cross-backend"):
            disagg.import_blob(dense, blob, rid="x")

    def test_geometry_mismatch_refused(self, tiny_model):
        cfg, params = tiny_model
        _, _, blob = _roundtrip(cfg, params, "paged",
                                dict(temperature=0.0))
        good = dict(blob.header["model"])
        b = _engine(cfg, params, "paged")
        # Layer-count and COMPUTE-DTYPE mismatches both refuse: a
        # bf16->f32 pair would otherwise silently cast the KV.
        for mutation in ({"n_layers": 99}, {"dtype": "bfloat16"}):
            blob.header["model"] = {**good, **mutation}
            with pytest.raises(ValueError, match="geometry"):
                disagg.import_blob(b, blob, rid="x")

    def test_engine_contract_mismatch_refused(self, tiny_model):
        cfg, params = tiny_model
        _, _, blob = _roundtrip(cfg, params, "paged",
                                dict(temperature=0.0))
        b = _engine(cfg, params, "paged", logprobs=True)
        with pytest.raises(ValueError, match="contract"):
            disagg.import_blob(b, blob, rid="x")

    def test_full_engine_raises_pool_exhausted(self, tiny_model):
        cfg, params = tiny_model
        _, _, blob = _roundtrip(cfg, params, "paged",
                                dict(temperature=0.0))
        b = _engine(cfg, params, "paged")
        b.submit("a", [1, 2, 3], 40)
        b.submit("b", [4, 5, 6], 40)
        b.step()  # both admitted into the 2 slots
        with pytest.raises(PoolExhausted):
            disagg.import_blob(b, blob, rid="x")

    def test_speculative_engine_refused_both_sides(self, tiny_model):
        cfg, params = tiny_model
        _, _, blob = _roundtrip(cfg, params, "paged",
                                dict(temperature=0.0))
        spec = engine_class("paged", speculative=True)(
            cfg, params, cfg, params, gamma=3, n_slots=2, max_len=96,
            cache_backend="paged",
        )
        with pytest.raises(ValueError, match="speculative"):
            disagg.import_blob(spec, blob, rid="x")
        with pytest.raises(ValueError, match="speculative"):
            disagg.export_slot(spec, 0, None)

    def test_prefill_only_refuses_constraint(self, tiny_model):
        cfg, params = tiny_model
        from shellac_tpu.inference.constraints import compile_token_dfa

        eng = _engine(cfg, params, "dense", eos_id=7)
        dfa = compile_token_dfa("ab", ByteTokenizer(),
                                cfg.vocab_size, 7)
        with pytest.raises(ValueError, match="prefill_only"):
            eng.submit("m", PROMPT, 4, prefill_only=True,
                       constraint=dfa)


# ---------------------------------------------------------------------
# Live two-replica migration over HTTP (the acceptance criterion)
# ---------------------------------------------------------------------


def _mk_server(cfg, params, role, backend, **kw):
    reg = Registry()
    eng = engine_class(backend)(cfg, params, n_slots=2, max_len=96,
                                cache_backend=backend, registry=reg)
    srv = InferenceServer(cfg, params, tokenizer=ByteTokenizer(),
                          role=role, registry=reg, engine=eng, **kw)
    httpd = make_http_server(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return srv, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post(base, path, payload, headers=None, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _stream(base, payload, headers=None, timeout=120):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return [json.loads(ln) for ln in r.read().splitlines()
                if ln.strip()]


def _get_json(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
class TestLiveMigration:
    @pytest.fixture(scope="class", params=["paged", "paged-int8"])
    def trio(self, request):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        servers = [
            _mk_server(cfg, params, role, request.param)
            for role in ("monolith", "prefill", "decode")
        ]
        yield servers
        for srv, httpd, _ in servers:
            httpd.shutdown()
            srv.close()

    def _migrate(self, trio, payload):
        pre_u = trio[1][2]
        dec_u = trio[2][2]
        st, body = _post(pre_u, "/generate",
                         {**payload, "prefill_only": True,
                          "migrate_to": dec_u}, TRACE_HDR)
        assert st == 200
        mig = json.loads(body)
        assert mig["migrated"] is True
        return mig

    def test_non_streamed_byte_identity(self, trio):
        mono_u = trio[0][2]
        dec_u = trio[2][2]
        payload = {"tokens": PROMPT, "max_new": 6,
                   "temperature": 0.0, "timeout": 120}
        _, mono_body = _post(mono_u, "/generate", payload, TRACE_HDR)
        mig = self._migrate(trio, payload)
        _, dis_body = _post(dec_u, "/generate",
                            {**payload, "adopt": mig["migration_id"]},
                            TRACE_HDR)
        # Byte-identical response bodies (same trace header on both).
        assert dis_body == mono_body
        # The prefill replica's migrate-target map drained (no leaks).
        assert not trio[1][0]._migrate_targets

    def test_streamed_identity_and_timelines(self, trio):
        mono_u = trio[0][2]
        pre_u = trio[1][2]
        dec_u = trio[2][2]
        payload = {"tokens": PROMPT, "max_new": 6,
                   "temperature": 0.0, "timeout": 120}
        mono = _stream(mono_u, {**payload, "stream": True}, TRACE_HDR)
        mig = self._migrate(trio, payload)
        dis = _stream(dec_u, {**payload, "stream": True,
                              "adopt": mig["migration_id"]}, TRACE_HDR)

        def cat(recs):
            return [t for r in recs if not r.get("done")
                    for t in r["tokens"]]

        assert cat(dis) == cat(mono)
        assert dis[-1] == mono[-1]  # identical final record
        # The ONE trace id is verifiable across both replicas'
        # /debug/request/<trace_id> timelines.
        pre_tl = _get_json(pre_u, f"/debug/request/{TID}")
        dec_tl = _get_json(dec_u, f"/debug/request/{TID}")
        pre_events = [e["event"] for e in pre_tl["events"]]
        dec_events = [e["event"] for e in dec_tl["events"]]
        assert "prefill-frozen" in pre_events
        assert "kv-export" in pre_events
        assert "kv-import" in dec_events
        assert "finish" in dec_events

    def test_role_surfaces_and_migration_metrics(self, trio):
        pre_srv, _, pre_u = trio[1]
        dec_srv, _, dec_u = trio[2]
        assert _get_json(pre_u, "/health")["role"] == "prefill"
        assert _get_json(dec_u, "/stats")["role"] == "decode"
        assert pre_srv.engine.stats["kv_exports"] >= 1
        assert dec_srv.engine.stats["kv_imports"] >= 1
        with urllib.request.urlopen(pre_u + "/metrics",
                                    timeout=30) as r:
            pre_m = r.read().decode()
        assert 'shellac_engine_role_info{role="prefill"} 1' in pre_m
        assert 'shellac_migrations_total{outcome="export"}' in pre_m
        assert "shellac_kv_transfer_seconds_bucket" in pre_m
        assert "shellac_kv_transfer_bytes_count" in pre_m
        assert "shellac_engine_kv_bytes_per_token" in pre_m
        with urllib.request.urlopen(dec_u + "/metrics",
                                    timeout=30) as r:
            dec_m = r.read().decode()
        assert 'shellac_migrations_total{outcome="import"}' in dec_m

    def test_unknown_migration_id_is_retryable_503(self, trio):
        dec_u = trio[2][2]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(dec_u, "/generate",
                  {"tokens": PROMPT, "max_new": 2,
                   "adopt": "no-such-migration"})
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After")

    def test_adopt_is_single_use(self, trio):
        dec_u = trio[2][2]
        payload = {"tokens": PROMPT, "max_new": 3,
                   "temperature": 0.0, "timeout": 120}
        mig = self._migrate(trio, payload)
        st, _ = _post(dec_u, "/generate",
                      {**payload, "adopt": mig["migration_id"]})
        assert st == 200
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(dec_u, "/generate",
                  {**payload, "adopt": mig["migration_id"]})
        assert e.value.code == 503

    def test_corrupt_import_is_400(self, trio):
        dec_u = trio[2][2]
        req = urllib.request.Request(
            dec_u + "/kv/import", data=b"garbage-not-a-blob",
            headers={"Content-Type": "application/octet-stream"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 400

    def test_prefill_only_needs_target_and_no_stream(self, trio):
        pre_u = trio[1][2]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(pre_u, "/generate",
                  {"tokens": PROMPT, "max_new": 2,
                   "prefill_only": True})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(pre_u, "/generate",
                  {"tokens": PROMPT, "max_new": 2,
                   "prefill_only": True, "stream": True,
                   "migrate_to": "http://127.0.0.1:1"})
        assert e.value.code == 400


# ---------------------------------------------------------------------
# Tier: role-aware pairing, fallbacks, retry contract
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestTierDisagg:
    @pytest.fixture(scope="class")
    def tier(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        servers = [
            _mk_server(cfg, params, role, "paged")
            for role in ("monolith", "prefill", "decode")
        ]
        reg = Registry()
        router = TierRouter(
            [u for _, _, u in servers], registry=reg,
            disagg_min_prompt=4, health_interval=0.2,
            default_timeout=120.0,
        )
        httpd = make_tier_http_server(router)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            router.poll_once()
            if all(r.routable for r in router.replicas):
                break
            time.sleep(0.1)
        yield router, reg, base, servers
        httpd.shutdown()
        router.close()
        for srv, h, _ in servers:
            h.shutdown()
            srv.close()

    def _mig(self, reg, outcome):
        return reg.value("shellac_migrations_total",
                         outcome=outcome) or 0

    def test_disagg_path_matches_monolithic(self, tier):
        router, reg, base, servers = tier
        payload = {"tokens": PROMPT, "max_new": 6,
                   "temperature": 0.0, "timeout": 120}
        _, mono_body = _post(servers[0][2], "/generate", payload)
        before = self._mig(reg, "ok")
        st, body = _post(base, "/generate", payload)
        assert st == 200
        assert json.loads(body)["tokens"] \
            == json.loads(mono_body)["tokens"]
        assert self._mig(reg, "ok") == before + 1
        # Tier /stats reflects roles + migration counts.
        stats = _get_json(base, "/stats")
        assert stats["migrated"] >= 1
        roles = {r["url"]: r["role"] for r in stats["replicas"]}
        assert set(roles.values()) == {"monolith", "prefill", "decode"}

    def test_streamed_disagg_path(self, tier):
        router, reg, base, servers = tier
        payload = {"tokens": PROMPT, "max_new": 6,
                   "temperature": 0.0, "timeout": 120,
                   "stream": True}
        before = self._mig(reg, "ok")
        recs = _stream(base, payload)
        toks = [t for r in recs if not r.get("done")
                for t in r["tokens"]]
        assert recs[-1]["done"] and recs[-1]["tokens"] == toks
        assert self._mig(reg, "ok") == before + 1

    def test_short_prompt_falls_back_on_cost(self, tier):
        router, reg, base, _ = tier
        before = self._mig(reg, "fallback_cost")
        st, _ = _post(base, "/generate",
                      {"tokens": [3, 1], "max_new": 2,
                       "temperature": 0.0, "timeout": 120})
        assert st == 200
        assert self._mig(reg, "fallback_cost") == before + 1

    def test_feature_falls_back(self, tier):
        router, reg, base, _ = tier
        before = self._mig(reg, "fallback_feature")
        st, _ = _post(base, "/generate",
                      {"tokens": PROMPT, "max_new": 2,
                       "temperature": 0.9, "n": 2, "best_of": 2,
                       "timeout": 120})
        assert st == 200
        assert self._mig(reg, "fallback_feature") == before + 1

    def test_decode_death_pre_byte_reruns_full_path(self, tier):
        """The retry contract: kill the only decode replica; the tier
        re-runs the full prefill->migrate path, finds no pair, and
        serves monolithically — the client sees success."""
        router, reg, base, servers = tier
        dec_srv, dec_httpd, dec_u = servers[2]
        dec_httpd.shutdown()
        dec_srv.close()
        for _ in range(6):
            router.poll_once()
        payload = {"tokens": PROMPT, "max_new": 4,
                   "temperature": 0.0, "timeout": 120}
        st, body = _post(base, "/generate", payload)
        assert st == 200
        assert len(json.loads(body)["tokens"]) == 4


# ---------------------------------------------------------------------
# Chaos acceptance: SIGKILL a decode replica mid-migration under load
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestDisaggChaos:
    def test_decode_sigkill_under_load_zero_failures(self):
        """THE acceptance scenario: a prefill replica + two decode
        replicas behind a disaggregated tier under sustained
        non-streaming load; SIGKILL one decode replica mid-migration.
        Every non-streaming request must succeed — decode deaths
        before the first client byte re-run the full path on the
        surviving pair (or fall back monolithically)."""
        from shellac_tpu.inference.chaos import LoadGenerator, ReplicaProc

        procs = []
        router = None
        httpd = None
        load = None
        try:
            procs = [
                ReplicaProc(extra_args=["--role", role], max_len=96)
                for role in ("prefill", "decode", "decode")
            ]
            for p in procs:
                p.wait_ready()
            reg = Registry()
            router = TierRouter(
                [p.url for p in procs], registry=reg,
                disagg_min_prompt=4, health_interval=0.2,
                default_timeout=60.0,
            )
            httpd = make_tier_http_server(router)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                router.poll_once()
                if all(r.routable for r in router.replicas):
                    break
                time.sleep(0.2)
            rng = np.random.default_rng(0)
            payloads = [
                {"tokens": [int(t) for t in rng.integers(1, 200, 16)],
                 "max_new": 4}
                for _ in range(4)
            ]
            load = LoadGenerator(base, payloads=payloads,
                                 concurrency=4, timeout=60.0)
            load.start()
            # Warm up until migrations are flowing, then SIGKILL one
            # decode replica mid-migration.
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if (reg.value("shellac_migrations_total",
                              outcome="ok") or 0) >= 3:
                    break
                time.sleep(0.25)
            assert (reg.value("shellac_migrations_total",
                              outcome="ok") or 0) >= 3, \
                "disaggregated path never engaged under load"
            procs[1].kill()
            time.sleep(8.0)
            counts = load.stop()
            errors = list(load.errors)
            load = None
            assert counts, "load generator issued no requests"
            bad = {k: v for k, v in counts.items() if k != "ok"}
            assert not bad, (counts, errors)
            # The kill produced retries/fallbacks, not client failures.
            assert counts["ok"] == sum(counts.values())
        finally:
            if load is not None:
                load.stop()
            if httpd is not None:
                httpd.shutdown()
            if router is not None:
                router.close()
            for p in procs:
                p.terminate()
