"""LoRA tests: identity at init, adapter-only training, sharded step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.training.lora import (
    LoRAConfig,
    init_lora,
    init_lora_state,
    lora_logical_axes,
    make_lora_train_step,
    merge_lora,
)


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


def _batch(cfg, b=2, s=16, seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (b, s), 0, cfg.vocab_size)
    return {"inputs": toks, "targets": toks}


class TestLoRAMerge:
    def test_identity_at_init(self):
        """B=0 at init, so the merged model equals the base model exactly."""
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        lora = init_lora(cfg, LoRAConfig(rank=4), jax.random.PRNGKey(1))
        merged = merge_lora(params, lora, LoRAConfig(rank=4))
        tokens = _batch(cfg)["inputs"]
        l1 = transformer.forward(cfg, params, tokens)
        l2 = transformer.forward(cfg, merged, tokens)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)

    def test_merge_changes_targets_only(self):
        cfg = _tiny()
        lcfg = LoRAConfig(rank=2, targets=("wq", "wo"))
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        lora = init_lora(cfg, lcfg, jax.random.PRNGKey(1))
        # Push B away from zero so the delta is nonzero.
        lora = jax.tree.map(lambda x: x + 0.1, lora)
        merged = merge_lora(params, lora, lcfg)
        assert not np.allclose(
            np.asarray(merged["layers"]["wq"]), np.asarray(params["layers"]["wq"])
        )
        np.testing.assert_array_equal(
            np.asarray(merged["layers"]["wk"]), np.asarray(params["layers"]["wk"])
        )

    def test_axes_match_adapters(self):
        cfg = _tiny()
        lcfg = LoRAConfig(rank=4, targets=("wq", "wk", "wv", "wo", "w_down"))
        lora = init_lora(cfg, lcfg, jax.random.PRNGKey(0))
        axes = lora_logical_axes(cfg, lcfg)
        flat_p = jax.tree_util.tree_flatten_with_path(lora)[0]
        flat_a = jax.tree_util.tree_flatten_with_path(
            axes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
        paths_p = {tuple(str(k) for k in p): leaf.ndim for p, leaf in flat_p}
        paths_a = {tuple(str(k) for k in p): len(leaf) for p, leaf in flat_a}
        assert paths_p == paths_a

    def test_validation(self):
        cfg = _tiny()
        with pytest.raises(ValueError, match="unknown LoRA targets"):
            LoRAConfig(targets=("nope",)).validate(cfg)
        with pytest.raises(ValueError, match="rank"):
            LoRAConfig(rank=0).validate(cfg)
        # MoE expert weights and interleaved stacks are valid targets.
        LoRAConfig(targets=("w_gate",)).validate(get_model_config("tiny-moe"))
        LoRAConfig(targets=("w_gate",)).validate(
            get_model_config("tiny-moe-interleaved")
        )


class TestLoRAMoE:
    """Expert-weight adapters: per-expert A/B pairs, grouped stacks."""

    def _moe(self, name="tiny-moe"):
        return get_model_config(name).replace(dtype="float32")

    MLP_ALL = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

    def test_expert_adapter_shapes(self):
        cfg = self._moe()
        lcfg = LoRAConfig(rank=4, targets=("w_gate", "w_down"))
        lora = init_lora(cfg, lcfg, jax.random.PRNGKey(0))
        e = cfg.moe.num_experts
        d, f = cfg.d_model, cfg.ff_dim
        L = cfg.n_layers
        assert lora["layers"]["w_gate"]["a"].shape == (L, e, d, 4)
        assert lora["layers"]["w_gate"]["b"].shape == (L, e, 4, f)
        assert lora["layers"]["w_down"]["a"].shape == (L, e, f, 4)
        assert lora["layers"]["w_down"]["b"].shape == (L, e, 4, d)

    def test_identity_at_init_moe(self):
        cfg = self._moe()
        lcfg = LoRAConfig(rank=4, targets=self.MLP_ALL)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        lora = init_lora(cfg, lcfg, jax.random.PRNGKey(1))
        merged = merge_lora(params, lora, lcfg)
        tokens = _batch(cfg)["inputs"]
        l1 = transformer.forward(cfg, params, tokens)
        l2 = transformer.forward(cfg, merged, tokens)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)

    def test_identity_at_init_interleaved(self):
        cfg = self._moe("tiny-moe-interleaved")
        lcfg = LoRAConfig(rank=2, targets=self.MLP_ALL)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        lora = init_lora(cfg, lcfg, jax.random.PRNGKey(1))
        assert set(lora["layers"]) == {"dense", "moe"}
        merged = merge_lora(params, lora, lcfg)
        tokens = _batch(cfg)["inputs"]
        l1 = transformer.forward(cfg, params, tokens)
        l2 = transformer.forward(cfg, merged, tokens)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)

    def test_axes_match_adapters_moe(self):
        for name in ("tiny-moe", "tiny-moe-interleaved"):
            cfg = self._moe(name)
            lcfg = LoRAConfig(rank=4, targets=self.MLP_ALL)
            lora = init_lora(cfg, lcfg, jax.random.PRNGKey(0))
            axes = lora_logical_axes(cfg, lcfg)
            flat_p = jax.tree_util.tree_flatten_with_path(lora)[0]
            flat_a = jax.tree_util.tree_flatten_with_path(
                axes, is_leaf=lambda x: isinstance(x, tuple)
            )[0]
            paths_p = {tuple(str(k) for k in p): leaf.ndim
                       for p, leaf in flat_p}
            paths_a = {tuple(str(k) for k in p): len(leaf)
                       for p, leaf in flat_a}
            assert paths_p == paths_a, name

    def test_loss_decreases_expert_targets(self):
        cfg = self._moe()
        tcfg = TrainConfig(warmup_steps=1, total_steps=50, learning_rate=1e-2)
        lcfg = LoRAConfig(rank=4, targets=("w_gate", "w_up", "w_down"))
        base = transformer.init_params(cfg, jax.random.PRNGKey(0))
        state = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(1))
        step = make_lora_train_step(cfg, tcfg, lcfg)
        batch = _batch(cfg)
        state, m0 = step(state, base, batch)
        first = float(m0["loss"])
        for _ in range(10):
            state, m = step(state, base, batch)
        assert float(m["loss"]) < first
        b = state.lora["layers"]["w_gate"]["b"]
        assert float(jnp.abs(b).max()) > 0

    def test_sharded_step_expert_targets(self):
        from shellac_tpu import ParallelConfig, make_mesh

        cfg = self._moe()
        # fsdp=4 divides num_experts=4 (the MoE mesh convention; a
        # straight fsdp=8 mesh cannot shard a 4-expert stack).
        mesh = make_mesh(ParallelConfig(fsdp=4, tp=2))
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        lcfg = LoRAConfig(rank=4, targets=("wq", "w_gate", "w_down"))
        base = transformer.init_params(cfg, jax.random.PRNGKey(0))
        state = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(1),
                                mesh=mesh)
        step = make_lora_train_step(cfg, tcfg, lcfg, mesh=mesh)
        state, metrics = step(state, base, _batch(cfg, b=8))
        assert np.isfinite(float(metrics["loss"]))

    def test_train_step_interleaved(self):
        cfg = self._moe("tiny-moe-interleaved")
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        lcfg = LoRAConfig(rank=2, targets=self.MLP_ALL)
        base = transformer.init_params(cfg, jax.random.PRNGKey(0))
        state = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(1))
        step = make_lora_train_step(cfg, tcfg, lcfg)
        state, metrics = step(state, base, _batch(cfg))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.step) == 1


class TestLoRATraining:
    def test_loss_decreases_base_frozen(self):
        cfg = _tiny()
        tcfg = TrainConfig(warmup_steps=1, total_steps=50, learning_rate=1e-2)
        lcfg = LoRAConfig(rank=4)
        base = transformer.init_params(cfg, jax.random.PRNGKey(0))
        state = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(1))
        step = make_lora_train_step(cfg, tcfg, lcfg)
        batch = _batch(cfg)
        state, m0 = step(state, base, batch)
        first = float(m0["loss"])
        for _ in range(10):
            state, m = step(state, base, batch)
        assert float(m["loss"]) < first
        assert int(state.step) == 11
        # Adapter B must have moved away from zero.
        b = state.lora["layers"]["wq"]["b"]
        assert float(jnp.abs(b).max()) > 0

    def test_sharded_step(self, mesh_fsdp8):
        cfg = _tiny()
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        lcfg = LoRAConfig(rank=4)
        base = transformer.init_params(cfg, jax.random.PRNGKey(0))
        state = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(1),
                                mesh=mesh_fsdp8)
        step = make_lora_train_step(cfg, tcfg, lcfg, mesh=mesh_fsdp8)
        batch = _batch(cfg, b=8)
        state, metrics = step(state, base, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_sharded_matches_unsharded(self, mesh_fsdp8):
        cfg = _tiny()
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        lcfg = LoRAConfig(rank=4)
        base = transformer.init_params(cfg, jax.random.PRNGKey(0))
        batch = _batch(cfg, b=8)

        s1 = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(1))
        st1 = make_lora_train_step(cfg, tcfg, lcfg)
        s1, m1 = st1(s1, base, batch)

        s2 = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(1),
                             mesh=mesh_fsdp8)
        st2 = make_lora_train_step(cfg, tcfg, lcfg, mesh=mesh_fsdp8)
        s2, m2 = st2(s2, base, batch)
        np.testing.assert_allclose(
            float(m1["loss"]), float(m2["loss"]), rtol=1e-5
        )
