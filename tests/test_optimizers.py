"""Optimizer variants: each must train, and shard without new code."""

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.training import init_train_state, make_train_step
from shellac_tpu.training.optimizer import make_optimizer


def _batch(cfg, b=4, s=32):
    toks = np.tile(np.arange(s, dtype=np.int32) % 97, (b, 1))
    return {"inputs": toks, "targets": np.roll(toks, -1, axis=1)}


@pytest.mark.parametrize("opt", ["adamw", "lion", "adafactor", "muon"])
def test_loss_decreases(opt):
    cfg = get_model_config("tiny").replace(dtype="float32")
    lr = 1e-3 if opt == "lion" else 3e-3  # lion wants ~3-10x lower lr
    tcfg = TrainConfig(optimizer=opt, learning_rate=lr, warmup_steps=1,
                       total_steps=100)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tcfg)
    batch = _batch(cfg)
    state, m0 = step(state, batch)
    for _ in range(20):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"])


@pytest.mark.parametrize("opt", ["lion", "adafactor", "muon"])
def test_sharded_step(opt, mesh_fsdp8):
    cfg = get_model_config("tiny").replace(dtype="float32")
    tcfg = TrainConfig(optimizer=opt, warmup_steps=1, total_steps=10)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_fsdp8)
    step = make_train_step(cfg, tcfg, mesh=mesh_fsdp8)
    state, metrics = step(state, _batch(cfg, b=8))
    assert np.isfinite(float(metrics["loss"]))


def test_unknown_optimizer():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(TrainConfig(optimizer="sgd"))


def test_muon_labels_and_dims():
    """Stacked matrices get muon with trailing-dims numbers;
    embeddings/head/norms stay on adamw; MLA's wkv_b expansions are
    muon'd as their REAL (kv_rank -> heads*dh) matrix."""
    from optax.contrib import MuonDimensionNumbers

    from shellac_tpu.models import transformer
    from shellac_tpu.training.optimizer import _muon_dims, _muon_mask

    cfg = get_model_config("tiny-mla").replace(
        dtype="float32", tie_embeddings=False
    )
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    labels = _muon_mask(params)
    assert labels["embed"] == "adamw"
    assert labels["lm_head"] == "adamw"
    assert labels["layers"]["attn_norm"] == "adamw"
    assert labels["layers"]["wkv_a"] == "muon"
    assert labels["layers"]["wkv_b_k"] == "muon"
    dims = _muon_dims(params)
    assert dims["layers"]["wkv_b_k"] == MuonDimensionNumbers(
        reduction_axis=1, output_axis=(2, 3)
    )
    assert dims["layers"]["wkv_a"] == MuonDimensionNumbers(
        reduction_axis=1, output_axis=2
    )


def test_muon_updates_are_orthogonalized():
    """End-to-end: a muon train step's matrix updates have equalized
    singular values (the quintic NS band), unlike raw adamw updates."""
    from shellac_tpu.training.optimizer import make_optimizer

    cfg = get_model_config("tiny").replace(dtype="float32")
    tcfg = TrainConfig(optimizer="muon", learning_rate=1.0,
                       warmup_steps=0, total_steps=10, weight_decay=0.0,
                       grad_clip_norm=1e9)
    from shellac_tpu.models import transformer
    from shellac_tpu.training.losses import cross_entropy

    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    opt = make_optimizer(tcfg)
    state = opt.init(params)
    batch = _batch(cfg)
    import jax.numpy as jnp

    def loss(p):
        logits = transformer.forward(
            cfg, p, jnp.asarray(batch["inputs"])
        )
        return cross_entropy(logits, jnp.asarray(batch["targets"]))[0]

    grads = jax.grad(loss)(params)
    updates, _ = opt.update(grads, state, params)
    u = np.asarray(updates["layers"]["w_gate"])  # (L, d, f)
    sv = np.linalg.svd(u[0], compute_uv=False)
    # NS equalizes the singular values WITHIN the gradient's row space
    # (null directions of a low-rank grad stay exactly null); assert the
    # non-null spectrum is flat, unlike a raw gradient's.
    live = sv[sv > 0.05 * sv.max()]
    assert len(live) >= 8
    assert live.max() / live.min() < 5, (live.min(), live.max())
    gsv = np.linalg.svd(np.asarray(grads["layers"]["w_gate"])[0],
                        compute_uv=False)
    assert gsv.max() / np.median(gsv) > 10  # raw grad was anisotropic


def test_muon_checkpoint_roundtrip(tmp_path):
    from shellac_tpu.training.checkpoint import Checkpointer

    cfg = get_model_config("tiny").replace(dtype="float32")
    tcfg = TrainConfig(optimizer="muon", warmup_steps=1, total_steps=5)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tcfg)
    state, _ = step(state, _batch(cfg))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(1, state, force=True, wait=True)
    abstract = jax.eval_shape(lambda s: s, state)
    restored = ckpt.restore(abstract_state=abstract)
    state2, m = step(restored, _batch(cfg))
    assert np.isfinite(float(m["loss"]))
