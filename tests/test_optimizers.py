"""Optimizer variants: each must train, and shard without new code."""

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.training import init_train_state, make_train_step
from shellac_tpu.training.optimizer import make_optimizer


def _batch(cfg, b=4, s=32):
    toks = np.tile(np.arange(s, dtype=np.int32) % 97, (b, 1))
    return {"inputs": toks, "targets": np.roll(toks, -1, axis=1)}


@pytest.mark.parametrize("opt", ["adamw", "lion", "adafactor"])
def test_loss_decreases(opt):
    cfg = get_model_config("tiny").replace(dtype="float32")
    lr = 1e-3 if opt == "lion" else 3e-3  # lion wants ~3-10x lower lr
    tcfg = TrainConfig(optimizer=opt, learning_rate=lr, warmup_steps=1,
                       total_steps=100)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tcfg)
    batch = _batch(cfg)
    state, m0 = step(state, batch)
    for _ in range(20):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"])


@pytest.mark.parametrize("opt", ["lion", "adafactor"])
def test_sharded_step(opt, mesh_fsdp8):
    cfg = get_model_config("tiny").replace(dtype="float32")
    tcfg = TrainConfig(optimizer=opt, warmup_steps=1, total_steps=10)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_fsdp8)
    step = make_train_step(cfg, tcfg, mesh=mesh_fsdp8)
    state, metrics = step(state, _batch(cfg, b=8))
    assert np.isfinite(float(metrics["loss"]))


def test_unknown_optimizer():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(TrainConfig(optimizer="sgd"))
