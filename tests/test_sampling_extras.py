"""min-p sampling, repetition penalty, and remat policies."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer
from shellac_tpu.ops.sampling import (
    min_p_mask,
    repetition_penalty,
    sample,
    sample_batched,
)


class TestSampleBatched:
    """Per-row-parameter sampler: token-exact vs the scalar path when
    all rows share one setting (same key, same masked logits)."""

    V = 64

    def _logits(self, b=4, seed=0):
        return jax.random.normal(
            jax.random.PRNGKey(seed), (b, self.V)
        ) * 3.0

    def _vecs(self, b, temperature, top_k, top_p, min_p):
        return (
            jnp.full((b,), temperature, jnp.float32),
            jnp.full((b,), top_k if top_k is not None else self.V, jnp.int32),
            jnp.full((b,), top_p if top_p is not None else 1.0, jnp.float32),
            jnp.full((b,), min_p if min_p is not None else 0.0, jnp.float32),
        )

    @pytest.mark.parametrize("kw", [
        dict(temperature=0.0),
        dict(temperature=1.0),
        dict(temperature=0.7, top_k=8),
        dict(temperature=1.3, top_p=0.8),
        dict(temperature=1.0, min_p=0.1),
        dict(temperature=0.9, top_k=16, top_p=0.9, min_p=0.05),
    ])
    def test_matches_scalar(self, kw):
        logits = self._logits()
        key = jax.random.PRNGKey(42)
        want = sample(key, logits, **kw)
        got = sample_batched(
            key, logits,
            *self._vecs(logits.shape[0], kw.get("temperature", 1.0),
                        kw.get("top_k"), kw.get("top_p"), kw.get("min_p")),
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_mixed_rows(self):
        """Greedy and sampled rows coexist: the greedy row equals
        argmax; a top-k=1 row equals argmax too; others stay in-mask."""
        logits = self._logits(b=3, seed=1)
        temp = jnp.asarray([0.0, 1.0, 1.0], jnp.float32)
        topk = jnp.asarray([self.V, 1, 4], jnp.int32)
        topp = jnp.ones((3,), jnp.float32)
        minp = jnp.zeros((3,), jnp.float32)
        toks = np.asarray(sample_batched(
            jax.random.PRNGKey(0), logits, temp, topk, topp, minp
        ))
        am = np.asarray(jnp.argmax(logits, axis=-1))
        assert toks[0] == am[0]
        assert toks[1] == am[1]
        top4 = np.asarray(jnp.argsort(logits[2])[-4:])
        assert toks[2] in top4


class TestMinP:
    def test_mask_keeps_relative_threshold(self):
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        masked = min_p_mask(logits, 0.5)  # cutoff = 0.25
        kept = np.asarray(masked > -1e29)
        assert kept.tolist() == [[True, True, False, False]]

    def test_sample_respects_min_p(self):
        logits = jnp.log(jnp.asarray([0.6, 0.3, 0.1]))
        keys = jax.random.split(jax.random.PRNGKey(0), 200)
        toks = jax.vmap(
            lambda k: sample(k, logits, temperature=1.0, min_p=0.4)
        )(keys)
        assert set(np.asarray(toks).tolist()) == {0, 1}  # 0.1 < 0.4*0.6


class TestRepetitionPenalty:
    def test_hf_convention(self):
        logits = jnp.asarray([2.0, -2.0, 1.0])
        seen = jnp.asarray([True, True, False])
        out = np.asarray(repetition_penalty(logits, seen, 2.0))
        np.testing.assert_allclose(out, [1.0, -4.0, 1.0])

    def test_engine_suppresses_loops(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jnp.ones((1, 4), jnp.int32)
        plain = Engine(cfg, params, temperature=0.0).generate(
            prompt, max_new_tokens=12
        )
        heavy = Engine(
            cfg, params, temperature=0.0, repetition_penalty=1e6
        ).generate(prompt, max_new_tokens=12)
        plain_t = np.asarray(plain.tokens)[0]
        heavy_t = np.asarray(heavy.tokens)[0]
        # Untuned tiny models loop; an extreme penalty must kill repeats
        # entirely (every emitted token distinct, and != the prompt id).
        assert len(set(heavy_t.tolist())) == 12
        assert 1 not in heavy_t
        # Sanity: the plain engine did loop, so the test discriminates.
        assert len(set(plain_t.tolist())) < 12


class TestRematPolicy:
    @pytest.mark.parametrize("policy", ["dots", "dots_no_batch"])
    def test_same_outputs_and_grads(self, policy):
        cfg = get_model_config("tiny").replace(dtype="float32", remat=True)
        cfg2 = cfg.replace(remat_policy=policy)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.ones((2, 16), jnp.int32)

        def loss(c):
            return lambda p: jnp.sum(
                transformer.forward(c, p, tokens) ** 2
            ) * 1e-6

        l1, g1 = jax.value_and_grad(loss(cfg))(params)
        l2, g2 = jax.value_and_grad(loss(cfg2))(params)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )

    def test_unknown_policy(self):
        cfg = get_model_config("tiny").replace(
            dtype="float32", remat=True, remat_policy="everything"
        )
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="unknown remat_policy"):
            transformer.forward(cfg, params, jnp.ones((1, 8), jnp.int32))


class TestPenalties:
    def _engine(self, **kw):
        from shellac_tpu.inference.batching import BatchingEngine

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        return cfg, params, BatchingEngine(
            cfg, params, n_slots=2, max_len=64, temperature=0.0, **kw
        )

    def test_presence_penalty_forbids_repeats(self):
        """A huge presence penalty makes greedy decode emit all-distinct
        tokens (the unpenalized tiny model repeats quickly)."""
        cfg, params, eng = self._engine()
        prompt = [5, 9, 2]
        eng.submit("plain", prompt, 16)
        done = {}
        while len(done) < 1:
            done.update(eng.step())
        assert len(set(done["plain"])) < len(done["plain"])  # repeats

        eng.submit("pen", prompt, 16, presence_penalty=1e9)
        done = {}
        while len(done) < 1:
            done.update(eng.step())
        out = done["pen"]
        assert len(set(out)) == len(out)  # all distinct

    def test_penalties_match_reference_loop(self):
        """Greedy decode with presence+frequency penalties is BIT-exact
        against a hand-rolled loop applying the same formula to the raw
        single-request logits."""
        from shellac_tpu.inference.kvcache import init_cache

        cfg, params, eng = self._engine()
        prompt = [7, 3, 11, 2]
        pp, fp = 0.8, 0.4
        eng.submit("r", prompt, 10, presence_penalty=pp,
                   frequency_penalty=fp)
        done = {}
        while len(done) < 1:
            done.update(eng.step())
        got = done["r"]

        # Reference: manual prefill + per-token decode with counts.
        cache = init_cache(cfg, batch=1, max_len=64)
        toks = jnp.asarray([prompt], jnp.int32)
        logits, cache = transformer.forward_with_cache(
            cfg, params, toks, cache, fresh_cache=True,
            new_tokens_len=jnp.asarray([len(prompt)], jnp.int32),
        )
        counts = np.zeros(cfg.vocab_size, np.float32)
        # First token samples from the UNPENALIZED prefill logits
        # (nothing generated yet), then joins the counts.
        cur = int(jnp.argmax(logits[0, len(prompt) - 1]))
        expect = [cur]
        counts[cur] += 1
        for _ in range(9):
            logits, cache = transformer.forward_with_cache(
                cfg, params, jnp.asarray([[cur]], jnp.int32), cache,
            )
            adj = np.asarray(logits[0, 0], np.float32)
            adj = adj - pp * (counts > 0) - fp * counts
            cur = int(np.argmax(adj))
            expect.append(cur)
            counts[cur] += 1
        assert got == expect

    def test_penalty_counts_cleared_on_slot_reuse(self):
        """A penalized request must not leak its counts into the next
        request on the same slot."""
        cfg, params, eng = self._engine()
        prompt = [5, 9, 2]
        eng.submit("a", prompt, 8)
        base = {}
        while len(base) < 1:
            base.update(eng.step())

        eng.submit("b", prompt, 8, presence_penalty=1e9)
        done = {}
        while len(done) < 1:
            done.update(eng.step())
        # Same slot, plain request again: output must match the first
        # unpenalized run exactly.
        eng.submit("c", prompt, 8)
        done = {}
        while len(done) < 1:
            done.update(eng.step())
        assert done["c"] == base["a"]

    def test_server_and_openai_penalties(self):
        import json as _json
        import threading
        import urllib.request

        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )
        from shellac_tpu.training.tokenizer import ByteTokenizer

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv = InferenceServer(
            cfg, params, tokenizer=ByteTokenizer(), n_slots=2,
            max_len=64, temperature=0.0,
        )
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            def post(path, payload):
                req = urllib.request.Request(
                    f"{base}{path}", data=_json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=120) as r:
                    return _json.loads(r.read())

            plain = post("/generate", {"tokens": [5, 9, 2], "max_new": 12})
            pen = post("/generate", {
                "tokens": [5, 9, 2], "max_new": 12,
                "presence_penalty": 1e9,
            })
            assert len(set(pen["tokens"])) == len(pen["tokens"])
            assert pen["tokens"] != plain["tokens"]
            # OpenAI route: a nonzero penalty is now accepted.
            oai = post("/v1/completions", {
                "prompt": [5, 9, 2], "max_tokens": 12,
                "temperature": 0, "presence_penalty": 2.0,
            })
            assert oai["choices"][0]["text"]
        finally:
            httpd.shutdown()
            srv.close()


class TestPerRequestSeed:
    def _engine(self):
        from shellac_tpu.inference.batching import BatchingEngine

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        return BatchingEngine(
            cfg, params, n_slots=2, max_len=64, temperature=0.9, seed=123,
        )

    def _drain(self, eng, reqs):
        for rid, prompt, kw in reqs:
            eng.submit(rid, prompt, 12, **kw)
        done = {}
        while len(done) < len(reqs):
            done.update(eng.step())
        return done

    def test_seeded_requests_are_deterministic(self):
        """The same seed reproduces the same tokens across runs,
        engines, slot placements, and co-tenants; different seeds
        differ."""
        prompt = [5, 9, 2]
        a = self._drain(self._engine(), [("x", prompt, {"seed": 7})])
        # Different engine instance, different co-tenant load, the
        # seeded request lands on a different slot.
        b = self._drain(self._engine(), [
            ("pad", [1, 2, 3, 4], {}),  # occupies slot 0 first
            ("x", prompt, {"seed": 7}),
        ])
        assert a["x"] == b["x"]
        c = self._drain(self._engine(), [("x", prompt, {"seed": 8})])
        assert c["x"] != a["x"]

    def test_unseeded_stream_unchanged_by_seeded_neighbor(self):
        """A neighbor's SEEDEDNESS must not perturb the shared stream
        (its presence legitimately advances the engine key — compare
        against the same load unseeded, not against running alone)."""
        prompt = [4, 8, 15]
        with_unseeded = self._drain(self._engine(), [
            ("u", prompt, {}),
            ("n", [16, 23, 42], {}),
        ])
        with_seeded = self._drain(self._engine(), [
            ("u", prompt, {}),
            ("n", [16, 23, 42], {"seed": 99}),
        ])
        assert with_unseeded["u"] == with_seeded["u"]

    def test_openai_seed(self):
        import json as _json
        import threading
        import urllib.request

        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )
        from shellac_tpu.training.tokenizer import ByteTokenizer

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv = InferenceServer(
            cfg, params, tokenizer=ByteTokenizer(), n_slots=2,
            max_len=64, temperature=0.8,
        )
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            def post(payload):
                req = urllib.request.Request(
                    f"{base}/v1/completions",
                    data=_json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=120) as r:
                    return _json.loads(r.read())

            p = {"prompt": "ab", "max_tokens": 8, "temperature": 0.9,
                 "seed": 42}
            assert post(p)["choices"][0]["text"] == \
                post(p)["choices"][0]["text"]
        finally:
            httpd.shutdown()
            srv.close()

    def test_negative_seed_rejected(self):
        eng = self._engine()
        import pytest as _pytest

        with _pytest.raises(ValueError, match="seed"):
            eng.submit("r", [1, 2], 4, seed=-3)

    def test_large_seed_folds_instead_of_killing_the_scheduler(self):
        """OpenAI clients send 63-bit seeds; int32 overflow must not
        reach the device vectors (a scheduler-thread OverflowError
        permanently fails the server)."""
        eng = self._engine()
        out = self._drain(
            eng, [("big", [5, 9, 2], {"seed": 2**33 + 7})]
        )
        # Deterministic under the folded value too.
        out2 = self._drain(
            self._engine(), [("big", [5, 9, 2], {"seed": 2**33 + 7})]
        )
        assert out["big"] == out2["big"]
