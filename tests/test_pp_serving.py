"""Pipeline-parallel (pp-mesh) serving.

Engines run on pp meshes through the same GSPMD idiom as training: the
`layers` rule shards the stacked params AND the stacked KV cache over
pp (per-stage residency — each stage holds its own layers' weights and
cache rows), and the decode scan's per-layer slices resolve through
the partitioner. The serving contract is the usual one: greedy output
BIT-IDENTICAL to the unsharded engine. See docs/inference.md
("Pipeline-parallel serving") for why tp remains the latency answer
and pp is the capacity play.
"""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.models import transformer


def _cfg():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(ParallelConfig(pp=2, tp=2, dp=2))
    return cfg, params, shard_params(cfg, params, mesh), mesh


def _reqs(cfg, n=4):
    rng = np.random.default_rng(3)
    return [(i, rng.integers(1, cfg.vocab_size, size=s).tolist(), 8)
            for i, s in enumerate((3, 7, 5, 9))][:n]


class TestPpServing:
    def test_dense_engine_token_exact(self, setup):
        cfg, params, sharded, mesh = setup
        reqs = _reqs(cfg)
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0).run(reqs)
        got = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, mesh=mesh).run(reqs)
        assert got == want

    def test_paged_engine_token_exact(self, setup):
        cfg, params, sharded, mesh = setup
        reqs = _reqs(cfg)
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0).run(reqs)
        got = PagedBatchingEngine(
            cfg, sharded, n_slots=2, max_len=64, block_size=32,
            temperature=0.0, mesh=mesh,
        ).run(reqs)
        assert got == want

    def test_per_stage_cache_residency(self, setup):
        """The KV cache's layer axis must shard over pp — each stage
        holds its OWN layers' cache rows, not a replicated copy (the
        memory-capacity point of pp serving)."""
        cfg, params, sharded, mesh = setup
        eng = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, mesh=mesh)
        spec = eng._cache.k.sharding.spec
        assert spec[0] == "pp", spec
        # Params too: stacked layer weights shard over pp.
        wq_spec = sharded["layers"]["wq"].sharding.spec
        assert wq_spec[0] == "pp", wq_spec

    @pytest.mark.parametrize("pp_pipeline", [False, True])
    def test_http_server_on_pp_mesh(self, setup, pp_pipeline):
        cfg, params, sharded, mesh = setup
        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )

        eng = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, mesh=mesh,
                             pp_pipeline=pp_pipeline)
        srv = InferenceServer(cfg, sharded, engine=eng)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        req = urllib.request.Request(
            base + "/generate",
            json.dumps({"tokens": [3, 5, 7], "max_new": 6}).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            got = json.loads(r.read())["tokens"]
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0).run([(0, [3, 5, 7], 6)])[0]
        assert got == want
        httpd.shutdown()
        srv.close()

    def test_int8_cache_on_pp_mesh(self, setup):
        cfg, params, sharded, mesh = setup
        reqs = _reqs(cfg, n=2)
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, kv_quant="int8").run(reqs)
        got = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, kv_quant="int8",
                             mesh=mesh).run(reqs)
        assert got == want
