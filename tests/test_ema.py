"""EMA parameter averaging (TrainConfig.ema_decay)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.training import init_train_state, make_train_step


def _run(decay, steps=5):
    cfg = get_model_config("tiny")
    tcfg = TrainConfig(
        learning_rate=3e-3, warmup_steps=1, total_steps=50, ema_decay=decay
    )
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tcfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
    )
    batch = {"inputs": tokens, "targets": tokens}
    for _ in range(steps):
        state, _ = step(state, batch)
    return state


def _dist(a, b):
    return float(
        sum(jnp.sum((x.astype(jnp.float32) - y.astype(jnp.float32)) ** 2)
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))) ** 0.5
    )


class TestEMA:
    def test_disabled_by_default(self):
        state = _run(None)
        assert state.ema_params is None

    def test_ema_lags_params(self):
        """High decay tracks slowly; low decay hugs the live params."""
        slow = _run(0.99)
        fast = _run(0.5)
        d_slow = _dist(slow.ema_params, slow.params)
        d_fast = _dist(fast.ema_params, fast.params)
        assert d_slow > d_fast > 0

    def test_ema_structure_matches_params(self):
        state = _run(0.9)
        jax.tree.map(
            lambda e, p: None if e.shape == p.shape else pytest.fail("shape"),
            state.ema_params, state.params,
        )

    def test_checkpoint_roundtrip(self, tmp_path):
        from shellac_tpu.training.checkpoint import Checkpointer

        state = _run(0.9)
        ckpt = Checkpointer(str(tmp_path / "ck"))
        ckpt.save(5, state, force=True, wait=True)
        abstract = jax.eval_shape(lambda s: s, state)
        restored = ckpt.restore(abstract_state=abstract)
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(restored.ema_params)[0]),
            np.asarray(jax.tree.leaves(state.ema_params)[0]),
        )

    def test_ema_on_mesh(self, mesh_fsdp8):
        """EMA leaves inherit param shardings via path-suffix matching."""
        from shellac_tpu.training import batch_shardings

        cfg = get_model_config("tiny")
        tcfg = TrainConfig(warmup_steps=1, total_steps=5, ema_decay=0.9)
        state = init_train_state(
            cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_fsdp8
        )
        step = make_train_step(cfg, tcfg, mesh=mesh_fsdp8)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        bs = batch_shardings(mesh_fsdp8)
        batch = {
            "inputs": jax.device_put(tokens, bs),
            "targets": jax.device_put(tokens, bs),
        }
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))
        # EMA embed must be sharded like the live embed.
        assert (
            state.ema_params["embed"].sharding == state.params["embed"].sharding
        )
