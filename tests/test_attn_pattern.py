"""Per-layer attention patterns (Gemma-2/3 style) and score softcap.

The pattern machinery reshapes the flat (L, ...) layer stack into
(L/period, period, ...) groups inside forward — these tests pin the
invariants: a uniform pattern equals the flat path bit-for-bit, the
cached decode matches the training forward, and the softcap kernels
(flash fwd/bwd, dense + paged decode) match the reference math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu.config import ModelConfig
from shellac_tpu.models.transformer import (
    forward,
    forward_with_cache,
    init_params,
)
from shellac_tpu.ops.attention import attention_ref
from shellac_tpu.ops.flash_attention import flash_attention


def _cfg(**kw):
    base = dict(
        vocab_size=128, d_model=64, n_layers=4, n_heads=4, n_kv_heads=2,
        max_seq_len=64, dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base).validate()


def test_pattern_validation():
    with pytest.raises(ValueError, match="entries"):
        _cfg(attn_pattern=("window", "banana"), attn_window=8)
    with pytest.raises(ValueError, match="attn_window"):
        _cfg(attn_pattern=("window", "full"))
    with pytest.raises(ValueError, match="whole"):
        _cfg(attn_pattern=("window", "full", "full"), attn_window=8)


def test_uniform_pattern_equals_flat():
    """("window",)*k patterns must reproduce the flat windowed scan
    exactly — same params, same math, only the scan grouping differs."""
    cfg_flat = _cfg(attn_window=8)
    cfg_pat = cfg_flat.replace(attn_pattern=("window", "window"))
    params = init_params(cfg_flat, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    a = forward(cfg_pat, params, toks, attn_impl="ref")
    b = forward(cfg_flat, params, toks, attn_impl="ref")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_alternating_pattern_differs_from_uniform():
    """Sanity: the "full" layers really drop the window."""
    cfg_pat = _cfg(attn_window=4, attn_pattern=("window", "full"))
    params = init_params(cfg_pat, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 128)
    mixed = forward(cfg_pat, params, toks, attn_impl="ref")
    allwin = forward(
        cfg_pat.replace(attn_pattern=None), params, toks, attn_impl="ref"
    )
    assert float(jnp.abs(mixed - allwin).max()) > 1e-4


def test_patterned_decode_matches_forward():
    """Prefill + per-token decode through the grouped cache scan must
    reproduce the training forward's logits position by position."""
    from shellac_tpu.inference.kvcache import init_cache

    cfg = _cfg(
        attn_window=8, attn_pattern=("window", "full"), attn_softcap=30.0,
        attn_scale=0.2, post_norms=True,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, 128)
    full = forward(cfg, params, toks, attn_impl="ref")

    cache = init_cache(cfg, batch=2, max_len=64)
    got, cache = forward_with_cache(
        cfg, params, toks[:, :12], cache, fresh_cache=True, attn_impl="ref"
    )
    np.testing.assert_allclose(
        np.asarray(got[:, :12]), np.asarray(full[:, :12]), atol=1e-5
    )
    for t in range(12, 24):
        got, cache = forward_with_cache(
            cfg, params, toks[:, t:t + 1], cache, attn_impl="ref"
        )
        np.testing.assert_allclose(
            np.asarray(got[:, 0]), np.asarray(full[:, t]), atol=1e-5
        )


@pytest.mark.parametrize("window", [None, 64])
def test_flash_softcap_parity(window):
    key = jax.random.PRNGKey(0)
    b, s, h, hkv, d = 2, 256, 4, 2, 64
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d))
    kw = dict(causal=True, window=window, scale=0.11, softcap=30.0)
    ref = attention_ref(q, k, v, **kw)
    got = flash_attention(
        q, k, v, **kw, interpret=True, block_q=64, block_k=64
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_flash_softcap_grads():
    """The backward kernels chain the tanh derivative; grads must match
    autodiff through the reference to fp32 tolerance."""
    b, s, h, hkv, d = 1, 128, 2, 1, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d))
    kw = dict(causal=True, scale=0.13, softcap=25.0)

    def f_ref(q, k, v):
        return (attention_ref(q, k, v, **kw) ** 2).sum()

    def f_fl(q, k, v):
        return (flash_attention(
            q, k, v, **kw, interpret=True, block_q=64, block_k=64
        ) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(f_fl, argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a), atol=1e-4)


def test_decode_softcap_parity():
    from shellac_tpu.ops.decode_attention import _decode_ref, decode_attention

    b, s, h, hkv, d, max_len = 4, 1, 8, 4, 128, 512
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    ck = jax.random.normal(jax.random.PRNGKey(1), (b, hkv, max_len, d))
    cv = jax.random.normal(jax.random.PRNGKey(2), (b, hkv, max_len, d))
    idx = jnp.array([37, 100, 250, 511], jnp.int32)
    for cap, win in [(30.0, None), (25.0, 128)]:
        got = decode_attention(
            q, ck, cv, idx, window=win, softcap=cap, impl="flash",
            interpret=True,
        )
        ref = _decode_ref(q, ck, cv, idx, win, d ** -0.5, softcap=cap)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_paged_decode_softcap_parity():
    from shellac_tpu.inference.kvcache import paged_gather_layer
    from shellac_tpu.ops.decode_attention import (
        _decode_ref,
        paged_decode_attention,
    )

    b, s, h, hkv, d = 4, 1, 8, 4, 128
    bs_pg, nb, npool = 16, 64, 300
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    pk = jax.random.normal(jax.random.PRNGKey(1), (npool, hkv, bs_pg, d))
    pv = jax.random.normal(jax.random.PRNGKey(2), (npool, hkv, bs_pg, d))
    tab = jax.random.permutation(
        jax.random.PRNGKey(3), npool
    )[: b * nb].reshape(b, nb).astype(jnp.int32)
    idx = jnp.array([17, 300, 600, 1023], jnp.int32)
    got = paged_decode_attention(
        q, pk, pv, tab, idx, softcap=40.0, impl="flash", interpret=True
    )
    ka, va = paged_gather_layer(pk, pv, tab)
    ref = _decode_ref(q, ka, va, idx, None, d ** -0.5, softcap=40.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_patterned_training_on_mesh():
    """Patterned stacks train under fsdp/tp sharding: the grouped scan's
    reshaped leaves must keep valid shardings end to end."""
    from shellac_tpu.parallel.mesh import make_mesh
    from shellac_tpu.config import ParallelConfig, TrainConfig
    from shellac_tpu.training.trainer import init_train_state, make_train_step

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = _cfg(
        attn_window=8, attn_pattern=("window", "full"), attn_softcap=30.0,
        post_norms=True, dtype="float32",
    )
    mesh = make_mesh(
        ParallelConfig(fsdp=2, tp=2), devices=jax.devices()[:4]
    )
    tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=2, total_steps=10)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh)
    step = make_train_step(cfg, tcfg, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
    batch = {"inputs": tokens, "targets": tokens}
    state2, m1 = step(state, batch)
    _, m2 = step(state2, batch)
    assert np.isfinite(m1["loss"]) and m2["loss"] < m1["loss"] * 1.5


def test_dual_rope_sp_training_parity():
    """Gemma-3-style dual rope under sequence parallelism: the sp mesh
    forward (ring on full layers, ulysses on window layers) must match
    the unsharded reference forward."""
    from shellac_tpu.models.registry import get_model_config
    from shellac_tpu.parallel.mesh import make_mesh
    from shellac_tpu.config import ParallelConfig

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = get_model_config("tiny-gemma3").replace(
        dtype="float32", remat=False
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref = forward(cfg, params, toks, attn_impl="ref")
    mesh = make_mesh(
        ParallelConfig(sp=2, tp=2), devices=jax.devices()[:4]
    )
    with mesh:
        got = jax.jit(
            lambda p, t: forward(cfg, p, t, mesh=mesh, attn_impl="auto")
        )(params, toks)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-3
    )


def test_dual_rope_pp_training_parity():
    """Dual rope + pattern under pipeline parallelism: pp=2 stages each
    hold whole periods and the local/global tables ride the microbatch
    extras; logits must match the unsharded forward."""
    from shellac_tpu.models.registry import get_model_config
    from shellac_tpu.parallel.mesh import make_mesh
    from shellac_tpu.config import ParallelConfig

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = get_model_config("tiny-gemma3").replace(
        dtype="float32", remat=False,
        # 6 layers / pp=2 -> 3 per stage: not a whole period of 6. Use a
        # period-3 variant so stages hold whole periods.
        attn_pattern=("window", "window", "full"),
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 256)
    # Ragged positions force the extras path (tables ride microbatches).
    pos = jnp.broadcast_to(jnp.arange(32, dtype=jnp.int32), (4, 32)) + 1
    ref = forward(cfg, params, toks, positions=pos, attn_impl="ref")
    mesh = make_mesh(
        ParallelConfig(pp=2, tp=2), devices=jax.devices()[:4]
    )
    with mesh:
        got = jax.jit(
            lambda p, t: forward(
                cfg, p, t, positions=pos, mesh=mesh, attn_impl="ref",
                pipeline_microbatches=2,
            )
        )(params, toks)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-3
    )


def test_flash_sinks_parity_and_grads():
    """GPT-OSS attention sinks in the flash kernel: fwd parity and all
    four gradients (q, k, v, AND the sink logits) vs the reference."""
    b, s, h, hkv, d = 2, 128, 4, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d))
    sinks = jax.random.normal(jax.random.PRNGKey(3), (h,)) * 2.0
    for win in (None, 32):
        kw = dict(causal=True, window=win, scale=0.13)
        ref = attention_ref(q, k, v, sinks=sinks, **kw)
        got = flash_attention(q, k, v, sinks=sinks, **kw, interpret=True,
                              block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

        def f_ref(q, k, v, s):
            return (attention_ref(q, k, v, sinks=s, **kw) ** 2).sum()

        def f_fl(q, k, v, s):
            return (flash_attention(
                q, k, v, sinks=s, **kw, interpret=True, block_q=64,
                block_k=64,
            ) ** 2).sum()

        g_ref = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, sinks)
        g_fl = jax.grad(f_fl, argnums=(0, 1, 2, 3))(q, k, v, sinks)
        for a, bb in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                       atol=1e-4)


def test_decode_sinks_parity():
    from shellac_tpu.inference.kvcache import paged_gather_layer
    from shellac_tpu.ops.decode_attention import (
        _decode_ref,
        decode_attention,
        paged_decode_attention,
    )

    b, s, h, hkv, d, max_len = 4, 1, 8, 4, 128, 512
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d))
    ck = jax.random.normal(jax.random.PRNGKey(1), (b, hkv, max_len, d))
    cv = jax.random.normal(jax.random.PRNGKey(2), (b, hkv, max_len, d))
    sinks = jax.random.normal(jax.random.PRNGKey(3), (h,)) * 2.0
    idx = jnp.array([37, 100, 250, 511], jnp.int32)
    for win in (None, 128):
        got = decode_attention(q, ck, cv, idx, window=win, sinks=sinks,
                               impl="flash", interpret=True)
        ref = _decode_ref(q, ck, cv, idx, win, d ** -0.5, sinks=sinks)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5)

    bs_pg, nb, npool = 16, 64, 300
    pk = jax.random.normal(jax.random.PRNGKey(4), (npool, hkv, bs_pg, d))
    pv = jax.random.normal(jax.random.PRNGKey(5), (npool, hkv, bs_pg, d))
    tab = jax.random.permutation(
        jax.random.PRNGKey(6), npool
    )[: b * nb].reshape(b, nb).astype(jnp.int32)
    idx2 = jnp.array([17, 300, 600, 1023], jnp.int32)
    got = paged_decode_attention(q, pk, pv, tab, idx2, sinks=sinks,
                                 impl="flash", interpret=True)
    ka, va = paged_gather_layer(pk, pv, tab)
    ref = _decode_ref(q, ka, va, idx2, None, d ** -0.5, sinks=sinks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_sinks_sp_training_parity():
    """Sinks under sequence parallelism: ring (full layers) rebases its
    online softmax with the per-head sink, ulysses slices the sink
    vector per rank after its head all-to-all."""
    from shellac_tpu.config import ParallelConfig
    from shellac_tpu.models.registry import get_model_config
    from shellac_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = get_model_config("tiny-gptoss").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    # Give the zero-init sinks real values so the test has teeth.
    params["layers"]["sinks"] = jax.random.normal(
        jax.random.PRNGKey(7), params["layers"]["sinks"].shape
    ) * 2.0
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref = forward(cfg, params, toks, attn_impl="ref")
    mesh = make_mesh(ParallelConfig(sp=2, tp=2), devices=jax.devices()[:4])
    with mesh:
        got = jax.jit(
            lambda p, t: forward(cfg, p, t, mesh=mesh, attn_impl="auto")
        )(params, toks)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-3
    )
